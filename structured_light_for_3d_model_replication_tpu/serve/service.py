"""Service composition + stdlib HTTP front end.

:class:`ReconstructionService` wires queue → batcher → program cache →
device workers into one lifecycle (start / serve / drain) and owns the
job registry clients poll. :class:`ServeHTTPServer` is the transport: a
``ThreadingHTTPServer`` (same dependency posture as `hw/command_server.py`
— no web framework) exposing

========================  ==================================================
``POST /submit``           ``.npy`` capture stack body (+ ``X-*`` option
                           headers) → ``{"job_id": ...}``; 429 + Retry-After
                           on backpressure, 503 while draining, 400 on a
                           malformed stack
``GET /status?id=``        job lifecycle + taxonomy error payload
``GET /result?id=``        the PLY/STL bytes (409 until done)
``GET /healthz``           liveness + drain flag + worker/queue state
``GET /metrics``           Prometheus text: queue depth, batch-occupancy
                           histogram, program-cache stats, per-stage span
                           latencies (utils/trace), compile/device-memory
                           telemetry (utils/telemetry)
``GET /events?n=``         flight-recorder journal tail as JSONL
                           (utils/events; docs/OBSERVABILITY.md)
========================  ==================================================

The HTTP layer holds no state of its own — every handler delegates to the
service object, so in-process callers (tests, bench) and HTTP clients see
identical semantics.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import threading
import time
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..config import DecodeConfig, ProjectorConfig, TriangulationConfig
from ..health import QualityGates
from ..stream import StreamParams
from ..utils import events, telemetry, trace
from ..utils.log import get_logger
from .batcher import BucketBatcher, BucketKey
from .cache import ContentCache, ProgramCache, content_key
from .fleet import PeerCacheClient
from .governor import GovernorParams, OverloadGovernor
from .jobs import (
    DONE,
    FAILED,
    AdmissionQueue,
    Job,
    JobRejected,
    StackFormatError,
    error_payload,
)
from .lanes import LANE_DEAD, DeviceLanePool
from .sessions import SessionManager, UnknownSessionError
from .store import JournalStore, SessionStreamStore
from .tenants import TenantQuotas
from .worker import DeviceWorker

log = get_logger(__name__)

_PRIORITY_NAMES = {"high": 0, "normal": 1, "low": 2}
_CONTENT_TYPES = {"ply": "application/x-ply",
                  "stl": "model/stl",
                  "mesh_ply": "application/x-ply",  # vertex-colored mesh
                  "render_png": "image/png",  # splat novel-view render
                  "json": "application/json"}  # session-stop payloads
#: What a ONE-SHOT submit may ask for — the worker's postprocess menu.
#: ``json`` is the session-stop payload shape and ``render_png`` needs a
#: session's fitted splat scene; neither is a worker artifact.
_SUBMIT_FORMATS = ("ply", "stl", "mesh_ply")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Service tuning surface (docs/SERVING.md has the tuning guide)."""

    proj: ProjectorConfig = ProjectorConfig()
    decode_cfg: DecodeConfig = DecodeConfig()
    tri_cfg: TriangulationConfig = TriangulationConfig()
    gates: QualityGates = QualityGates()

    queue_depth: int = 64          # bounded admission (backpressure above)
    linger_ms: float = 10.0        # max wait for batch company
    workers: int = 1               # device launch lanes
    # Device dimension (serve/lanes.py; docs/SERVING.md § multi-chip):
    # worker lanes spread round-robin over up to this many local
    # devices (None = all of jax.local_devices()). workers=N with N
    # chips visible is the one-lane-per-chip topology; the default
    # workers=1 keeps the historical single-device service.
    devices: int | None = None
    # Sharded big-bucket tier: a bucket whose padded H*W meets this
    # threshold dispatches ONE cross-chip program (camera rows sharded
    # over parallel/mesh.py's space axis, spanning shard_devices chips;
    # 0 = all visible) instead of serializing on a single lane — and
    # its heavy Poisson postprocess solves over the same device mesh.
    # None disables the tier.
    shard_min_pixels: int | None = None
    shard_devices: int = 0
    buckets: tuple = ((1080, 1920),)   # padded (H, W) shapes
    batch_sizes: tuple = (1, 2, 4, 8)
    max_cache_entries: int = 32
    warmup: bool = True            # precompile buckets × batch sizes
    # Warm the SESSION-lane jit programs too (stream/warmup.py): per-stop
    # registration, windowed refine, model fuse and the preview chain
    # compile at replica start instead of inside the first session — the
    # failover window a survivor pays when it adopts a dead replica's
    # session (ROADMAP; asserted by the fleet chaos gate). Only applies
    # when ``warmup`` is on.
    warmup_sessions: bool = True
    mesh_depth: int = 7            # STL results: Poisson depth
    # Scene representation for one-shot STL/mesh_ply results
    # (docs/MESHING.md): "poisson" watertight print path, "tsdf" the
    # fused colored-surface path (fusion/).
    mesh_representation: str = "poisson"
    completed_cap: int = 256       # terminal jobs kept for /status///result
    # Byte budget for retained result payloads (a 1080p PLY is ~30 MB —
    # 256 of those would pin ~8 GB; the count cap alone doesn't bound
    # memory). Oldest terminal jobs are evicted past EITHER cap.
    result_cache_bytes: int = 512 << 20
    # Compile/memory telemetry (docs/OBSERVABILITY.md): sl_compile_total,
    # sl_compile_seconds, device-memory gauges and the recompile-storm
    # detector on this service's /metrics.
    telemetry: bool = True
    # Streaming sessions (docs/STREAMING.md): per-session incremental
    # fusion defaults and the bounded live-session cap. Per-session
    # overrides are limited to the non-compiling surface
    # (`sessions.SESSION_OPTION_KEYS`).
    stream: StreamParams = StreamParams()
    max_sessions: int = 8
    # Idle expiry for sessions (live AND finalized): a crashed client's
    # abandoned session frees its slot + model buffers after this.
    session_ttl_s: float = 3600.0
    # -- durability (serve/store.py; docs/SERVING.md § durability) --------
    # Journal volume: crash-safe WAL of job admissions/terminals and
    # per-session accepted stops, plus the persistent half of the
    # content cache. None = in-memory service (the historical behavior);
    # set it and restart with start(recover_from=...) / `--recover` to
    # survive kill -9.
    store_dir: str | None = None
    # Content-hash result cache: duplicate submits (same stack bytes +
    # same processing config) return the finished artifact at admission
    # without touching the queue — and, with a store_dir, across
    # restarts and past result-registry eviction.
    content_cache: bool = True
    content_cache_bytes: int = 256 << 20
    # Overload governor (serve/governor.py): circuit breaker on the
    # worker-exception rate, graduated load shedding (previews first,
    # then low-priority admissions), worker watchdog.
    governor: GovernorParams = GovernorParams()
    # -- fleet tier (serve/fleet.py, serve/router.py; SERVING.md § fleet)
    # Replica identity: stamped into journaled session heads and the
    # /healthz//readyz payloads; None = a fresh random id per process.
    replica_id: str | None = None
    # Peer base URLs for the shared content cache: a local miss at
    # admission consults peers' ``GET /cache/<key>`` (bounded timeouts,
    # per-peer breakers, single-flight, negative TTL) before computing.
    peers: tuple = ()
    peer_timeout_s: float = 2.0     # per-peer request bound
    peer_budget_s: float = 3.0      # whole peer-lookup bound
    peer_negative_ttl_s: float = 5.0
    # Shared session-handoff volume: the WAL streams session ops there
    # (SessionStreamStore sink) so a survivor replica can adopt a dead
    # replica's live sessions. Requires store_dir (the stream rides the
    # WAL's group commit). May be a local directory (the historical
    # shared-POSIX layout) or an object-store spec
    # (``http://host:port[/prefix]`` — serve/blobstore.py; replicas
    # then share no filesystem at all).
    handoff_dir: str | None = None
    # -- per-tenant admission quotas (serve/tenants.py) -------------------
    # Sustained admissions/s per tenant (the X-Tenant header; 0 = quotas
    # off) and the token bucket's burst headroom. Enforced at admission
    # BEFORE the queue and governor — one hot client can't starve the
    # fleet — with retryable 429s (taxonomy TenantQuotaError +
    # Retry-After) and per-tenant serve_tenant_* counters. Content-cache
    # hits are exempt (they cost the fleet nothing).
    tenant_rate_per_s: float = 0.0
    tenant_burst: int = 8
    # Cost-weighted tenant spend (`tenants.stack_cost`): a token spend
    # proportional to the stack's MEGAPIXELS instead of 1-per-submit —
    # a 4K stack and a 240p stack stop costing the same. Rejections
    # refund the exact cost spent (the refund-parity contract). The
    # headers-time probe checks at the COST FLOOR (the body — and with
    # it the true cost — hasn't been read yet, and probing higher
    # would 429 cheap stacks a weighted admit accepts); the
    # authoritative weighted spend happens at admission.
    tenant_cost_weighted: bool = False
    # -- device-loss tolerance (serve/lanes.py; SERVING.md failure
    # matrix). A device declared dead (lane-health escalation or the
    # watchdog's per-device budget) is probed with a tiny synthetic
    # program at this cadence, doubling per miss up to the cap; a probe
    # that answers re-warms the lane and returns it to the pool.
    device_probe_interval_s: float = 5.0
    device_probe_backoff_max_s: float = 60.0


def synthetic_calib_provider(proj: ProjectorConfig):
    """Per-bucket synthetic rig calibration (the no-hardware default —
    the same `models/synthetic.default_calibration` geometry the bench
    and tests use). Memoized per (H, W): Calibration arrays live on
    device and are shared by every batch of that bucket."""
    from ..models import synthetic
    from ..ops.triangulate import make_calibration

    lock = threading.Lock()
    cache: dict = {}

    def provider(height: int, width: int):
        with lock:
            calib = cache.get((height, width))
        if calib is not None:
            return calib
        cam_K, proj_K, R, T = synthetic.default_calibration(
            height, width, proj)
        calib = make_calibration(cam_K, proj_K, R, T, height, width,
                                 proj_width=proj.width,
                                 proj_height=proj.height)
        with lock:
            cache[(height, width)] = calib
        return calib

    return provider


def fixed_calib_provider(calib):
    """Single-rig provider from a loaded calibration (``--calib`` .mat):
    only the bucket matching its camera geometry is servable."""
    h, w = int(calib.Nc.shape[0]), int(calib.Nc.shape[1])

    def provider(height: int, width: int):
        if (height, width) != (h, w):
            raise StackFormatError(
                f"service calibration is {h}x{w}; bucket "
                f"{height}x{width} has no calibration")
        return calib

    return provider


class ReconstructionService:
    """Queue → batcher → cache → workers, one lifecycle, one job registry."""

    def __init__(self, config: ServeConfig = ServeConfig(),
                 calib_provider=None,
                 registry: "trace.MetricsRegistry | None" = None,
                 tracer: "trace.Tracer | None" = None):
        self.config = config
        # Fresh registry per service by default: parallel services (tests,
        # bench sweeps) must not sum each other's counters. Pass
        # trace.REGISTRY explicitly to meter into the process-global one.
        self.registry = registry if registry is not None \
            else trace.MetricsRegistry()
        self.tracer = tracer if tracer is not None else trace.GLOBAL
        self.queue = AdmissionQueue(max_depth=config.queue_depth)
        self.batcher = BucketBatcher(
            self.queue, buckets=config.buckets,
            batch_sizes=config.batch_sizes,
            linger_s=config.linger_ms / 1e3)
        self.calib_provider = (calib_provider if calib_provider is not None
                               else synthetic_calib_provider(config.proj))
        self.cache = ProgramCache(self.calib_provider,
                                  max_entries=config.max_cache_entries,
                                  registry=self.registry)
        # Fleet identity: journaled session heads carry it, so a
        # restarting replica can tell "still mine" from "a survivor
        # adopted this while I was dead" (handoff-aware recovery).
        self.replica_id = config.replica_id or f"r-{uuid.uuid4().hex[:8]}"
        # Shared session-handoff volume (fleet tier): session ops stream
        # there as the WAL sink, riding the group commit.
        if config.handoff_dir is not None and config.store_dir is None:
            raise ValueError(
                "handoff_dir requires store_dir — the handoff stream is "
                "a sink of the WAL's group commit")
        self.handoff: SessionStreamStore | None = (
            SessionStreamStore(config.handoff_dir)
            if config.handoff_dir is not None else None)
        # Durability journal + persistent content cache share one volume.
        self.store: JournalStore | None = (
            JournalStore(config.store_dir, sink=self.handoff)
            if config.store_dir is not None else None)
        self.content_cache: ContentCache | None = (
            ContentCache(max_bytes=config.content_cache_bytes,
                         dir=(self.store.content_dir
                              if self.store is not None else None),
                         registry=self.registry)
            if config.content_cache else None)
        # Peer half of the shared content cache (serve/fleet.py):
        # consulted at admission after a local miss; every degraded mode
        # converges on "compute locally", never a stall.
        self.peer_cache: PeerCacheClient | None = (
            PeerCacheClient(config.peers,
                            timeout_s=config.peer_timeout_s,
                            budget_s=config.peer_budget_s,
                            negative_ttl_s=config.peer_negative_ttl_s,
                            registry=self.registry)
            if (config.peers and config.content_cache) else None)
        # Constructed here (its counter families must exist in the
        # registry from the first scrape) but installed into the compile-
        # event dispatch only for the start→drain window, so an abandoned
        # or failed service never keeps receiving process-wide events.
        self.telemetry: "telemetry.DeviceTelemetry | None" = (
            telemetry.DeviceTelemetry(registry=self.registry)
            if config.telemetry else None)
        self.governor = OverloadGovernor(
            config.governor, self.queue, self.registry,
            telemetry=self.telemetry, store=self.store)
        # Per-tenant admission quotas (serve/tenants.py); None = off.
        self.tenants: TenantQuotas | None = (
            TenantQuotas(config.tenant_rate_per_s, config.tenant_burst,
                         self.registry)
            if config.tenant_rate_per_s > 0 else None)
        # Device-lane pool (serve/lanes.py): every worker lane is pinned
        # to one local device; sessions get sticky lanes; buckets past
        # shard_min_pixels route to the cross-chip sharded tier. The
        # pool also owns lane HEALTH — its device-dead transitions call
        # back into _on_device_dead (cross-lane re-pin, worker
        # deactivation, probe-revive scheduling).
        self.lanes = DeviceLanePool(
            n_lanes=max(1, config.workers),
            max_devices=config.devices,
            shard_min_pixels=config.shard_min_pixels,
            shard_devices=config.shard_devices,
            registry=self.registry)
        self.lanes.on_device_dead = self._on_device_dead
        # Sharded-fault attribution (docs/ROBUSTNESS.md § probe-
        # convict): N consecutive faults on one sharded span fire this
        # hook; the service probes each member and convicts the dead
        # one — the only way a sharded-only workload ever detects a
        # chip death (the launch error can't name the member).
        self.lanes.on_span_suspect = self._on_span_suspect
        # Lane re-resolution at absorb time (device-loss tier): a stop
        # whose session re-pinned must ride the adopting lane's buckets.
        self.batcher.lane_resolver = self._resolve_lane
        # Seeded device chaos (hw/faults.py): SL_DEVICE_FAULTS arms a
        # FaultyDevice shim at every lane's launch boundary — how the
        # chaos bench and the multichip-chaos CI gate kill a chip.
        from ..hw import faults as hwfaults

        plan = hwfaults.DeviceFaultPlan.from_env()
        self.fault_injector = (hwfaults.DeviceFaultInjector(plan)
                               if plan is not None else None)
        if self.fault_injector is not None:
            log.warning("device faults armed: %d rule(s)",
                        len(plan.rules))
        # Probe-revive bookkeeping: device label -> (backoff_s, due_t).
        self._probe_plan: dict[str, tuple[float, float]] = {}
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self._queue_depth0 = config.queue_depth
        self._workers_lock = threading.Lock()
        self._worker_seq = max(1, config.workers)
        self.workers = [self._make_worker(f"serve-worker-{i}",
                                          self.lanes.lane(i))
                        for i in range(max(1, config.workers))]
        self._jobs_lock = threading.Lock()
        self._jobs: OrderedDict[str, Job] = OrderedDict()
        self._draining = False
        self._started = False
        self._jobs_total = lambda status: self.registry.counter(
            "serve_jobs_total", "jobs by admission/terminal status",
            status=status)
        self._queue_gauge = self.registry.gauge(
            "serve_queue_depth", "jobs waiting in the admission queue")
        # Per-job latency histograms: seconds-valued, so they take the
        # explicit latency bucket layout (the occupancy-shaped Histogram
        # default would bin every sub-second wait into `le="1"`).
        self._queue_wait_s = self.registry.histogram(
            "serve_job_queue_wait_seconds",
            "submit-to-start wait per job",
            buckets=trace.LATENCY_SECONDS_BUCKETS)
        self._run_s = self.registry.histogram(
            "serve_job_run_seconds", "start-to-terminal time per job",
            buckets=trace.LATENCY_SECONDS_BUCKETS)
        self._events_seen: dict[str, int] = {}  # _sync_event_counters
        self._events_seen_lock = threading.Lock()
        self._warmup_report: dict = {}
        self._ready = False  # /readyz: warmup + recovery complete
        self.sessions = SessionManager(
            config.stream, config.proj, config.decode_cfg, config.tri_cfg,
            max_sessions=config.max_sessions,
            session_ttl_s=config.session_ttl_s,
            store=self.store,
            preview_shed=self.governor.shed_previews,
            replica_id=self.replica_id,
            lane_pool=self.lanes if self.lanes.multi_device else None)

    def _make_worker(self, name: str, lane) -> DeviceWorker:
        return DeviceWorker(self.batcher, self.cache,
                            gates=self.config.gates,
                            mesh_depth=self.config.mesh_depth,
                            registry=self.registry, tracer=self.tracer,
                            name=name, governor=self.governor,
                            mesh_representation=self.config
                            .mesh_representation,
                            lane=lane, lane_pool=self.lanes,
                            fault_injector=self.fault_injector)

    def _restart_worker(self, wedged: DeviceWorker) -> DeviceWorker:
        """Watchdog callback: replace one wedged worker with a fresh
        lane ON THE SAME DEVICE — the wedged worker's sticky sessions
        and per-device AOT programs live there, so a replacement that
        migrated would compile (and strand every session pinned to the
        lane). The wedged thread is asked to stop but cannot be killed —
        if its launch ever returns, Job's first-terminal-wins rule makes
        the race harmless."""
        wedged.request_stop()
        wedged.abort()
        with self._workers_lock:
            self._worker_seq += 1
            repl = self._make_worker(
                f"serve-worker-r{self._worker_seq}", wedged.lane)
            self.workers = [repl if w is wedged else w
                            for w in self.workers]
        repl.start()
        return repl

    # -- device-loss tolerance (serve/lanes.py; SERVING.md) ---------------

    def _escalate_worker_device(self, worker: DeviceWorker) -> None:
        """Watchdog escalation: a device whose per-device restart budget
        is spent (every fresh lane wedges) is declared DEAD — the pool's
        callback then re-pins its sessions and schedules the probe."""
        if worker.lane is None:
            return
        self.lanes.mark_device_dead(worker.lane.label,
                                    reason="watchdog budget exhausted")

    def _resolve_lane(self, job: Job) -> int | None:
        """Batcher lane hook: the lane a job should ride NOW. Session
        stops follow their session's CURRENT sticky lane (it may have
        re-pinned since the stop was submitted); anything stamped with
        a dead lane re-routes to the least-loaded survivor."""
        pool = self.lanes
        if not pool.multi_device:
            return job.lane
        if job.session_id is not None and job.launch_retries == 0:
            # Session affinity — EXCEPT for a job the device-loss path
            # already re-laned: its explicit retry placement must win,
            # or the resolver would bounce it straight back onto the
            # sick (not-yet-dead) lane it just died on, burning the
            # retry budget without ever reaching a survivor.
            entry = self.sessions.peek(job.session_id)
            if entry is not None and entry.lane is not None \
                    and pool.lane_alive(entry.lane.index):
                return entry.lane.index
        if job.lane is None or pool.lane_alive(job.lane):
            return job.lane
        target = pool.retry_lane()
        return target.index if target is not None else job.lane

    def _lane_program_keys(self, lane) -> list:
        """The ProgramKeys a worker on ``lane`` can dispatch to, over
        the configured buckets × batch sizes — the single definition of
        the warmed program set, shared by start()'s warmup and the
        probe path's re-warm (divergence would silently re-introduce
        post-revive compiles in the worker hot path)."""
        keys = []
        for h, w in self.config.buckets:
            bkey = self._bucket_key(h, w)
            for b in self.config.batch_sizes:
                keys.append(self.lanes.route(bkey, int(b), lane))
        return keys

    def _span_program_keys(self, span) -> list:
        """The sharded ProgramKeys the router would answer over an
        EXPLICIT span, for every configured bucket × batch — the warm
        set for a span about to come into service (probe-convict
        re-form, revival restore). Warming these OFF the worker hot
        path is what keeps the zero-recompile steady state across a
        span change."""
        keys = []
        for h, w in self.config.buckets:
            bkey = self._bucket_key(h, w)
            for b in self.config.batch_sizes:
                k = self.lanes.span_program_key(bkey, int(b), span)
                if k is not None:
                    keys.append(k)
        return keys

    def _warm_span_programs(self, span) -> bool:
        """Compile/warm the sharded programs for ``span``; True when
        every key is resident afterwards. Failures are contained — the
        worker's next dispatch would compile inline (counted, slower,
        but correct), so a warm failure must not block the span change
        that routing has already made."""
        ok = True
        for k in self._span_program_keys(span):
            try:
                self.cache.get(k)
            except Exception as e:
                ok = False
                events.record("span_warm_failed", severity="error",
                              program=k.label(), message=str(e))
        return ok

    def _on_span_suspect(self, span) -> None:
        """Probe-convict: the pool saw N consecutive device-class
        faults on sharded launches over ``span`` (worker thread; no
        locks held). Run the tiny probe program on EVERY span member —
        the launch error couldn't name the casualty, the per-member
        probe can — and convict the ones that fail via
        ``mark_device_dead`` (which re-pins sessions, stops lane
        workers, and schedules the probe-revive cycle exactly like a
        lane-attributed death). Then warm the re-formed span's programs
        so surviving sharded traffic stays compile-free."""
        convicted = []
        for label in span:
            if self.lanes.device_state(label) == LANE_DEAD:
                continue  # already convicted (e.g. by a lane launch)
            if not self._probe_device(label):
                convicted.append(label)
        if not convicted:
            # Inconclusive: every member answered its probe. Transient
            # mesh failure (link blip, collective timeout) — leave the
            # span alone; another fault streak re-probes.
            events.record("span_probe_inconclusive", severity="warning",
                          span=list(span))
            return
        for label in convicted:
            events.record("span_member_convicted", severity="error",
                          device=label, span=list(span))
            log.error("sharded span %s: probe convicted member %s",
                      "+".join(span), label)
            self.lanes.mark_device_dead(
                label, reason="sharded-fault probe conviction")
        new_span = self.lanes.span_devices()
        if new_span:
            self._warm_span_programs(new_span)
        if self.store is not None:
            self.store.note("span_reformed", convicted=convicted,
                            span=list(new_span))

    def _lane_device_count(self) -> int:
        return len(self.lanes.distinct_devices())

    def _rescale_queue(self) -> None:
        """Degraded-capacity honesty: the admission bound tracks the
        live-device fraction, so /readyz, /fleet/signals and the 429
        backpressure all describe the pool that actually exists."""
        total = self._lane_device_count()
        if total <= 1:
            return
        live = max(0, total - len(self.lanes.dead_devices()))
        self.queue.set_max_depth(
            max(1, round(self._queue_depth0 * max(1, live) / total)))
        self._queue_gauge.set(self.queue.depth())

    def _on_device_dead(self, label: str) -> None:
        """The pool's dead-transition callback (worker or watchdog
        thread; no locks held). Contain the chip: stop its workers
        (their in-flight batch was already re-queued cross-lane), move
        its sticky sessions to surviving lanes (compile-free — every
        distinct lane device was session-warmed at start), re-key any
        pending work, shrink the admission bound, and schedule the
        probe-revive cycle."""
        with self._workers_lock:
            victims = [w for w in self.workers
                       if w.lane is not None and w.lane.label == label]
        for w in victims:
            # abandoned: the watchdog must not "replace" a deactivated
            # worker, and _revive_device's replacement scan must cover
            # a victim still ALIVE at revival time (e.g. blocked inside
            # a hung launch that outlives the quarantine) — skipping it
            # would leave the revived lane permanently worker-less.
            w.abandoned = True
            w.request_stop()
            w.abort()
        moved = self.lanes.repin_sessions(label)
        for sid, lane in moved.items():
            entry = self.sessions.peek(sid)
            if entry is not None:
                # repin migrates the session's device-resident state
                # too — committed arrays would otherwise keep pulling
                # compute back to the dead chip.
                entry.repin(lane)
        repinned = self.batcher.repin_pending()
        self._rescale_queue()
        if self.store is not None:
            self.store.note("device_dead", device=label,
                            sessions_repinned=len(moved))
        log.warning("device %s contained: %d worker(s) stopped, %d "
                    "session(s) re-pinned, %d pending job(s) re-keyed",
                    label, len(victims), len(moved), repinned)
        cfg = self.config
        self._probe_plan[label] = (
            cfg.device_probe_interval_s,
            time.monotonic() + cfg.device_probe_interval_s)
        if not self._draining:
            self._ensure_probe_thread()

    def _ensure_probe_thread(self) -> None:
        with self._workers_lock:
            if self._probe_thread is not None \
                    and self._probe_thread.is_alive():
                # Benign with the exit handshake in _probe_loop: a
                # thread seen alive here either already cleared
                # _probe_thread (we spawn fresh) or will re-check
                # dead_devices() under this same lock before exiting
                # and keep looping for the device that just died.
                return
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="serve-device-probe",
                daemon=True)
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        """Quarantine probing: each dead device gets a tiny synthetic
        launch at backoff cadence; success re-warms and revives the
        lane. The thread exits when nothing is dead (restarted by the
        next dead transition) — the exit re-checks under _workers_lock
        so a concurrent dead transition can never slip between the
        empty check and _ensure_probe_thread's is_alive() test and be
        left with no probe cycle."""
        tick = min(0.5, self.config.device_probe_interval_s / 2)
        while not self._probe_stop.wait(max(0.05, tick)):
            dead = self.lanes.dead_devices()
            if not dead:
                with self._workers_lock:
                    if self.lanes.dead_devices():
                        continue  # died between checks: keep probing
                    self._probe_thread = None
                    return
            now = time.monotonic()
            for label in dead:
                backoff, due = self._probe_plan.get(
                    label, (self.config.device_probe_interval_s, now))
                if now < due:
                    continue
                ok = self._probe_device(label)
                events.record("device_probe", severity="info",
                              device=label, ok=ok,
                              backoff_s=round(backoff, 2))
                # The plan is dropped only on a COMPLETED revival: a
                # probe that answered but whose re-warm failed keeps
                # the device dead, and must keep its backoff too — a
                # popped plan would retry probe + full re-warm every
                # tick in a hot loop.
                if ok and self._revive_device(label):
                    self._probe_plan.pop(label, None)
                else:
                    backoff = min(
                        backoff * 2,
                        self.config.device_probe_backoff_max_s)
                    self._probe_plan[label] = (backoff,
                                               now + backoff)

    def _probe_device(self, label: str) -> bool:
        """One probe launch on a dead device, THROUGH the fault
        boundary (a still-faulted chip must stay quarantined)."""
        if self.fault_injector is not None:
            # Counts as a launch on purpose (see next_fault): probes
            # are what let a count-limited transient outage expire
            # while the device is quarantined and worker-launch-free.
            rule = self.fault_injector.next_fault(label)
            if rule is not None and rule.kind != "latency":
                # Any still-armed fault keeps the chip quarantined —
                # including nan_output: the injector poisons WORKER
                # launches, not this probe's arithmetic, so treating a
                # NaN-emitting chip's probe as clean would revive it
                # into an indefinite die/revive flap. (Real hardware
                # needs no special case: whatever the sick chip
                # actually returns hits the finite check below.)
                return False
        dev = self.lanes.device_by_label(label)
        if dev is None:
            return False
        try:
            import jax

            x = jax.device_put(np.ones((8,), np.float32), dev)
            out = np.asarray(x + np.float32(1.0))
            return bool(np.isfinite(out).all())
        except Exception as e:
            log.debug("device probe %s failed: %s", label, e)
            return False

    def _revive_device(self, label: str) -> bool:
        """Probe success: re-warm the lane's program set AND the
        restored (post-revival) sharded span's programs (cache hits
        when still resident; honest counted compiles when the LRU
        evicted them while dead), THEN rejoin — fresh workers, restored
        admission bound, fresh watchdog budget — and migrate the
        sessions that were displaced off this device back home
        (``rebalance_sessions``; compile-free via the per-device
        session warmup, so their finalize stays bitwise, with flap
        hysteresis so a bouncing chip doesn't thrash them). True iff
        the device actually rejoined (a failed re-warm keeps it dead
        and the caller keeps its probe backoff)."""
        lanes = self.lanes.lanes_on(label)
        if not lanes:
            return False
        try:
            for k in self._lane_program_keys(lanes[0]):
                if k.device == label:
                    self.cache.get(k)
            # The span this revival restores (the full set again once
            # every member is back): warmed BEFORE the device flips
            # live, so the first sharded dispatch after the re-form is
            # a hit, not an inline compile on the request path.
            for k in self._span_program_keys(
                    self.lanes.span_devices(assume_live=label)):
                self.cache.get(k)
        except Exception as e:
            events.record("device_rewarm_failed", severity="error",
                          device=label, message=str(e))
            return False  # stays dead; the probe retries at backoff
        self.lanes.revive_device(label)
        # Restore the admission bound in the same breath as the state
        # flip: anything watching device_state() may act on HEALTHY
        # immediately, and the worker-restart + rebalance steps below
        # can take a while on a loaded host.
        self._rescale_queue()
        self.governor.reset_restart_budget(label)
        with self._workers_lock:
            for lane in lanes:
                for i, w in enumerate(self.workers):
                    if w.lane is lane and (not w.alive
                                           or getattr(w, "abandoned",
                                                      False)):
                        self._worker_seq += 1
                        repl = self._make_worker(
                            f"serve-worker-r{self._worker_seq}", lane)
                        self.workers[i] = repl
                        repl.start()
        # Revival rebalancing: bring the displaced sticky sessions home
        # (after the fresh workers exist, so the lane can serve them).
        moved = self.lanes.rebalance_sessions(label)
        for sid, lane in moved.items():
            entry = self.sessions.peek(sid)
            if entry is not None:
                entry.repin(lane)
        if moved:
            self.batcher.repin_pending()
        if self.store is not None:
            self.store.note("device_revived", device=label,
                            sessions_rebalanced=len(moved))
        return True

    # -- lifecycle ---------------------------------------------------------

    def start(self, recover_from: "str | bool | None" = None
              ) -> "ReconstructionService":
        """Warm up, optionally recover a journal volume, start workers.

        ``recover_from``: True replays this service's own ``store_dir``;
        a path opens (and adopts) that volume. Recovery runs AFTER
        warmup — the replay rides the already-compiled B=1 lane — and
        BEFORE the workers start, so recovered jobs re-queue exactly
        once, ahead of fresh traffic. ``/readyz`` reports 503 until this
        method completes."""
        if recover_from and recover_from is not True \
                and self.store is not None and os.path.abspath(
                    str(recover_from)) != os.path.abspath(self.store.root):
            # Silently replaying the CONFIGURED volume while the caller
            # named a different one would "recover" nothing they asked
            # for and journal new state to the wrong disk.
            raise ValueError(
                f"recover_from={recover_from!r} conflicts with the "
                f"configured store_dir {self.store.root!r} — a service "
                "journals to exactly one volume")
        if recover_from and self.store is None:
            if recover_from is True:
                raise ValueError("recover_from=True needs a configured "
                                 "store_dir")
            self.store = JournalStore(str(recover_from),
                                      sink=self.handoff)
            self.sessions.store = self.store
            self.governor.store = self.store
            if self.content_cache is not None:
                # Adopting the volume adopts its persistent content
                # cache too — the memory-only cache built when store_dir
                # was unset would miss every pre-restart artifact.
                self.content_cache = ContentCache(
                    max_bytes=self.config.content_cache_bytes,
                    dir=self.store.content_dir, registry=self.registry)
        if self.telemetry is not None:
            self.telemetry.install()   # before warmup: count its compiles
        try:
            if self.config.warmup:
                # Warm EXACTLY the program set the lane router will
                # dispatch to (serve/lanes.py): per-device keys for
                # every distinct lane chip, the cross-chip sharded key
                # for buckets past shard_min_pixels, the historical
                # un-pinned keys on a single-device pool — so the
                # zero-recompile steady state holds per chip.
                t0 = time.monotonic()
                pkeys, seen = [], set()
                for lane in self.lanes.distinct_devices():
                    for k in self._lane_program_keys(lane):
                        if k not in seen:
                            seen.add(k)
                            pkeys.append(k)
                self._warmup_report = self.cache.warmup(
                    (), program_keys=pkeys)
                log.info("warmup: %d programs in %.1fs",
                         len(self._warmup_report), time.monotonic() - t0)
                if self.config.warmup_sessions:
                    # Session-lane warmup (stream/warmup.py): an adopted
                    # or recovered session must find every per-stop
                    # program already compiled — the fleet failover
                    # window is otherwise dominated by these compiles.
                    # Runs ONCE PER DISTINCT LANE DEVICE (jit keys
                    # placement): a session is sticky on its lane, and
                    # both first placement and failover adoption must
                    # find that chip's programs warm.
                    from ..stream.warmup import warm_session_programs

                    import contextlib

                    session_lanes = (self.lanes.distinct_devices()
                                     if self.lanes.multi_device
                                     else [None])
                    for h, w in self.config.buckets:
                        for lane in session_lanes:
                            label = f"session:{h}x{w}" + (
                                f"@{lane.label}" if lane else "")
                            if lane is not None:
                                import jax

                                ctx = jax.default_device(lane.device)
                            else:
                                ctx = contextlib.nullcontext()
                            with ctx:
                                report = warm_session_programs(
                                    self.config.stream, h * w,
                                    col_bits=self.config.proj.col_bits,
                                    row_bits=self.config.proj.row_bits,
                                    frame_shape=(h, w))
                            self._warmup_report[label] = report
            if recover_from:
                self._recover()
        except BaseException:
            if self.telemetry is not None:
                self.telemetry.uninstall()
            raise
        for w in self.workers:
            w.start()
        self.governor.start_watchdog(lambda: list(self.workers),
                                     self._restart_worker,
                                     escalate_fn=(
                                         self._escalate_worker_device
                                         if self.lanes.multi_device
                                         else None))
        self._started = True
        self._ready = True
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: refuse new work, finish everything admitted,
        stop workers. Returns True when every worker exited in time."""
        self._draining = True
        self._ready = False
        self.queue.close()
        self.governor.stop_watchdog()
        self._probe_stop.set()
        for w in self.workers:
            w.request_stop()
        deadline = time.monotonic() + timeout
        ok = True
        for w in self.workers:
            w.join(max(0.0, deadline - time.monotonic()))
            ok = ok and not w.alive
        if not ok:
            log.warning("drain timed out after %.1fs with workers alive",
                        timeout)
        if self.telemetry is not None:
            self.telemetry.uninstall()
        if self.store is not None:
            self.store.note("drain", clean=ok)
            self.store.close()
        return ok

    def abort(self) -> None:
        """Crash-style stop for the durability tests and the soak bench:
        workers exit at their next loop iteration WITHOUT draining, the
        queue keeps its jobs, nothing journals a terminal transition —
        the in-process stand-in for ``kill -9``. The journal retains
        every acked op; a new service over the same ``store_dir`` with
        ``start(recover_from=True)`` takes over."""
        self._draining = True
        self._ready = False
        self.governor.stop_watchdog()
        self._probe_stop.set()
        for w in self.workers:
            w.abort()
        for w in self.workers:
            w.join(timeout=5.0)
        if self.telemetry is not None:
            self.telemetry.uninstall()
        if self.store is not None:
            self.store.close()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def ready(self) -> bool:
        """Readiness (the ``/readyz`` contract): warmup + recovery done,
        at least one worker lane alive, not draining."""
        return (self._ready and not self._draining
                and any(w.alive for w in self.workers))

    def _bucket_key(self, h: int, w: int) -> BucketKey:
        cfg = self.config
        return BucketKey(height=h, width=w, frames=cfg.proj.n_frames,
                         col_bits=cfg.proj.col_bits,
                         row_bits=cfg.proj.row_bits,
                         decode_cfg=cfg.decode_cfg, tri_cfg=cfg.tri_cfg)

    # -- recovery (serve/store.py) -----------------------------------------

    def _recover(self) -> None:
        """Replay the journal: re-queue every non-terminal job under its
        ORIGINAL id (clients keep polling the ids they hold) and rebuild
        every live session by replaying its accepted stops through the
        compiled B=1 lane — deterministic, so a recovered session
        finalizes bitwise-identically to an uninterrupted one."""
        state = self.store.recover()
        if state.empty:
            return
        t0 = time.monotonic()
        n_jobs = n_sessions = n_stops = 0
        for rj in state.jobs:
            try:
                stack = self.store.load_stack(rj.stack_path)
            except (OSError, ValueError) as e:
                # A purged/corrupt stack blob: the job cannot replay.
                # Register it FAILED (the client polling its id gets an
                # honest taxonomy answer, not a 404) — which also
                # journals its terminal op, so the dead admission stops
                # haunting every future recovery of this volume.
                events.record("job_recover_failed", severity="error",
                              job_id=rj.job_id, message=str(e))
                job = Job(stack=np.empty((0, 0, 0), np.uint8),
                          col_bits=self.config.proj.col_bits,
                          row_bits=self.config.proj.row_bits,
                          result_format=rj.result_format,
                          priority=rj.priority, job_id=rj.job_id)
                job.journal_kind = "job"
                job.recovered = True
                job.on_terminal = self._on_terminal
                self._jobs_total("submitted").inc()
                with events.context(job_id=job.job_id):
                    from ..health import CaptureError

                    job.fail(CaptureError(
                        f"recovered capture stack unreadable "
                        f"({rj.stack_path}): {e}"))
                self._register(job)
                continue
            deadline = None
            if rj.deadline_s is not None:
                deadline = rj.deadline_s - (time.time()
                                            - rj.submitted_wall)
            job = Job(stack=stack, col_bits=self.config.proj.col_bits,
                      row_bits=self.config.proj.row_bits,
                      decode_cfg=self.config.decode_cfg,
                      tri_cfg=self.config.tri_cfg,
                      result_format=rj.result_format,
                      priority=rj.priority,
                      deadline_s=deadline, job_id=rj.job_id)
            job.content_key = rj.content_key
            job.journal_kind = "job"
            job.recovered = True
            job.on_terminal = self._on_terminal
            self._jobs_total("submitted").inc()
            with events.context(job_id=job.job_id):
                if deadline is not None and deadline <= 0:
                    self._register(job)
                    from .jobs import DeadlineExceededError

                    job.fail(DeadlineExceededError(
                        f"deadline {rj.deadline_s:.2f}s lapsed across "
                        "the crash/restart window"))
                    continue
                try:
                    self.queue.submit(job)
                except JobRejected as e:  # shrunk queue_depth on restart
                    self._register(job)
                    job.fail(e)
                    continue
                self._register(job)
            events.record("job_recovered", job_id=job.job_id,
                          result_format=rj.result_format)
            n_jobs += 1
        for rs in state.sessions:
            if self.handoff is not None:
                # Handoff-aware recovery: while this replica was dead
                # the router may have re-pinned the session to a
                # survivor (adopt_session stamps the stream's owner),
                # or the session may have ENDED there (end tombstone).
                # Either is POSITIVE evidence the session is no longer
                # ours — journal the local tombstone so this WAL drains
                # clean instead of resurrecting a second live copy. A
                # MISSING stream is the opposite: the mirror never
                # wrote (shared-volume failure, handoff enabled after
                # the session started) and this WAL holds the ONLY
                # copy — recover it; losing acked stops to a mirror
                # hiccup would invert the durability contract.
                stream = self.handoff.stream_state(rs.session_id)
                owner = self.handoff.owner(rs.session_id)
                if stream == "ended" or (stream == "live"
                                         and owner != rs.replica):
                    events.record(
                        "session_skipped_handed_off", severity="warning",
                        session_id=rs.session_id, journaled_by=rs.replica,
                        stream_state=stream, stream_owner=owner)
                    # scope=local: the sink must NOT mirror this end —
                    # the stream now belongs to the adopter (or is the
                    # tombstone we consume below).
                    self.store.append(
                        {"op": "session_end",
                         "session_id": rs.session_id,
                         "reason": "handed_off", "scope": "local"},
                        sync=False)
                    if stream == "ended":
                        # Tombstone consumed: only THIS replica's WAL
                        # referenced it; dropping it bounds tombstone
                        # accumulation on long-lived volumes.
                        self.handoff.drop_session(rs.session_id)
                    continue
                if stream == "missing":
                    events.record(
                        "session_recovered_without_stream",
                        severity="warning", session_id=rs.session_id,
                        message="no handoff stream (mirror never "
                                "wrote); recovering from the local "
                                "WAL only")
            try:
                entry = self.sessions.restore(rs.session_id, rs.options,
                                              rs.scan_id)
            except JobRejected as e:  # shrunk max_sessions on restart
                events.record("session_recover_failed", severity="error",
                              session_id=rs.session_id, message=str(e))
                continue
            if self.handoff is not None and stream == "missing":
                # Heal the stream from the local WAL (head + stop
                # blobs) so the recovered session is adoptable again;
                # a still-failing shared volume degrades handoff only.
                try:
                    self.handoff.mirror(
                        {"op": "session", "session_id": rs.session_id,
                         "scan_id": rs.scan_id, "options": rs.options,
                         "replica": rs.replica}, self.store)
                    for jid, path in rs.stops:
                        self.handoff.mirror(
                            {"op": "stop",
                             "session_id": rs.session_id,
                             "job_id": jid, "stack": path}, self.store)
                except OSError as e:
                    self.handoff.mirror_failures += 1
                    events.record("handoff_mirror_failed",
                                  severity="error",
                                  session_id=rs.session_id,
                                  message=str(e))
            replayed = 0
            for path in rs.stop_paths:
                try:
                    stack = self.store.load_stack(path)
                    self._replay_stop(entry, stack)
                    replayed += 1
                except Exception as e:
                    # A stop that cannot replay degrades the session (it
                    # loses bitwise parity) but must not kill recovery
                    # of everything else.
                    events.record(
                        "session_recover_degraded", severity="error",
                        session_id=rs.session_id, message=str(e),
                        exc_type=type(e).__name__)
            with entry.lock:
                entry.stops_submitted = replayed
            events.record("session_recovered", session_id=rs.session_id,
                          scan_id=rs.scan_id, stops_replayed=replayed)
            n_sessions += 1
            n_stops += replayed
        log.info("recovered %d job(s), %d session(s) (%d stops "
                 "replayed) in %.2fs", n_jobs, n_sessions, n_stops,
                 time.monotonic() - t0)
        events.record("service_recovered", jobs=n_jobs,
                      sessions=n_sessions, stops=n_stops,
                      seconds=round(time.monotonic() - t0, 3))

    def _replay_stop(self, entry, stack: np.ndarray) -> None:
        """Run one journaled stop through the SAME program the worker
        used (the bucket's B=1 executable) and hand the per-lane arrays
        to the session's ingest — the exact decode path of the original
        submission, so replay is bit-reproducible."""
        stack = self._validate_stack(stack)
        probe = Job(stack=stack, col_bits=self.config.proj.col_bits,
                    row_bits=self.config.proj.row_bits,
                    decode_cfg=self.config.decode_cfg,
                    tri_cfg=self.config.tri_cfg)
        key = self.batcher.key_for(probe)
        # Route through the session's sticky lane (serve/lanes.py): the
        # replay must hit the SAME per-device executable the original
        # stops ran on — warmed at start, so recovery stays compile-free
        # and bitwise.
        pkey = self.lanes.route(key, 1, getattr(entry, "lane", None))
        compiled = self.cache.get(pkey)
        calib = self.cache.placed_calib(pkey)
        batch = np.zeros((1, key.frames, key.height, key.width), np.uint8)
        f, h, w = stack.shape
        batch[0, :f, :h, :w] = stack
        out = compiled(self.cache.stage(pkey, batch), calib)
        points = np.asarray(out.points)[0]
        colors = np.asarray(out.colors)[0]
        valid = np.asarray(out.valid)[0]
        vgrid = valid.reshape(key.height, key.width)[:h, :w]
        entry.ingest(points, colors, valid, coverage=float(vgrid.mean()),
                     frame_shape=(key.height, key.width))

    # -- submission --------------------------------------------------------

    def _content_sig(self, result_format: str) -> str:
        """Config half of the content-hash key: everything besides the
        pixels that shapes the artifact."""
        cfg = self.config
        return (f"{cfg.proj.col_bits}/{cfg.proj.row_bits}/"
                f"{cfg.decode_cfg}/{cfg.tri_cfg}/"
                f"mesh{cfg.mesh_depth}/{cfg.mesh_representation}/"
                f"{result_format}")

    def _tenant_cost(self, stack: np.ndarray) -> float:
        """Token spend for one admission: 1.0 historically, the stack's
        megapixel cost under ``tenant_cost_weighted``
        (`tenants.stack_cost` — a 4K stack spends ~8×, a 240p one
        ~1/10th, so per-tenant budgets meter actual fleet burn)."""
        if not self.config.tenant_cost_weighted:
            return 1.0
        from .tenants import stack_cost

        _, h, w = stack.shape
        return stack_cost(h, w)

    def submit_array(self, stack: np.ndarray, result_format: str = "ply",
                     priority="normal",
                     deadline_s: float | None = None,
                     tenant: str | None = None) -> Job:
        """Validate + admit one capture stack; returns the live Job.
        Raises a :class:`~.jobs.JobRejected` subclass on refusal.

        A content-cache hit (same bytes, same config, finished before —
        even pre-restart or post-eviction) returns a completed job
        WITHOUT touching the queue; the lookup runs before the overload
        governor AND the tenant quota because a cached answer costs
        nothing and relieves load."""
        cfg = self.config
        try:
            stack = self._validate_stack(stack)
            if result_format not in _SUBMIT_FORMATS:
                raise StackFormatError(
                    f"result_format must be one of "
                    f"{sorted(_SUBMIT_FORMATS)}, got {result_format!r}")
            if isinstance(priority, str):
                if priority not in _PRIORITY_NAMES:
                    raise StackFormatError(
                        f"priority must be one of "
                        f"{sorted(_PRIORITY_NAMES)} or an int, "
                        f"got {priority!r}")
                priority = _PRIORITY_NAMES[priority]
            ckey = None
            if self.content_cache is not None and not self._draining:
                # A draining service refuses even free answers: drain
                # means "go to another replica", and a 200 here would
                # keep clients pinned to a dying process.
                ckey = content_key(stack, self._content_sig(result_format))
                cached = self.content_cache.get(ckey)
                source = "local"
                if cached is None and self.peer_cache is not None:
                    # Shared fleet cache: a mesh computed on replica A
                    # answers a duplicate submit here. Bounded lookup —
                    # every degraded peer mode is a local miss. The
                    # fetched artifact is re-cached locally so the NEXT
                    # duplicate is a local hit.
                    cached = self.peer_cache.lookup(ckey)
                    source = "peer"
                    if cached is not None:
                        payload, meta, fmt = cached
                        self.content_cache.put(ckey, payload,
                                               dict(meta), fmt)
                if cached is not None:
                    return self._complete_from_cache(
                        ckey, result_format, int(priority), cached,
                        source=source)
            # Governor BEFORE the tenant spend: a fleet-side refusal
            # (breaker open, shedding) must not drain the tenant's
            # bucket for work that never ran — and a queue-full
            # rejection below refunds the token for the same reason.
            self.governor.admit(int(priority))
            cost = self._tenant_cost(stack)
            if self.tenants is not None:
                self.tenants.admit(tenant, cost=cost)
            job = Job(stack=stack, col_bits=cfg.proj.col_bits,
                      row_bits=cfg.proj.row_bits,
                      decode_cfg=cfg.decode_cfg, tri_cfg=cfg.tri_cfg,
                      result_format=result_format,
                      priority=int(priority), deadline_s=deadline_s)
            job.content_key = ckey
            # journal_kind BEFORE admission: a worker may reach the
            # terminal transition before _journal_job runs, and that
            # job_done must not be lost (the store's mirror tolerates
            # done-before-admitted ordering).
            job.journal_kind = "job" if self.store is not None else None
            # Observer BEFORE admission (a worker may finish the job
            # before _register runs); registry entry AFTER admission (a
            # rejected job must leave no trace — a pre-registered one
            # would sit QUEUED forever, pinning its stack, unbounded
            # growth under the exact overload the bounded queue exists
            # for).
            job.on_terminal = self._on_terminal
            try:
                self.queue.submit(job)
            except JobRejected:
                if self.tenants is not None:
                    # Refund EXACTLY the weighted spend (refund parity).
                    self.tenants.refund(tenant, cost=cost)
                raise
            self._journal_job(job, stack)
            self._register(job)
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise
        self._jobs_total("submitted").inc()
        self._queue_gauge.set(self.queue.depth())
        return job

    def _complete_from_cache(self, ckey: str, result_format: str,
                             priority: int, cached,
                             source: str = "local") -> Job:
        """Land a content-cache hit as an already-terminal job in the
        registry (same polling surface as a computed result).
        ``source`` says which half of the shared cache answered —
        "local" (this replica's disk/memory) or "peer" (fetched over
        the fleet's GET /cache/<key> protocol)."""
        payload, meta, fmt = cached
        job = Job(stack=np.empty((0, 0, 0), np.uint8),
                  col_bits=self.config.proj.col_bits,
                  row_bits=self.config.proj.row_bits,
                  result_format=fmt or result_format, priority=priority)
        job.content_key = ckey
        job.on_terminal = self._on_terminal
        self._jobs_total("submitted").inc()  # counter conservation
        job.mark_running()
        job.complete(payload, **{**meta, "content_cache_hit": True,
                                 "cache_source": source})
        self._register(job)
        events.record("content_cache_hit", job_id=job.job_id,
                      key=ckey[:12], source=source)
        return job

    def _journal_job(self, job: Job, stack: np.ndarray) -> None:
        """WAL the admission (stack blob first, then the op — the op
        must never reference a blob that does not exist). Runs after
        queue.submit: a rejected job journals nothing; the sync append
        is the durability promise the HTTP 200 rides on.

        A failing volume (disk full, I/O error) degrades DURABILITY,
        never availability: the job still runs and serves — it just
        won't survive a crash — and its journal_kind is cleared so the
        terminal op doesn't dangle against an admission that never
        landed."""
        if self.store is None:
            return
        try:
            rel = self.store.put_stack(job.job_id, stack)
            self.store.append({
                "op": "job", "job_id": job.job_id, "stack": rel,
                "result_format": job.result_format,
                "priority": job.priority, "deadline_s": job.deadline_s,
                "content_key": job.content_key})
        except OSError as e:
            job.journal_kind = None
            log.error("job %s admission not journaled (%s) — it will "
                      "not survive a crash", job.job_id, e)
            events.record("journal_write_failed", severity="error",
                          job_id=job.job_id, message=str(e))

    def _validate_stack(self, stack: np.ndarray) -> np.ndarray:
        cfg = self.config
        stack = np.asarray(stack)
        if stack.dtype != np.uint8:
            raise StackFormatError(
                f"stack must be uint8, got {stack.dtype}")
        if stack.ndim != 3:
            raise StackFormatError(
                f"stack must be (frames, H, W), got shape {stack.shape}")
        f, h, w = stack.shape
        if f != cfg.proj.n_frames:
            raise StackFormatError(
                f"stack has {f} frames; this service's protocol is "
                f"{cfg.proj.n_frames} (2 + 2x{cfg.proj.col_bits} + "
                f"2x{cfg.proj.row_bits})")
        # Must fit SOME configured bucket (per-axis maxima are not
        # enough: a stack under both maxima but inside no single bucket
        # would otherwise fail late in the worker — or trigger a
        # request-time compile of an off-menu quantum bucket).
        if h < 8 or w < 8 or not any(h <= bh and w <= bw
                                     for bh, bw in cfg.buckets):
            raise StackFormatError(
                f"frame size {h}x{w} fits no configured bucket "
                f"{list(cfg.buckets)} (min 8x8)")
        return stack

    # -- streaming sessions (docs/STREAMING.md) ----------------------------

    def create_session(self, options: dict | None = None,
                       tenant: str | None = None) -> dict:
        """``POST /session``: open a streaming session. Refused while
        draining (same rule as submissions), past ``max_sessions``, or
        over the tenant's admission quota."""
        if self._draining:
            from .jobs import QueueClosedError

            self._jobs_total("rejected").inc()
            raise QueueClosedError()
        try:
            if self.tenants is not None:
                self.tenants.admit(tenant)
            try:
                entry = self.sessions.create(options)
            except JobRejected:
                if self.tenants is not None:
                    self.tenants.refund(tenant)  # registry refused
                raise
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise
        return {"session_id": entry.session_id,
                "scan_id": entry.session.scan_id}

    def submit_session_stop(self, session_id: str, stack: np.ndarray,
                            tenant: str | None = None) -> Job:
        """``POST /session/<id>/stop``: admit one stop through the SAME
        queue → batcher → program-cache lane as one-shot jobs; the
        decoded arrays are handed to the session instead of a writer.
        Returns the live Job (its meta carries the fuse/skip decision)."""
        entry = self.sessions.get(session_id)
        cfg = self.config
        try:
            stack = self._validate_stack(stack)
            # Governor before the tenant spend (same rationale as
            # submit_array: fleet-side refusals don't charge tenants).
            self.governor.admit(1)
            cost = self._tenant_cost(stack)
            if self.tenants is not None:
                self.tenants.admit(tenant, cost=cost)
            job = Job(stack=stack, col_bits=cfg.proj.col_bits,
                      row_bits=cfg.proj.row_bits,
                      decode_cfg=cfg.decode_cfg, tri_cfg=cfg.tri_cfg,
                      result_format="json")
            job.decode_sink = entry.ingest
            job.journal_kind = "stop"
            job.session_id = session_id
            # Sticky lane affinity (serve/lanes.py): only the worker on
            # the session's device lane flushes this stop — the
            # session's fuse/preview programs live (warm) on that chip.
            if entry.lane is not None:
                job.lane = entry.lane.index
            job.on_terminal = self._on_terminal
            try:
                self.queue.submit(job)
            except JobRejected:
                if self.tenants is not None:
                    self.tenants.refund(tenant, cost=cost)  # nothing ran
                raise
            if self.store is not None:
                # The accepted stop IS the session's recoverable state:
                # replaying these blobs in order through the B=1 lane
                # rebuilds the session bit-for-bit. (A stop whose job
                # later FAILS service-side journals a stop_failed op —
                # replay must skip it exactly as the live session never
                # fused it.) A failing volume degrades durability, not
                # the stop itself.
                try:
                    rel = self.store.put_stack(
                        f"{session_id}-{job.job_id}", stack)
                    self.store.append({"op": "stop",
                                       "session_id": session_id,
                                       "job_id": job.job_id,
                                       "stack": rel})
                except OSError as e:
                    job.journal_kind = None
                    log.error("session %s stop not journaled (%s) — it "
                              "will not survive a crash", session_id, e)
                    events.record("journal_write_failed",
                                  severity="error",
                                  session_id=session_id,
                                  job_id=job.job_id, message=str(e))
            self._register(job)
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise
        entry.note_pending(job)
        with entry.lock:
            entry.stops_submitted += 1
        self._jobs_total("submitted").inc()
        self._queue_gauge.set(self.queue.depth())
        return job

    def session_preview(self, session_id: str):
        """``GET /session/<id>/preview``: latest progressive STL bytes +
        meta, or None before the first preview."""
        return self.sessions.get(session_id).preview_bytes()

    def _session_splat_mesher(self, entry):
        """The session's splat previewer, or a 400 when the session
        was not created with ``representation="splat"`` — the render
        surface exists only on that lane (docs/RENDERING.md)."""
        mesher = getattr(entry.session, "_mesher", None)
        if not hasattr(mesher, "render_png"):
            raise StackFormatError(
                "session has no render lane — create it with "
                '{"representation": "splat"} to get novel-view renders')
        return mesher

    def _splat_scene_off_lock(self, entry, mesher):
        """Build the session's current splat scene with the EXPENSIVE
        phase off the session lock (the ROADMAP async-scene-build
        item): the cheap seed snapshot runs under the lock, the
        fixed-iteration appearance fit runs lock-FREE on the snapshot
        (concurrent stop ingest proceeds — a live-polling render
        client no longer delays the capture cadence), and the publish
        re-takes the lock (newest-stops-wins, so racing builds
        converge). Returns the built scene, or None before the first
        fused stop."""
        with entry.lock:
            with entry.device_ctx():
                token = mesher.begin_scene_build()
        if token is None:
            return None
        with entry.device_ctx():
            mesher.finish_scene_build(token)
        with entry.lock:
            with entry.device_ctx():
                scene = mesher.adopt_scene(token)
            entry.last_t = time.monotonic()
        return scene

    def render_session(self, session_id: str, azim: float, elev: float,
                       width: int | None = None,
                       height: int | None = None):
        """``GET /session/<id>/render?az=..&el=..[&w=..&h=..]``: render
        the session's CURRENT splat scene from a novel orbit view —
        PNG bytes + meta, or None before the first fused stop (the
        endpoint's 409). Angles are traced operands of one compiled
        program per resolution; ``w``/``h`` must name a configured
        render size (each size is its own program — an open set would
        mint compiles on demand, which the zero-steady-state-recompile
        bar forbids), else 400. A render that follows new stops
        REBUILDS the scene (seed + ``splat_fit_iters`` fit steps) with
        the fit OFF the session lock (`_splat_scene_off_lock`), so
        concurrent stop ingest is not delayed; only the cheap
        seed/publish/raster phases hold the lock, on the session's
        sticky lane device (docs/RENDERING.md)."""
        entry = self.sessions.get(session_id)
        mesher = self._session_splat_mesher(entry)
        if (width is None) != (height is None):
            raise StackFormatError("pass both w and h, or neither")
        if width is not None \
                and not mesher.render_size_ok(width, height):
            raise StackFormatError(
                f"render size {width}x{height} is not served; "
                f"configured sizes: "
                f"{['%dx%d' % s for s in mesher.render_sizes]}")
        if not (-360.0 <= float(azim) <= 360.0) \
                or not (-90.0 <= float(elev) <= 90.0):
            raise StackFormatError(
                f"render angles out of range (az {azim}, el {elev}): "
                "az in [-360, 360], el in [-90, 90]")
        scene = self._splat_scene_off_lock(entry, mesher)
        if scene is None:
            return None
        with entry.lock:
            with entry.device_ctx():
                out = mesher.render_png(float(azim), float(elev),
                                        width, height, scene=scene)
            entry.last_t = time.monotonic()
        if out is not None:
            events.record("session_rendered", session_id=session_id,
                          **{k: out[1][k] for k in ("azim", "elev",
                                                    "render_s")})
        return out

    def session_splats(self, session_id: str) -> bytes | None:
        """``GET /session/<id>/splats``: the current splat scene as an
        .npz archive — ``cli render`` reproduces the endpoint's pixels
        from it offline (the serve↔CLI parity contract), or None
        before the first fused stop. The scene build's fit phase runs
        off the session lock, like renders."""
        entry = self.sessions.get(session_id)
        mesher = self._session_splat_mesher(entry)
        scene = self._splat_scene_off_lock(entry, mesher)
        if scene is None:
            return None
        with entry.lock:
            with entry.device_ctx():
                return mesher.scene_bytes(scene=scene)

    def finalize_session(self, session_id: str,
                         result_format: str = "stl") -> Job:
        """``POST /session/<id>/finalize``: close the ring, build the
        final artifact, and land it as a terminal job in the ordinary
        registry — the existing ``GET /result`` path serves it. Runs on
        the calling thread (one full pose solve + merge + mesh)."""
        if result_format not in ("ply", "stl", "mesh_ply", "render_png"):
            raise StackFormatError(
                f"result_format must be 'ply', 'stl', 'mesh_ply' or "
                f"'render_png', got {result_format!r}")
        entry = self.sessions.get(session_id)
        if result_format == "render_png":
            # Lane check BEFORE finalize — a 400 must not close the ring.
            self._session_splat_mesher(entry)
        cfg = self.config
        # Settle in-flight stops FIRST (without the session lock — their
        # sinks need it): a stop the client already got a 200 for must
        # be fused or journaled before the ring closes. A stop that
        # cannot settle inside the timeout surfaces as a 409 from the
        # session's own guards rather than a silent exclusion.
        entry.settle_pending(timeout_s=120.0)
        with entry.lock:
            if entry.result_job_id is not None:
                job = self.get_job(entry.result_job_id)
                if job is not None:
                    return job  # idempotent finalize
                from .sessions import SessionResultEvicted

                raise SessionResultEvicted(
                    f"session {session_id} finalized but its result "
                    "job fell out of the bounded registry — the "
                    "artifact is gone; re-scan")
            # Finalize on the session's sticky device (no-op context
            # without a lane): the model buffers already live there,
            # and the finalize-only programs (full-ring solve, merge)
            # compile-and-run where the session's data is instead of
            # pulling it across chips.
            with entry.device_ctx():
                result = entry.session.finalize(
                    mesh=result_format in ("stl", "mesh_ply"))
            if result_format == "stl":
                from .worker import _stl_bytes

                payload = _stl_bytes(result.mesh)
                meta = {"vertices": int(len(result.mesh.vertices)),
                        "faces": int(len(result.mesh.faces))}
            elif result_format == "mesh_ply":
                # Vertex-colored final mesh (colors survive only under
                # the TSDF representation; Poisson meshes carry none).
                from .worker import _mesh_ply_bytes

                payload = _mesh_ply_bytes(result.mesh)
                meta = {"vertices": int(len(result.mesh.vertices)),
                        "faces": int(len(result.mesh.faces)),
                        "colored": result.mesh.vertex_colors is not None}
            elif result_format == "render_png":
                # The splat lane's rendered artifact: the fitted scene's
                # default orbit view (docs/RENDERING.md; live-angle
                # renders ride GET /session/<id>/render). Under the
                # sticky lane device like every other session device
                # path — the lazy scene rebuild must land where the
                # per-lane warmup compiled.
                with entry.device_ctx():
                    out = self._session_splat_mesher(entry).render_png(
                        30.0, 20.0)
                if out is None:
                    raise RuntimeError(
                        "no splat scene to render (no stops fused)")
                payload, rmeta = out
                meta = {k: rmeta[k] for k in ("azim", "elev", "width",
                                              "height", "splats")}
            else:
                from .worker import _ply_bytes

                payload = _ply_bytes(result.cloud)
                meta = {}
            meta.update(points=len(result.cloud),
                        stops_fused=result.stats["stops_fused"],
                        stops_skipped=result.stats["stops_skipped"])
            job = Job(stack=np.empty((0, 0, 0), np.uint8),
                      col_bits=cfg.proj.col_bits,
                      row_bits=cfg.proj.row_bits,
                      result_format=result_format)
            job.on_terminal = self._on_terminal
            self._jobs_total("submitted").inc()  # counter conservation
            job.complete(payload, **meta)
            self._register(job)
            entry.result_job_id = job.job_id
        # Journal OUTSIDE the session lock (append can block on the
        # group commit): a finalized session's stops are no longer
        # needed for recovery — the artifact lives in the registry, and
        # a post-crash client re-scans (documented in SERVING.md).
        if self.store is not None:
            self.store.append({"op": "session_end",
                               "session_id": session_id,
                               "reason": "finalized",
                               "replica": self.replica_id})
        return job

    def adopt_session(self, session_id: str) -> dict:
        """``POST /session/<id>/adopt`` (fleet tier): take over a live
        session from the shared handoff stream — the router calls this
        on a survivor after the session's pinned replica died.

        Claims ownership on the stream FIRST (so the dead replica's
        eventual ``--recover`` sees the session is no longer its),
        re-journals the session into THIS replica's WAL (so the
        adopter's own crash-recovery covers it), and replays the
        journaled stops through the compiled B=1 lane — deterministic,
        so the re-pinned session finalizes bitwise-identically to an
        uninterrupted run. Idempotent: adopting a session already live
        here is a no-op report."""
        if self.handoff is None:
            raise StackFormatError(
                "this replica has no handoff volume configured "
                "(--handoff-dir)")
        if self._draining:
            from .jobs import QueueClosedError

            raise QueueClosedError()
        try:
            entry = self.sessions.get(session_id)
        except UnknownSessionError:
            entry = None
        if entry is not None:
            with entry.lock:
                fused = entry.session.stops_fused
            return {"session_id": session_id, "adopted": False,
                    "stops_fused": fused, "replica": self.replica_id}
        info = self.handoff.read_session(session_id)
        if info is None:
            raise UnknownSessionError(
                f"session {session_id!r} has no handoff stream (never "
                "created with a handoff volume, or already ended)")
        t0 = time.monotonic()
        # Ownership first — a sync, direct stream append: from this line
        # on, the previous owner's recovery must skip the session.
        self.handoff.append({"op": "session_owner",
                             "session_id": session_id,
                             "replica": self.replica_id,
                             "t_wall": time.time()})
        entry = self.sessions.restore(session_id, info.options,
                                      info.scan_id)
        if self.store is not None:
            self.store.append({"op": "session", "session_id": session_id,
                               "scan_id": info.scan_id,
                               "options": info.options,
                               "replica": self.replica_id})
        replayed = degraded = 0
        for job_id, blob in info.stops:
            try:
                stack = self.handoff.load_blob(blob)
                self._replay_stop(entry, stack)
            except Exception as e:
                # One unreadable blob degrades the session (bitwise
                # parity is gone) but must not kill the adoption.
                events.record("session_recover_degraded",
                              severity="error", session_id=session_id,
                              message=str(e), exc_type=type(e).__name__)
                degraded += 1
                continue
            if self.store is not None:
                # Same job ids as the origin replica's stops: the sink
                # re-mirrors them, and the stream reader dedups by id.
                rel = self.store.put_stack(
                    f"{session_id}-{job_id or uuid.uuid4().hex[:8]}",
                    stack)
                self.store.append({"op": "stop",
                                   "session_id": session_id,
                                   "job_id": job_id, "stack": rel})
            replayed += 1
        with entry.lock:
            entry.stops_submitted = replayed
            fused = entry.session.stops_fused
        events.record("session_adopted", session_id=session_id,
                      scan_id=info.scan_id, from_replica=info.replica,
                      replica=self.replica_id, stops_replayed=replayed,
                      stops_degraded=degraded,
                      seconds=round(time.monotonic() - t0, 3))
        log.info("adopted session %s from %s: %d stop(s) replayed "
                 "(%d degraded) in %.2fs", session_id, info.replica,
                 replayed, degraded, time.monotonic() - t0)
        return {"session_id": session_id, "adopted": True,
                "stops_fused": fused, "stops_degraded": degraded,
                "replica": self.replica_id}

    def cache_export(self, key: str) -> tuple[bytes, dict, str] | None:
        """``GET /cache/<key>`` (the peer protocol's server half): this
        replica's LOCAL content-cache entry, or None. Never consults
        peers — a fleet of replicas proxying each other's lookups would
        recurse. Uses the non-counting peek so peer probes don't inflate
        this replica's admission hit/miss counters."""
        if self.content_cache is None:
            return None
        return self.content_cache.peek(key)

    def check_admission(self, priority: int = 1,
                        tenant: str | None = None) -> None:
        """Headers-time backpressure probe for the HTTP layer: raises the
        rejection `submit_array` would (tenant quota, governor
        shedding/breaker OR queue backpressure), AND counts it — a
        refusal must hit the rejected counter whether it happened before
        or after the body was read. The tenant check is the NON-spending
        probe (`TenantQuotas.check`) — rejecting an over-budget tenant
        before its ~95 MB body is buffered, while the authoritative
        token spend happens exactly once, inside
        `submit_array`/`submit_session_stop`."""
        try:
            if self.tenants is not None:
                # Under cost weighting the true cost is unknown until
                # the body is read: probe at the COST FLOOR, so a cheap
                # stack a weighted admit would accept is never 429'd
                # at headers time (the probe stays advisory either
                # way; the authoritative spend is the weighted admit).
                from .tenants import MIN_STACK_COST

                probe_cost = (MIN_STACK_COST
                              if self.config.tenant_cost_weighted
                              else 1.0)
                self.tenants.check(tenant, cost=probe_cost)
            self.governor.admit(priority)
            self.queue.check_admission()
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise

    def _on_terminal(self, job: Job) -> None:
        """Counter conservation: every admitted job ends exactly one of
        done/failed (rejected jobs are counted at submit), wherever the
        terminal transition happened — worker postprocess, batch-scoped
        failure, or deadline scrub in the queue/batcher."""
        self._jobs_total("done" if job.status == DONE else "failed").inc()
        wait_end = job.started_t or job.finished_t
        if wait_end is not None:
            self._queue_wait_s.observe(wait_end - job.submitted_t)
        if job.started_t is not None and job.finished_t is not None:
            self._run_s.observe(job.finished_t - job.started_t)
        # Durability bookkeeping: only successful NON-hit artifacts enter
        # the content cache (failures keep their honest taxonomy answer;
        # a hit is already cached), and only one-shot jobs journal their
        # terminal (stops are tracked per session, synthesized result
        # jobs not at all).
        if (self.content_cache is not None and job.status == DONE
                and job.content_key is not None
                and job.result_bytes is not None
                and not job.result_meta.get("content_cache_hit")):
            self.content_cache.put(job.content_key, job.result_bytes,
                                   dict(job.result_meta),
                                   job.result_format)
        if self.store is not None and job.journal_kind == "job":
            self.store.append({"op": "job_done", "job_id": job.job_id,
                               "status": job.status}, sync=False)
        elif self.store is not None and job.journal_kind == "stop" \
                and job.status == FAILED:
            # A stop whose job failed SERVICE-side was never fused by
            # the live session; replay must skip it or a recovered
            # session would fuse one stop more than the uninterrupted
            # run (breaking bitwise recovery parity). Successful stops
            # stay journaled until their session ends.
            self.store.append({"op": "stop_failed",
                               "session_id": job.session_id,
                               "job_id": job.job_id}, sync=False)
        events.record("job_terminal",
                      severity="info" if job.status == DONE else "warning",
                      job_id=job.job_id, status=job.status,
                      exc_type=(job.error or {}).get("type"))

    def _register(self, job: Job) -> None:
        with self._jobs_lock:
            self._jobs[job.job_id] = job
            # Bound the registry two ways (live jobs are never touched —
            # a client could still be polling them):
            # count cap — drop the oldest terminal ENTRIES entirely;
            terminal = [(jid, j) for jid, j in self._jobs.items()
                        if j.status in (DONE, FAILED)]
            excess = len(self._jobs) - self.config.completed_cap
            for jid, _ in terminal[:max(0, excess)]:
                del self._jobs[jid]
            # byte budget — drop only the oldest result PAYLOADS. The
            # entries stay, so a client that saw "done" and comes late
            # gets an explicit 410 ("result evicted"), never a silent
            # unknown-job 404.
            kept = [j for _, j in terminal[max(0, excess):]]
            held = sum(len(j.result_bytes) for j in kept
                       if j.result_bytes is not None)
            for j in kept:
                if held <= self.config.result_cache_bytes:
                    break
                held -= j.release_result()

    # -- inspection --------------------------------------------------------

    def get_job(self, job_id: str) -> Job | None:
        with self._jobs_lock:
            return self._jobs.get(job_id)

    def result_payload(self, job: Job) -> bytes | None:
        """The job's artifact bytes — from the registry, or (when the
        byte-bounded registry evicted the payload) re-fetched from the
        content-hash cache. Only when BOTH are gone does ``/result``
        answer its 410."""
        data = job.result_bytes
        if data is None and job.content_key is not None \
                and self.content_cache is not None:
            cached = self.content_cache.get(job.content_key)
            if cached is not None:
                return cached[0]
        return data

    def status(self, job_id: str) -> dict | None:
        job = self.get_job(job_id)
        if job is None:
            return None
        out = job.status_dict()
        # Terminal counters are registered at observation time (cheap,
        # idempotent-per-scrape is fine for these dashboards).
        return out

    def stats(self) -> dict:
        out = {
            "replica_id": self.replica_id,
            "queue_depth": self.queue.depth(),
            "queue_capacity": self.queue.max_depth,
            "pending_batches": self.batcher.pending_depth(),
            "draining": self._draining,
            "ready": self.ready,
            "workers_alive": sum(w.alive for w in self.workers),
            "lanes": self.lanes.stats(),
            "cache": self.cache.stats(),
            "warmup": self._warmup_report,
            "sessions": self.sessions.stats(),
            "governor": self.governor.stats(),
        }
        if self.tenants is not None:
            out["tenants"] = self.tenants.stats()
        if self.content_cache is not None:
            out["content_cache"] = self.content_cache.stats()
        if self.store is not None:
            out["store"] = self.store.stats()
        if self.peer_cache is not None:
            out["peer_cache"] = self.peer_cache.stats()
        if self.handoff is not None:
            out["handoff"] = self.handoff.stats()
        return out

    def readiness(self) -> dict:
        """The ``/readyz`` payload: ready iff warmup + recovery are done,
        a worker lane is alive, and the service is not draining —
        routers stop sending here on 503 while ``/healthz`` (liveness)
        stays 200 so the orchestrator does NOT restart the pod during a
        drain or warmup."""
        reasons = []
        if not self._started:
            reasons.append("starting (warmup/recovery in progress)")
        if self._draining:
            reasons.append("draining")
        if self._started and not any(w.alive for w in self.workers):
            reasons.append("no worker lanes alive")
        out = {"ready": self.ready, "reasons": reasons,
               "replica_id": self.replica_id}
        dead = self.lanes.dead_devices()
        if dead:
            # Degraded-but-ready honesty: the pool serves at N−1 chips.
            # Routers keep sending (ready stays true while any lane
            # lives); autoscalers read the shrunken capacity here and
            # on /fleet/signals.
            out["degraded"] = True
            out["devices_dead"] = dead
            out["queue_capacity"] = self.queue.max_depth
        return out

    def metrics_text(self) -> str:
        self._queue_gauge.set(self.queue.depth())
        if self.telemetry is not None:
            self.telemetry.sample_memory()  # refresh device gauges
        self._sync_event_counters()
        return self.registry.prometheus_text(tracer=self.tracer)

    def _sync_event_counters(self) -> None:
        """Mirror the process flight recorder's severity tallies onto
        THIS service's registry at scrape time — the recorder is
        process-global and counts into trace.REGISTRY, which a service
        with a private registry (the default) never renders. Deltas keep
        the counters monotonic across scrapes; the lock keeps concurrent
        scrapes (ThreadingHTTPServer) from double-applying a delta. When
        the service IS handed the global registry, the recorder already
        counts there — mirroring would double every event."""
        if self.registry is trace.REGISTRY:
            return
        with self._events_seen_lock:
            for sev, total in events.RECORDER.severity_counts().items():
                seen = self._events_seen.get(sev, 0)
                if total > seen:
                    self.registry.counter(
                        "sl_events_total",
                        "flight-recorder events by severity",
                        severity=sev).inc(total - seen)
                    self._events_seen[sev] = total

    def events_jsonl(self, n: int = 256, kind: str | None = None) -> str:
        """Tail of the process flight journal (GET /events): the ordered,
        correlated record of what recently happened to which job.
        ``kind`` filters to one event kind (e.g. ``session_evicted``)."""
        return events.to_jsonl(n, kind=kind)


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


MAX_SUBMIT_BYTES = 1 << 30  # absolute transport bound; admission is tighter


class _ServeHandler(BaseHTTPRequestHandler):
    service: ReconstructionService  # bound by ServeHTTPServer

    protocol_version = "HTTP/1.1"
    # Socket timeout: a stalled upload or idle keep-alive connection must
    # not pin its handler thread forever — without this, N dead-slow
    # clients hold N threads with the admission queue's 429 never
    # engaging (the request never completes).
    timeout = 120.0

    def _json(self, obj, status=200, headers=()):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _bytes(self, data: bytes, content_type: str):
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # ------------------------------------------------------------------

    def _reject(self, e: JobRejected) -> None:
        """JobRejected → response mapping shared by every POST route."""
        payload = error_payload(e)
        retry = payload.get("retry_after_s")
        status = 400
        headers = []
        if e.retryable:
            status = 503 if retry is None else 429
            if retry is not None:
                headers.append(("Retry-After", str(max(1, round(retry)))))
        if self.close_connection:  # body was never read (length gate)
            headers.append(("Connection", "close"))
        self._json({"error": payload}, status, headers)

    def _read_stack_body(self):
        """Read + decode an ``.npy`` POST body behind the headers-time
        gates (length bound, queue backpressure + governor shedding) —
        the early-error paths respond WITHOUT reading the (possibly
        ~95 MB) body; under HTTP/1.1 keep-alive the unread bytes would
        desync the next request on the connection, so those paths close
        it."""
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0 or length > MAX_SUBMIT_BYTES:
            self.close_connection = True
            # Counted here because this refusal never reaches the
            # service's own counting gates (check_admission /
            # submit_array) — transport-level refusals must hit the
            # rejected counter too.
            self.service._jobs_total("rejected").inc()
            raise StackFormatError(
                f"Content-Length {length} outside (0, "
                f"{MAX_SUBMIT_BYTES}]")
        # Backpressure at HEADERS time: when the queue is full or
        # draining, reject before buffering the (~95 MB at 1080p)
        # body — N overloaded connections must cost N sockets, not
        # N stacks of transient RSS. submit_array/submit_session_stop
        # below remain the authoritative (race-free) gates. Advisory by
        # design: a duplicate submit the content cache could answer is
        # sometimes refused here — the cache cannot be consulted before
        # the body exists.
        try:
            self.service.check_admission(
                _PRIORITY_NAMES.get(
                    self.headers.get("X-Priority", "normal"), 1),
                tenant=self._tenant())
        except JobRejected:
            self.close_connection = True
            raise
        body = self.rfile.read(length)
        return np.load(io.BytesIO(body), allow_pickle=False)

    def _tenant(self) -> str | None:
        return self.headers.get("X-Tenant")

    def _read_json_body(self) -> dict:
        """Small JSON POST body ({} when absent)."""
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return {}
        if length > (1 << 20):
            self.close_connection = True
            raise StackFormatError(f"JSON body too large ({length} B)")
        body = self.rfile.read(length)
        try:
            out = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            raise StackFormatError("body must be a JSON object")
        if not isinstance(out, dict):
            raise StackFormatError("body must be a JSON object")
        return out

    def do_POST(self):
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if url.path == "/submit":
                stack = self._read_stack_body()
                deadline = self.headers.get("X-Deadline-S")
                job = self.service.submit_array(
                    stack,
                    result_format=self.headers.get("X-Result-Format",
                                                   "ply"),
                    priority=self.headers.get("X-Priority", "normal"),
                    deadline_s=float(deadline) if deadline else None,
                    tenant=self._tenant())
                self._json({"job_id": job.job_id, "status": job.status})
            elif parts and parts[0] == "session":
                self._post_session(parts)
            else:
                self.close_connection = True
                self._json({"error": "not found"}, 404,
                           headers=(("Connection", "close"),))
        except JobRejected as e:
            self._reject(e)
        except UnknownSessionError as e:
            self._json({"error": {"type": type(e).__name__,
                                  "message": str(e)}}, 404)
        except Exception as e:
            # Undecodable body, bad header values, … — client-side
            # errors. The body may not have been read (e.g. a garbage
            # Content-Length header throws before rfile.read), so this
            # path closes the connection like the other early errors.
            self.close_connection = True
            self._json({"error": {"type": type(e).__name__,
                                  "message": str(e)}}, 400,
                       headers=(("Connection", "close"),))

    def _post_session(self, parts: list[str]) -> None:
        """POST /session | /session/<id>/stop | /session/<id>/finalize
        (docs/STREAMING.md)."""
        if len(parts) == 1:
            out = self.service.create_session(self._read_json_body(),
                                              tenant=self._tenant())
            self._json(out)
        elif len(parts) == 3 and parts[2] == "stop":
            stack = self._read_stack_body()
            job = self.service.submit_session_stop(parts[1], stack,
                                                   tenant=self._tenant())
            self._json({"job_id": job.job_id, "status": job.status,
                        "session_id": parts[1]})
        elif len(parts) == 3 and parts[2] == "adopt":
            # Fleet handoff (docs/SERVING.md § fleet): take over a live
            # session from the shared stream. 404 when no stream exists,
            # 409 when adoption cannot proceed (e.g. session registry
            # full) — the router tries the next survivor.
            try:
                out = self.service.adopt_session(parts[1])
            except (JobRejected, UnknownSessionError):
                raise
            except Exception as e:
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 409)
                return
            self._json(out)
        elif len(parts) == 3 and parts[2] == "finalize":
            from .sessions import SessionResultEvicted

            body = self._read_json_body()
            try:
                job = self.service.finalize_session(
                    parts[1], body.get("result_format", "stl"))
            except (JobRejected, UnknownSessionError):
                raise
            except SessionResultEvicted as e:
                # The one-shot result-eviction semantics (HTTP 410):
                # finalize happened, the artifact is gone for good.
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 410)
                return
            except Exception as e:
                # A finalize that cannot proceed (too few fused stops,
                # meshing failure) is a client-visible conflict, not a
                # server error — the session stays usable.
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 409)
                return
            self._json({"job_id": job.job_id, "status": job.status,
                        "result": dict(job.result_meta)})
        else:
            self.close_connection = True
            self._json({"error": "not found"}, 404,
                       headers=(("Connection", "close"),))

    def do_GET(self):
        url = urlparse(self.path)
        if url.path == "/healthz":
            # LIVENESS: the process is up and answering — always 200.
            # Routing decisions belong to /readyz; if this endpoint went
            # 503 during a graceful drain, an orchestrator probing it
            # for liveness would kill the pod mid-drain.
            self._json({"ok": True, **self.service.stats()})
        elif url.path == "/readyz":
            # READINESS: 503 until warmup + recovery complete, while no
            # worker lane is alive, and during drain — the router's
            # send-traffic-here signal (docs/SERVING.md deployment
            # recipe).
            ready = self.service.readiness()
            self._json(ready, 200 if ready["ready"] else 503)
        elif url.path == "/metrics":
            data = self.service.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif url.path == "/events":
            q = parse_qs(url.query)
            try:
                n = int((q.get("n") or ["256"])[0])
            except ValueError:
                n = 256
            kind = (q.get("kind") or [None])[0]
            data = self.service.events_jsonl(max(1, n),
                                             kind=kind).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/x-ndjson; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif url.path.startswith("/cache/"):
            # Peer protocol (serve/fleet.py): export one LOCAL content-
            # cache artifact to a fleet peer. Served even while draining
            # (a free answer for a peer costs nothing and 404s would
            # look like misses).
            key = url.path[len("/cache/"):]
            out = None
            if len(key) == 64 and all(c in "0123456789abcdef"
                                      for c in key):
                out = self.service.cache_export(key)
            if out is None:
                self._json({"error": "no such artifact"}, 404)
            else:
                payload, meta, fmt = out
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("X-Content-Format", fmt)
                self.send_header("X-Content-Meta", json.dumps(meta))
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
        elif url.path == "/status":
            job_id = (parse_qs(url.query).get("id") or [""])[0]
            status = self.service.status(job_id)
            if status is None:
                self._json({"error": f"unknown job {job_id!r}"}, 404)
            else:
                self._json(status)
        elif url.path == "/result":
            self._result((parse_qs(url.query).get("id") or [""])[0])
        elif url.path.startswith("/session/"):
            self._get_session([p for p in url.path.split("/") if p],
                              parse_qs(url.query))
        else:
            self._json({"error": "not found"}, 404)

    def _get_session(self, parts: list[str], query=None) -> None:
        """GET /session/<id> (status) | /session/<id>/preview (latest
        progressive STL) | /session/<id>/render?az=..&el=.. (splat
        novel view PNG) | /session/<id>/splats (scene .npz)."""
        query = query or {}
        try:
            if len(parts) == 2:
                self._json(self.service.sessions.get(
                    parts[1]).status_dict())
            elif len(parts) == 3 and parts[2] == "render":
                self._session_render(parts[1], query)
            elif len(parts) == 3 and parts[2] == "splats":
                data = self.service.session_splats(parts[1])
                if data is None:
                    self._json({"session_id": parts[1],
                                "error": "no splat scene yet (submit a "
                                         "stop first)"}, 409)
                    return
                self._bytes(data, "application/octet-stream")
            elif len(parts) == 3 and parts[2] == "preview":
                out = self.service.session_preview(parts[1])
                if out is None:
                    self._json({"session_id": parts[1],
                                "error": "no preview yet (submit a "
                                         "stop first)"}, 409)
                    return
                data, meta = out
                self.send_response(200)
                self.send_header("Content-Type", _CONTENT_TYPES["stl"])
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Preview-Stop", str(meta.get("stop")))
                self.send_header("X-Preview-Faces",
                                 str(meta.get("faces")))
                self.send_header("X-Stops-Fused",
                                 str(meta.get("stops_fused")))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._json({"error": "not found"}, 404)
        except UnknownSessionError as e:
            self._json({"error": {"type": type(e).__name__,
                                  "message": str(e)}}, 404)
        except JobRejected as e:
            # Render-surface refusals (no splat lane, off-menu size,
            # out-of-range angles) — client errors, not conflicts.
            self._reject(e)

    def _session_render(self, session_id: str, query: dict) -> None:
        """GET /session/<id>/render: az/el floats (defaults 30/20), an
        optional configured w×h. 400 on malformed/out-of-range values,
        409 before the first fused stop."""
        def num(name, default):
            raw = (query.get(name) or [None])[0]
            if raw is None:
                return default
            try:
                val = float(raw)
            except ValueError:
                raise StackFormatError(
                    f"query param {name!r} must be a number, "
                    f"got {raw!r}")
            if not np.isfinite(val):
                # 'nan'/'inf' PARSE as floats but int() on them raises
                # past the 400 mapping — reject them as the client
                # errors they are.
                raise StackFormatError(
                    f"query param {name!r} must be finite, got {raw!r}")
            return val

        def whole(name):
            val = num(name, None)
            if val is not None and val != int(val):
                # Truncating 384.9 → 384 would 200 at a size the
                # client did not ask for — the endpoint's strict-400
                # posture applies to fractional sizes too.
                raise StackFormatError(
                    f"query param {name!r} must be an integer, "
                    f"got {val!r}")
            return val

        azim = num("az", 30.0)
        elev = num("el", 20.0)
        w = whole("w")
        h = whole("h")
        out = self.service.render_session(
            session_id, azim, elev,
            None if w is None else int(w),
            None if h is None else int(h))
        if out is None:
            self._json({"session_id": session_id,
                        "error": "no splat scene yet (submit a stop "
                                 "first)"}, 409)
            return
        data, meta = out
        self.send_response(200)
        self.send_header("Content-Type", _CONTENT_TYPES["render_png"])
        self.send_header("Content-Length", str(len(data)))
        self.send_header("X-Render-Splats", str(meta.get("splats")))
        self.send_header("X-Render-Seconds", str(meta.get("render_s")))
        self.end_headers()
        self.wfile.write(data)

    def do_DELETE(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) == 2 and parts[0] == "session":
            try:
                self.service.sessions.delete(parts[1])
            except UnknownSessionError as e:
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 404)
                return
            self._json({"session_id": parts[1], "deleted": True})
        else:
            self._json({"error": "not found"}, 404)

    def _result(self, job_id: str):
        job = self.service.get_job(job_id)
        if job is None:
            self._json({"error": f"unknown job {job_id!r}"}, 404)
        elif job.status == DONE:
            # Registry payload, or the content-hash cache when the byte
            # budget evicted it — 410 only when both are gone.
            data = self.service.result_payload(job)
            if data is None:
                self._json({"job_id": job_id, "status": job.status,
                            "error": "result evicted from the bounded "
                                     "result cache; resubmit the scan",
                            "result": dict(job.result_meta)}, 410)
            else:
                self._bytes(data, _CONTENT_TYPES[job.result_format])
        elif job.status == FAILED:
            self._json(job.status_dict(), 409)
        else:
            self._json({"job_id": job_id, "status": job.status,
                        "error": "result not ready"}, 409)

    def log_message(self, fmt, *args):  # per-request noise → debug log
        log.debug("http: " + fmt, *args)


class ServeHTTPServer:
    """Owns the listener thread (mirrors `hw/command_server.CommandServer`)."""

    def __init__(self, service: ReconstructionService,
                 host: str = "127.0.0.1", port: int = 0):
        self.service = service
        handler = type("BoundServeHandler", (_ServeHandler,),
                       {"service": service})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serve-http", daemon=True)
        self._started = False

    def start(self) -> "ServeHTTPServer":
        self._thread.start()
        self._started = True
        log.info("reconstruction service on :%d", self.port)
        return self

    def stop(self) -> None:
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()
