"""Service composition + stdlib HTTP front end.

:class:`ReconstructionService` wires queue → batcher → program cache →
device workers into one lifecycle (start / serve / drain) and owns the
job registry clients poll. :class:`ServeHTTPServer` is the transport: a
``ThreadingHTTPServer`` (same dependency posture as `hw/command_server.py`
— no web framework) exposing

========================  ==================================================
``POST /submit``           ``.npy`` capture stack body (+ ``X-*`` option
                           headers) → ``{"job_id": ...}``; 429 + Retry-After
                           on backpressure, 503 while draining, 400 on a
                           malformed stack
``GET /status?id=``        job lifecycle + taxonomy error payload
``GET /result?id=``        the PLY/STL bytes (409 until done)
``GET /healthz``           liveness + drain flag + worker/queue state
``GET /metrics``           Prometheus text: queue depth, batch-occupancy
                           histogram, program-cache stats, per-stage span
                           latencies (utils/trace), compile/device-memory
                           telemetry (utils/telemetry)
``GET /events?n=``         flight-recorder journal tail as JSONL
                           (utils/events; docs/OBSERVABILITY.md)
========================  ==================================================

The HTTP layer holds no state of its own — every handler delegates to the
service object, so in-process callers (tests, bench) and HTTP clients see
identical semantics.
"""

from __future__ import annotations

import dataclasses
import io
import json
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..config import DecodeConfig, ProjectorConfig, TriangulationConfig
from ..health import QualityGates
from ..stream import StreamParams
from ..utils import events, telemetry, trace
from ..utils.log import get_logger
from .batcher import BucketBatcher, BucketKey
from .cache import ProgramCache
from .jobs import (
    DONE,
    FAILED,
    AdmissionQueue,
    Job,
    JobRejected,
    StackFormatError,
    error_payload,
)
from .sessions import SessionManager, UnknownSessionError
from .worker import DeviceWorker

log = get_logger(__name__)

_PRIORITY_NAMES = {"high": 0, "normal": 1, "low": 2}
_CONTENT_TYPES = {"ply": "application/x-ply",
                  "stl": "model/stl",
                  "json": "application/json"}  # session-stop payloads


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Service tuning surface (docs/SERVING.md has the tuning guide)."""

    proj: ProjectorConfig = ProjectorConfig()
    decode_cfg: DecodeConfig = DecodeConfig()
    tri_cfg: TriangulationConfig = TriangulationConfig()
    gates: QualityGates = QualityGates()

    queue_depth: int = 64          # bounded admission (backpressure above)
    linger_ms: float = 10.0        # max wait for batch company
    workers: int = 1               # device launch lanes
    buckets: tuple = ((1080, 1920),)   # padded (H, W) shapes
    batch_sizes: tuple = (1, 2, 4, 8)
    max_cache_entries: int = 32
    warmup: bool = True            # precompile buckets × batch sizes
    mesh_depth: int = 7            # STL results: Poisson depth
    completed_cap: int = 256       # terminal jobs kept for /status///result
    # Byte budget for retained result payloads (a 1080p PLY is ~30 MB —
    # 256 of those would pin ~8 GB; the count cap alone doesn't bound
    # memory). Oldest terminal jobs are evicted past EITHER cap.
    result_cache_bytes: int = 512 << 20
    # Compile/memory telemetry (docs/OBSERVABILITY.md): sl_compile_total,
    # sl_compile_seconds, device-memory gauges and the recompile-storm
    # detector on this service's /metrics.
    telemetry: bool = True
    # Streaming sessions (docs/STREAMING.md): per-session incremental
    # fusion defaults and the bounded live-session cap. Per-session
    # overrides are limited to the non-compiling surface
    # (`sessions.SESSION_OPTION_KEYS`).
    stream: StreamParams = StreamParams()
    max_sessions: int = 8
    # Idle expiry for sessions (live AND finalized): a crashed client's
    # abandoned session frees its slot + model buffers after this.
    session_ttl_s: float = 3600.0


def synthetic_calib_provider(proj: ProjectorConfig):
    """Per-bucket synthetic rig calibration (the no-hardware default —
    the same `models/synthetic.default_calibration` geometry the bench
    and tests use). Memoized per (H, W): Calibration arrays live on
    device and are shared by every batch of that bucket."""
    from ..models import synthetic
    from ..ops.triangulate import make_calibration

    lock = threading.Lock()
    cache: dict = {}

    def provider(height: int, width: int):
        with lock:
            calib = cache.get((height, width))
        if calib is not None:
            return calib
        cam_K, proj_K, R, T = synthetic.default_calibration(
            height, width, proj)
        calib = make_calibration(cam_K, proj_K, R, T, height, width,
                                 proj_width=proj.width,
                                 proj_height=proj.height)
        with lock:
            cache[(height, width)] = calib
        return calib

    return provider


def fixed_calib_provider(calib):
    """Single-rig provider from a loaded calibration (``--calib`` .mat):
    only the bucket matching its camera geometry is servable."""
    h, w = int(calib.Nc.shape[0]), int(calib.Nc.shape[1])

    def provider(height: int, width: int):
        if (height, width) != (h, w):
            raise StackFormatError(
                f"service calibration is {h}x{w}; bucket "
                f"{height}x{width} has no calibration")
        return calib

    return provider


class ReconstructionService:
    """Queue → batcher → cache → workers, one lifecycle, one job registry."""

    def __init__(self, config: ServeConfig = ServeConfig(),
                 calib_provider=None,
                 registry: "trace.MetricsRegistry | None" = None,
                 tracer: "trace.Tracer | None" = None):
        self.config = config
        # Fresh registry per service by default: parallel services (tests,
        # bench sweeps) must not sum each other's counters. Pass
        # trace.REGISTRY explicitly to meter into the process-global one.
        self.registry = registry if registry is not None \
            else trace.MetricsRegistry()
        self.tracer = tracer if tracer is not None else trace.GLOBAL
        self.queue = AdmissionQueue(max_depth=config.queue_depth)
        self.batcher = BucketBatcher(
            self.queue, buckets=config.buckets,
            batch_sizes=config.batch_sizes,
            linger_s=config.linger_ms / 1e3)
        self.calib_provider = (calib_provider if calib_provider is not None
                               else synthetic_calib_provider(config.proj))
        self.cache = ProgramCache(self.calib_provider,
                                  max_entries=config.max_cache_entries,
                                  registry=self.registry)
        self.workers = [
            DeviceWorker(self.batcher, self.cache, gates=config.gates,
                         mesh_depth=config.mesh_depth,
                         registry=self.registry, tracer=self.tracer,
                         name=f"serve-worker-{i}")
            for i in range(max(1, config.workers))]
        self._jobs_lock = threading.Lock()
        self._jobs: OrderedDict[str, Job] = OrderedDict()
        self._draining = False
        self._started = False
        self._jobs_total = lambda status: self.registry.counter(
            "serve_jobs_total", "jobs by admission/terminal status",
            status=status)
        self._queue_gauge = self.registry.gauge(
            "serve_queue_depth", "jobs waiting in the admission queue")
        # Per-job latency histograms: seconds-valued, so they take the
        # explicit latency bucket layout (the occupancy-shaped Histogram
        # default would bin every sub-second wait into `le="1"`).
        self._queue_wait_s = self.registry.histogram(
            "serve_job_queue_wait_seconds",
            "submit-to-start wait per job",
            buckets=trace.LATENCY_SECONDS_BUCKETS)
        self._run_s = self.registry.histogram(
            "serve_job_run_seconds", "start-to-terminal time per job",
            buckets=trace.LATENCY_SECONDS_BUCKETS)
        # Constructed here (its counter families must exist in the
        # registry from the first scrape) but installed into the compile-
        # event dispatch only for the start→drain window, so an abandoned
        # or failed service never keeps receiving process-wide events.
        self.telemetry: "telemetry.DeviceTelemetry | None" = (
            telemetry.DeviceTelemetry(registry=self.registry)
            if config.telemetry else None)
        self._events_seen: dict[str, int] = {}  # _sync_event_counters
        self._events_seen_lock = threading.Lock()
        self._warmup_report: dict = {}
        self.sessions = SessionManager(
            config.stream, config.proj, config.decode_cfg, config.tri_cfg,
            max_sessions=config.max_sessions,
            session_ttl_s=config.session_ttl_s)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReconstructionService":
        if self.telemetry is not None:
            self.telemetry.install()   # before warmup: count its compiles
        try:
            if self.config.warmup:
                keys = [self._bucket_key(h, w)
                        for h, w in self.config.buckets]
                t0 = time.monotonic()
                self._warmup_report = self.cache.warmup(
                    keys, self.config.batch_sizes)
                log.info("warmup: %d programs in %.1fs",
                         len(self._warmup_report), time.monotonic() - t0)
        except BaseException:
            if self.telemetry is not None:
                self.telemetry.uninstall()
            raise
        for w in self.workers:
            w.start()
        self._started = True
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: refuse new work, finish everything admitted,
        stop workers. Returns True when every worker exited in time."""
        self._draining = True
        self.queue.close()
        for w in self.workers:
            w.request_stop()
        deadline = time.monotonic() + timeout
        ok = True
        for w in self.workers:
            w.join(max(0.0, deadline - time.monotonic()))
            ok = ok and not w.alive
        if not ok:
            log.warning("drain timed out after %.1fs with workers alive",
                        timeout)
        if self.telemetry is not None:
            self.telemetry.uninstall()
        return ok

    @property
    def draining(self) -> bool:
        return self._draining

    def _bucket_key(self, h: int, w: int) -> BucketKey:
        cfg = self.config
        return BucketKey(height=h, width=w, frames=cfg.proj.n_frames,
                         col_bits=cfg.proj.col_bits,
                         row_bits=cfg.proj.row_bits,
                         decode_cfg=cfg.decode_cfg, tri_cfg=cfg.tri_cfg)

    # -- submission --------------------------------------------------------

    def submit_array(self, stack: np.ndarray, result_format: str = "ply",
                     priority="normal",
                     deadline_s: float | None = None) -> Job:
        """Validate + admit one capture stack; returns the live Job.
        Raises a :class:`~.jobs.JobRejected` subclass on refusal."""
        cfg = self.config
        try:
            stack = self._validate_stack(stack)
            if result_format not in _CONTENT_TYPES:
                raise StackFormatError(
                    f"result_format must be one of "
                    f"{sorted(_CONTENT_TYPES)}, got {result_format!r}")
            if isinstance(priority, str):
                if priority not in _PRIORITY_NAMES:
                    raise StackFormatError(
                        f"priority must be one of "
                        f"{sorted(_PRIORITY_NAMES)} or an int, "
                        f"got {priority!r}")
                priority = _PRIORITY_NAMES[priority]
            job = Job(stack=stack, col_bits=cfg.proj.col_bits,
                      row_bits=cfg.proj.row_bits,
                      decode_cfg=cfg.decode_cfg, tri_cfg=cfg.tri_cfg,
                      result_format=result_format,
                      priority=int(priority), deadline_s=deadline_s)
            # Observer BEFORE admission (a worker may finish the job
            # before _register runs); registry entry AFTER admission (a
            # rejected job must leave no trace — a pre-registered one
            # would sit QUEUED forever, pinning its stack, unbounded
            # growth under the exact overload the bounded queue exists
            # for).
            job.on_terminal = self._on_terminal
            self.queue.submit(job)
            self._register(job)
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise
        self._jobs_total("submitted").inc()
        self._queue_gauge.set(self.queue.depth())
        return job

    def _validate_stack(self, stack: np.ndarray) -> np.ndarray:
        cfg = self.config
        stack = np.asarray(stack)
        if stack.dtype != np.uint8:
            raise StackFormatError(
                f"stack must be uint8, got {stack.dtype}")
        if stack.ndim != 3:
            raise StackFormatError(
                f"stack must be (frames, H, W), got shape {stack.shape}")
        f, h, w = stack.shape
        if f != cfg.proj.n_frames:
            raise StackFormatError(
                f"stack has {f} frames; this service's protocol is "
                f"{cfg.proj.n_frames} (2 + 2x{cfg.proj.col_bits} + "
                f"2x{cfg.proj.row_bits})")
        # Must fit SOME configured bucket (per-axis maxima are not
        # enough: a stack under both maxima but inside no single bucket
        # would otherwise fail late in the worker — or trigger a
        # request-time compile of an off-menu quantum bucket).
        if h < 8 or w < 8 or not any(h <= bh and w <= bw
                                     for bh, bw in cfg.buckets):
            raise StackFormatError(
                f"frame size {h}x{w} fits no configured bucket "
                f"{list(cfg.buckets)} (min 8x8)")
        return stack

    # -- streaming sessions (docs/STREAMING.md) ----------------------------

    def create_session(self, options: dict | None = None) -> dict:
        """``POST /session``: open a streaming session. Refused while
        draining (same rule as submissions) or past ``max_sessions``."""
        if self._draining:
            from .jobs import QueueClosedError

            self._jobs_total("rejected").inc()
            raise QueueClosedError()
        try:
            entry = self.sessions.create(options)
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise
        return {"session_id": entry.session_id,
                "scan_id": entry.session.scan_id}

    def submit_session_stop(self, session_id: str,
                            stack: np.ndarray) -> Job:
        """``POST /session/<id>/stop``: admit one stop through the SAME
        queue → batcher → program-cache lane as one-shot jobs; the
        decoded arrays are handed to the session instead of a writer.
        Returns the live Job (its meta carries the fuse/skip decision)."""
        entry = self.sessions.get(session_id)
        cfg = self.config
        try:
            stack = self._validate_stack(stack)
            job = Job(stack=stack, col_bits=cfg.proj.col_bits,
                      row_bits=cfg.proj.row_bits,
                      decode_cfg=cfg.decode_cfg, tri_cfg=cfg.tri_cfg,
                      result_format="json")
            job.decode_sink = entry.ingest
            job.on_terminal = self._on_terminal
            self.queue.submit(job)
            self._register(job)
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise
        entry.note_pending(job)
        with entry.lock:
            entry.stops_submitted += 1
        self._jobs_total("submitted").inc()
        self._queue_gauge.set(self.queue.depth())
        return job

    def session_preview(self, session_id: str):
        """``GET /session/<id>/preview``: latest progressive STL bytes +
        meta, or None before the first preview."""
        return self.sessions.get(session_id).preview_bytes()

    def finalize_session(self, session_id: str,
                         result_format: str = "stl") -> Job:
        """``POST /session/<id>/finalize``: close the ring, build the
        final artifact, and land it as a terminal job in the ordinary
        registry — the existing ``GET /result`` path serves it. Runs on
        the calling thread (one full pose solve + merge + mesh)."""
        if result_format not in ("ply", "stl"):
            raise StackFormatError(
                f"result_format must be 'ply' or 'stl', "
                f"got {result_format!r}")
        entry = self.sessions.get(session_id)
        cfg = self.config
        # Settle in-flight stops FIRST (without the session lock — their
        # sinks need it): a stop the client already got a 200 for must
        # be fused or journaled before the ring closes. A stop that
        # cannot settle inside the timeout surfaces as a 409 from the
        # session's own guards rather than a silent exclusion.
        entry.settle_pending(timeout_s=120.0)
        with entry.lock:
            if entry.result_job_id is not None:
                job = self.get_job(entry.result_job_id)
                if job is not None:
                    return job  # idempotent finalize
                from .sessions import SessionResultEvicted

                raise SessionResultEvicted(
                    f"session {session_id} finalized but its result "
                    "job fell out of the bounded registry — the "
                    "artifact is gone; re-scan")
            result = entry.session.finalize(mesh=result_format == "stl")
            if result_format == "stl":
                from .worker import _stl_bytes

                payload = _stl_bytes(result.mesh)
                meta = {"vertices": int(len(result.mesh.vertices)),
                        "faces": int(len(result.mesh.faces))}
            else:
                from .worker import _ply_bytes

                payload = _ply_bytes(result.cloud)
                meta = {}
            meta.update(points=len(result.cloud),
                        stops_fused=result.stats["stops_fused"],
                        stops_skipped=result.stats["stops_skipped"])
            job = Job(stack=np.empty((0, 0, 0), np.uint8),
                      col_bits=cfg.proj.col_bits,
                      row_bits=cfg.proj.row_bits,
                      result_format=result_format)
            job.on_terminal = self._on_terminal
            self._jobs_total("submitted").inc()  # counter conservation
            job.complete(payload, **meta)
            self._register(job)
            entry.result_job_id = job.job_id
        return job

    def check_admission(self) -> None:
        """Headers-time backpressure probe for the HTTP layer: raises the
        rejection `submit_array` would, AND counts it — a refusal must hit
        the rejected counter whether it happened before or after the body
        was read."""
        try:
            self.queue.check_admission()
        except JobRejected:
            self._jobs_total("rejected").inc()
            raise

    def _on_terminal(self, job: Job) -> None:
        """Counter conservation: every admitted job ends exactly one of
        done/failed (rejected jobs are counted at submit), wherever the
        terminal transition happened — worker postprocess, batch-scoped
        failure, or deadline scrub in the queue/batcher."""
        self._jobs_total("done" if job.status == DONE else "failed").inc()
        wait_end = job.started_t or job.finished_t
        if wait_end is not None:
            self._queue_wait_s.observe(wait_end - job.submitted_t)
        if job.started_t is not None and job.finished_t is not None:
            self._run_s.observe(job.finished_t - job.started_t)
        events.record("job_terminal",
                      severity="info" if job.status == DONE else "warning",
                      job_id=job.job_id, status=job.status,
                      exc_type=(job.error or {}).get("type"))

    def _register(self, job: Job) -> None:
        with self._jobs_lock:
            self._jobs[job.job_id] = job
            # Bound the registry two ways (live jobs are never touched —
            # a client could still be polling them):
            # count cap — drop the oldest terminal ENTRIES entirely;
            terminal = [(jid, j) for jid, j in self._jobs.items()
                        if j.status in (DONE, FAILED)]
            excess = len(self._jobs) - self.config.completed_cap
            for jid, _ in terminal[:max(0, excess)]:
                del self._jobs[jid]
            # byte budget — drop only the oldest result PAYLOADS. The
            # entries stay, so a client that saw "done" and comes late
            # gets an explicit 410 ("result evicted"), never a silent
            # unknown-job 404.
            kept = [j for _, j in terminal[max(0, excess):]]
            held = sum(len(j.result_bytes) for j in kept
                       if j.result_bytes is not None)
            for j in kept:
                if held <= self.config.result_cache_bytes:
                    break
                held -= j.release_result()

    # -- inspection --------------------------------------------------------

    def get_job(self, job_id: str) -> Job | None:
        with self._jobs_lock:
            return self._jobs.get(job_id)

    def status(self, job_id: str) -> dict | None:
        job = self.get_job(job_id)
        if job is None:
            return None
        out = job.status_dict()
        # Terminal counters are registered at observation time (cheap,
        # idempotent-per-scrape is fine for these dashboards).
        return out

    def stats(self) -> dict:
        return {
            "queue_depth": self.queue.depth(),
            "pending_batches": self.batcher.pending_depth(),
            "draining": self._draining,
            "workers_alive": sum(w.alive for w in self.workers),
            "cache": self.cache.stats(),
            "warmup": self._warmup_report,
            "sessions": self.sessions.stats(),
        }

    def metrics_text(self) -> str:
        self._queue_gauge.set(self.queue.depth())
        if self.telemetry is not None:
            self.telemetry.sample_memory()  # refresh device gauges
        self._sync_event_counters()
        return self.registry.prometheus_text(tracer=self.tracer)

    def _sync_event_counters(self) -> None:
        """Mirror the process flight recorder's severity tallies onto
        THIS service's registry at scrape time — the recorder is
        process-global and counts into trace.REGISTRY, which a service
        with a private registry (the default) never renders. Deltas keep
        the counters monotonic across scrapes; the lock keeps concurrent
        scrapes (ThreadingHTTPServer) from double-applying a delta. When
        the service IS handed the global registry, the recorder already
        counts there — mirroring would double every event."""
        if self.registry is trace.REGISTRY:
            return
        with self._events_seen_lock:
            for sev, total in events.RECORDER.severity_counts().items():
                seen = self._events_seen.get(sev, 0)
                if total > seen:
                    self.registry.counter(
                        "sl_events_total",
                        "flight-recorder events by severity",
                        severity=sev).inc(total - seen)
                    self._events_seen[sev] = total

    def events_jsonl(self, n: int = 256) -> str:
        """Tail of the process flight journal (GET /events): the ordered,
        correlated record of what recently happened to which job."""
        return events.to_jsonl(n)


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


MAX_SUBMIT_BYTES = 1 << 30  # absolute transport bound; admission is tighter


class _ServeHandler(BaseHTTPRequestHandler):
    service: ReconstructionService  # bound by ServeHTTPServer

    protocol_version = "HTTP/1.1"
    # Socket timeout: a stalled upload or idle keep-alive connection must
    # not pin its handler thread forever — without this, N dead-slow
    # clients hold N threads with the admission queue's 429 never
    # engaging (the request never completes).
    timeout = 120.0

    def _json(self, obj, status=200, headers=()):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _bytes(self, data: bytes, content_type: str):
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # ------------------------------------------------------------------

    def _reject(self, e: JobRejected) -> None:
        """JobRejected → response mapping shared by every POST route."""
        payload = error_payload(e)
        retry = payload.get("retry_after_s")
        status = 400
        headers = []
        if e.retryable:
            status = 503 if retry is None else 429
            if retry is not None:
                headers.append(("Retry-After", str(max(1, round(retry)))))
        if self.close_connection:  # body was never read (length gate)
            headers.append(("Connection", "close"))
        self._json({"error": payload}, status, headers)

    def _read_stack_body(self):
        """Read + decode an ``.npy`` POST body behind the headers-time
        gates (length bound, queue backpressure) — the early-error paths
        respond WITHOUT reading the (possibly ~95 MB) body; under
        HTTP/1.1 keep-alive the unread bytes would desync the next
        request on the connection, so those paths close it."""
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0 or length > MAX_SUBMIT_BYTES:
            self.close_connection = True
            # Counted here because this refusal never reaches the
            # service's own counting gates (check_admission /
            # submit_array) — transport-level refusals must hit the
            # rejected counter too.
            self.service._jobs_total("rejected").inc()
            raise StackFormatError(
                f"Content-Length {length} outside (0, "
                f"{MAX_SUBMIT_BYTES}]")
        # Backpressure at HEADERS time: when the queue is full or
        # draining, reject before buffering the (~95 MB at 1080p)
        # body — N overloaded connections must cost N sockets, not
        # N stacks of transient RSS. submit_array/submit_session_stop
        # below remain the authoritative (race-free) gates.
        try:
            self.service.check_admission()
        except JobRejected:
            self.close_connection = True
            raise
        body = self.rfile.read(length)
        return np.load(io.BytesIO(body), allow_pickle=False)

    def _read_json_body(self) -> dict:
        """Small JSON POST body ({} when absent)."""
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return {}
        if length > (1 << 20):
            self.close_connection = True
            raise StackFormatError(f"JSON body too large ({length} B)")
        body = self.rfile.read(length)
        try:
            out = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            raise StackFormatError("body must be a JSON object")
        if not isinstance(out, dict):
            raise StackFormatError("body must be a JSON object")
        return out

    def do_POST(self):
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if url.path == "/submit":
                stack = self._read_stack_body()
                deadline = self.headers.get("X-Deadline-S")
                job = self.service.submit_array(
                    stack,
                    result_format=self.headers.get("X-Result-Format",
                                                   "ply"),
                    priority=self.headers.get("X-Priority", "normal"),
                    deadline_s=float(deadline) if deadline else None)
                self._json({"job_id": job.job_id, "status": job.status})
            elif parts and parts[0] == "session":
                self._post_session(parts)
            else:
                self.close_connection = True
                self._json({"error": "not found"}, 404,
                           headers=(("Connection", "close"),))
        except JobRejected as e:
            self._reject(e)
        except UnknownSessionError as e:
            self._json({"error": {"type": type(e).__name__,
                                  "message": str(e)}}, 404)
        except Exception as e:
            # Undecodable body, bad header values, … — client-side
            # errors. The body may not have been read (e.g. a garbage
            # Content-Length header throws before rfile.read), so this
            # path closes the connection like the other early errors.
            self.close_connection = True
            self._json({"error": {"type": type(e).__name__,
                                  "message": str(e)}}, 400,
                       headers=(("Connection", "close"),))

    def _post_session(self, parts: list[str]) -> None:
        """POST /session | /session/<id>/stop | /session/<id>/finalize
        (docs/STREAMING.md)."""
        if len(parts) == 1:
            out = self.service.create_session(self._read_json_body())
            self._json(out)
        elif len(parts) == 3 and parts[2] == "stop":
            stack = self._read_stack_body()
            job = self.service.submit_session_stop(parts[1], stack)
            self._json({"job_id": job.job_id, "status": job.status,
                        "session_id": parts[1]})
        elif len(parts) == 3 and parts[2] == "finalize":
            from .sessions import SessionResultEvicted

            body = self._read_json_body()
            try:
                job = self.service.finalize_session(
                    parts[1], body.get("result_format", "stl"))
            except (JobRejected, UnknownSessionError):
                raise
            except SessionResultEvicted as e:
                # The one-shot result-eviction semantics (HTTP 410):
                # finalize happened, the artifact is gone for good.
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 410)
                return
            except Exception as e:
                # A finalize that cannot proceed (too few fused stops,
                # meshing failure) is a client-visible conflict, not a
                # server error — the session stays usable.
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 409)
                return
            self._json({"job_id": job.job_id, "status": job.status,
                        "result": dict(job.result_meta)})
        else:
            self.close_connection = True
            self._json({"error": "not found"}, 404,
                       headers=(("Connection", "close"),))

    def do_GET(self):
        url = urlparse(self.path)
        if url.path == "/healthz":
            stats = self.service.stats()
            ok = stats["workers_alive"] > 0 and not stats["draining"]
            self._json({"ok": ok, **stats}, 200 if ok else 503)
        elif url.path == "/metrics":
            data = self.service.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif url.path == "/events":
            try:
                n = int((parse_qs(url.query).get("n") or ["256"])[0])
            except ValueError:
                n = 256
            data = self.service.events_jsonl(max(1, n)).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/x-ndjson; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif url.path == "/status":
            job_id = (parse_qs(url.query).get("id") or [""])[0]
            status = self.service.status(job_id)
            if status is None:
                self._json({"error": f"unknown job {job_id!r}"}, 404)
            else:
                self._json(status)
        elif url.path == "/result":
            self._result((parse_qs(url.query).get("id") or [""])[0])
        elif url.path.startswith("/session/"):
            self._get_session([p for p in url.path.split("/") if p])
        else:
            self._json({"error": "not found"}, 404)

    def _get_session(self, parts: list[str]) -> None:
        """GET /session/<id> (status) | /session/<id>/preview (latest
        progressive STL)."""
        try:
            if len(parts) == 2:
                self._json(self.service.sessions.get(
                    parts[1]).status_dict())
            elif len(parts) == 3 and parts[2] == "preview":
                out = self.service.session_preview(parts[1])
                if out is None:
                    self._json({"session_id": parts[1],
                                "error": "no preview yet (submit a "
                                         "stop first)"}, 409)
                    return
                data, meta = out
                self.send_response(200)
                self.send_header("Content-Type", _CONTENT_TYPES["stl"])
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Preview-Stop", str(meta.get("stop")))
                self.send_header("X-Preview-Faces",
                                 str(meta.get("faces")))
                self.send_header("X-Stops-Fused",
                                 str(meta.get("stops_fused")))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._json({"error": "not found"}, 404)
        except UnknownSessionError as e:
            self._json({"error": {"type": type(e).__name__,
                                  "message": str(e)}}, 404)

    def do_DELETE(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) == 2 and parts[0] == "session":
            try:
                self.service.sessions.delete(parts[1])
            except UnknownSessionError as e:
                self._json({"error": {"type": type(e).__name__,
                                      "message": str(e)}}, 404)
                return
            self._json({"session_id": parts[1], "deleted": True})
        else:
            self._json({"error": "not found"}, 404)

    def _result(self, job_id: str):
        job = self.service.get_job(job_id)
        if job is None:
            self._json({"error": f"unknown job {job_id!r}"}, 404)
        elif job.status == DONE:
            data = job.result_bytes
            if data is None:  # payload fell out of the byte budget
                self._json({"job_id": job_id, "status": job.status,
                            "error": "result evicted from the bounded "
                                     "result cache; resubmit the scan",
                            "result": dict(job.result_meta)}, 410)
            else:
                self._bytes(data, _CONTENT_TYPES[job.result_format])
        elif job.status == FAILED:
            self._json(job.status_dict(), 409)
        else:
            self._json({"job_id": job_id, "status": job.status,
                        "error": "result not ready"}, 409)

    def log_message(self, fmt, *args):  # per-request noise → debug log
        log.debug("http: " + fmt, *args)


class ServeHTTPServer:
    """Owns the listener thread (mirrors `hw/command_server.CommandServer`)."""

    def __init__(self, service: ReconstructionService,
                 host: str = "127.0.0.1", port: int = 0):
        self.service = service
        handler = type("BoundServeHandler", (_ServeHandler,),
                       {"service": service})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serve-http", daemon=True)
        self._started = False

    def start(self) -> "ServeHTTPServer":
        self._thread.start()
        self._started = True
        log.info("reconstruction service on :%d", self.port)
        return self

    def stop(self) -> None:
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()
