"""Device worker: batch launch, per-job postprocess, fault containment.

One worker owns one launch-at-a-time lane to the device: it asks the
batcher for a coalesced batch, fetches the AOT executable from the
program cache (a hit in steady state), launches, reads back, and
postprocesses each job independently. Failure containment follows the
PR-3 rule (health.py): a poisoned stack degrades ITS job — a
`StopQualityError` in that job's status payload — while batchmates
complete normally and the process keeps serving. Only genuinely
batch-scoped failures (the launch itself) fail the whole batch, and even
those never kill the worker loop.

Graceful drain: ``request_stop`` flips the loop into force-flush mode —
partial buckets launch immediately (linger is pointless when no more
work is coming) — and the thread exits once batcher and queue are empty.
"""

from __future__ import annotations

import io
import threading
import time

import numpy as np

from ..health import QualityGates, ScanFault, StopQualityError
from ..hw import faults as hwfaults
from ..io.ply import PointCloud, write_ply
from ..io.stl import write_stl
from ..utils import events, sanitize, trace
from ..utils.log import get_logger
from .batcher import Batch, BucketBatcher
from .cache import ProgramCache, ProgramKey
from .jobs import DONE, FAILED

log = get_logger(__name__)


class DeviceOutputError(ScanFault):
    """A launch SUCCEEDED but its valid-masked payload is non-finite.
    Ambiguous on one observation — a sick chip emitting garbage OR a
    degenerate stack tripping a decode corner — so attribution is
    DEFERRED to the cross-lane retry's verdict: clean on another lane
    convicts the chip (feeds LANE health, never the whole-service
    breaker — one NaN-emitting chip must degrade itself, not shed
    fleet admissions), a second NaN elsewhere convicts the data (the
    job fails with the historical per-job containment semantics, and
    no lane is blamed — a poisoned upload must not walk healthy
    devices to dead). Detected only under SL_SANITIZE on multi-device
    pools; single-device services keep the historical per-job
    assert_finite containment."""


def _ply_bytes(cloud: PointCloud) -> bytes:
    buf = io.BytesIO()
    write_ply(buf, cloud)
    return buf.getvalue()


def _stl_bytes(mesh) -> bytes:
    buf = io.BytesIO()
    write_stl(buf, mesh)
    return buf.getvalue()


def _mesh_ply_bytes(mesh) -> bytes:
    from ..io.ply import write_ply_mesh

    buf = io.BytesIO()
    write_ply_mesh(buf, mesh)
    return buf.getvalue()


class DeviceWorker:
    """Thread running the batch → launch → postprocess loop.

    With a lane pool (serve/lanes.py) each worker is PINNED to one
    device lane: batches stage onto that chip, programs come from the
    lane's per-device cache keys, and `next_batch(lane=…)` restricts the
    flush to free buckets plus this lane's sticky-session ones. Buckets
    past the pool's ``shard_min_pixels`` route to the sharded cross-chip
    program instead (one huge job spans chips rather than serializing on
    this lane).
    """

    def __init__(self, batcher: BucketBatcher, cache: ProgramCache,
                 gates: QualityGates = QualityGates(),
                 mesh_depth: int = 7,
                 registry: "trace.MetricsRegistry | None" = None,
                 tracer: "trace.Tracer | None" = None,
                 name: str = "serve-worker",
                 governor=None, mesh_representation: str = "poisson",
                 lane=None, lane_pool=None, fault_injector=None):
        self.batcher = batcher
        self.cache = cache
        self.gates = gates
        self.mesh_depth = mesh_depth
        self.mesh_representation = mesh_representation
        self.registry = registry if registry is not None else trace.REGISTRY
        self.tracer = tracer if tracer is not None else trace.GLOBAL
        # Overload governor (serve/governor.py): fed worker outcomes for
        # the circuit breaker; the watchdog reads the heartbeat below.
        self.governor = governor
        self.lane = lane                # DeviceLane | None
        self.lane_pool = lane_pool      # DeviceLanePool | None
        # Seeded device chaos (hw/faults.DeviceFaultInjector, armed via
        # SL_DEVICE_FAULTS): launches on this lane go through the
        # FaultyDevice shim. None in production.
        self.fault_injector = fault_injector
        self.name = name
        # Heartbeat: stamped every loop iteration. While the thread is
        # stuck inside a launch it goes stale — the watchdog's wedge
        # signal.
        self.last_beat = time.monotonic()
        self.abandoned = False  # set by the watchdog on replacement
        self._stop = threading.Event()
        self._abort = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._batches = self.registry.counter(
            "serve_batches_total", "batches launched")
        self._occupancy = self.registry.histogram(
            "serve_batch_occupancy", "real jobs per launched batch",
            buckets=(1, 2, 4, 8))
        self._padded = self.registry.counter(
            "serve_padded_slots_total",
            "batch slots filled with zero stacks to reach a bucketed size")
        # Per-lane visibility (docs/SERVING.md § multi-chip): which chip
        # did the work. Labeled by device so N workers sharing a chip
        # sum into one series; "default" = no lane pool (historical
        # single-device service).
        lane_label = self.lane.label if self.lane is not None else "default"
        self._lane_jobs = self.registry.counter(
            "serve_lane_jobs_total", "jobs completed per device lane",
            device=lane_label)
        self._lane_batches = self.registry.counter(
            "serve_lane_batches_total", "batches launched per device lane",
            device=lane_label)
        self._lane_occupancy = self.registry.histogram(
            "serve_lane_occupancy", "real jobs per batch, per device lane",
            buckets=(1, 2, 4, 8), device=lane_label)
        self._sharded_batches = self.registry.counter(
            "serve_sharded_batches_total",
            "batches dispatched through the cross-chip sharded tier")

    # ------------------------------------------------------------------

    def start(self) -> "DeviceWorker":
        self._thread.start()
        return self

    def request_stop(self) -> None:
        self._stop.set()

    def abort(self) -> None:
        """Crash-style stop: exit at the next loop iteration WITHOUT
        draining the queue or pending buckets (simulated kill -9 for the
        durability tests/bench — queued jobs stay non-terminal, exactly
        what the journal must recover)."""
        self._abort.set()

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    # ------------------------------------------------------------------

    def _run(self) -> None:
        while True:
            if self._abort.is_set():
                return
            self.last_beat = time.monotonic()
            draining = self._stop.is_set()
            batch = self.batcher.next_batch(
                timeout=0.05, force=draining,
                lane=self.lane.index if self.lane is not None else None)
            if batch is None:
                if draining and self.batcher.pending_depth() == 0 \
                        and self.batcher.queue.depth() == 0:
                    return
                continue
            try:
                contained = self._process(batch)
                # The breaker hears "ok" only for a batch with NO
                # service-side failures: a postprocess bug contained
                # per-job already fed note_worker_failure, and pairing
                # every such batch with an ok would pin the window's
                # failure rate at 50% no matter how broken the lane is.
                if self.governor is not None and not contained:
                    self.governor.note_worker_ok()
            except Exception as e:
                self._handle_batch_failure(batch, e)

    def _handle_batch_failure(self, batch: Batch, e: Exception) -> None:
        """Batch-scoped failure (compile, launch, transfer — or an
        injected/real device loss). Device-class faults feed LANE health
        (serve/lanes.py: healthy→suspect→dead escalation) and their jobs
        are RE-QUEUED onto a surviving lane instead of failed — a dead
        chip must cost latency, never acked work. Anything else keeps
        the historical containment: every job fails with the fault
        payload, and the governor's breaker counts it."""
        log.warning("batch %s failed: %s", batch.key.label(), e)
        key = getattr(batch, "program_key", None)
        sharded = key is not None and bool(key.shards)
        label = self.lane.label if self.lane is not None else None
        # Classify against THIS lane's platform ("cpu:0" → "cpu"), not
        # the process default backend — the right row of the device-loss
        # taxonomy in a heterogeneous pool.
        device_fault = (isinstance(e, DeviceOutputError)
                        or hwfaults.is_device_loss(
                            e, backend=label.split(":", 1)[0]
                            if label else None))
        events.record(
            "batch_failed", severity="error", message=str(e),
            program=batch.key.label(), exc_type=type(e).__name__,
            device=label or "default", device_fault=device_fault,
            sharded=sharded,
            jobs=",".join(j.job_id for j in batch.jobs))
        nan_fault = isinstance(e, DeviceOutputError)
        if device_fault and self.lane_pool is not None \
                and not nan_fault:
            if sharded:
                # A sharded program spans many chips and the launch
                # error cannot name WHICH mesh member died — blaming
                # the driving worker's own (healthy) lane would kill
                # the wrong chip. Instead the pool counts consecutive
                # faults per SPAN; at the threshold it fires the
                # service's probe-convict hook, which runs a tiny
                # program on each member and feeds mark_device_dead
                # with the actual casualty (docs/ROBUSTNESS.md §
                # probe-convict). The batch's jobs still retry below
                # and re-dispatch through whatever span route()
                # answers after the re-form.
                self.lane_pool.note_sharded_failure(
                    key.span or (), reason=type(e).__name__)
            elif label is not None:
                # NaN faults defer attribution further (below): the
                # fault could live in the DATA, and only the
                # cross-lane retry's outcome disambiguates.
                self.lane_pool.note_launch_failure(label,
                                                   reason="device_lost")
        failed = 0
        for job in batch.jobs:
            if device_fault and nan_fault and not sharded \
                    and getattr(job, "nan_lane", None) is not None:
                # Second NaN for this job, on a DIFFERENT lane: the
                # NaN follows the JOB, not the chip — a degenerate
                # stack tripping a decode/triangulate corner. Fail it
                # per the historical containment (below) and blame no
                # lane: without this, one poisoned upload retried a
                # few times would walk every healthy device to dead.
                pass
            elif device_fault and self._retry_cross_lane(
                    job, None if sharded else label):
                # Sharded faults exclude NO lane: the casualty is some
                # span member (the probe's verdict, maybe this worker's
                # own chip, maybe not) — excluding the driving lane
                # here would strand retries in a 2-lane pool once the
                # OTHER lane's device is convicted.
                if nan_fault and label is not None:
                    # Deferred attribution: remember where the NaN
                    # happened; a CLEAN completion on another lane
                    # confirms the chip (fed in _process), a second
                    # NaN elsewhere convicts the data (above).
                    job.nan_lane = label
                continue
            failed += 1
            with events.context(job_id=job.job_id):
                job.fail(e)
        # The breaker hears only batches that actually COST jobs: a
        # device-class fault whose work was absorbed by surviving lanes
        # is the lane escalation's problem, not grounds to shed
        # admissions fleet-wide. (On a single-device pool nothing can
        # absorb it, every job fails, and the breaker opens — the
        # historical protection.)
        if failed and self.governor is not None:
            self.governor.note_worker_failure()

    def _retry_cross_lane(self, job, exclude_label: str | None) -> bool:
        """Re-queue one job from a device-faulted batch onto a surviving
        lane. False (→ the caller fails the job honestly) when the pool
        has no healthy lane off this device, the retry budget is spent,
        or the job is already terminal (deadline scrub race)."""
        pool = self.lane_pool
        if pool is None or not pool.multi_device:
            return False
        if job.status in (DONE, FAILED):
            return True  # terminal already: nothing to fail OR retry
        if job.launch_retries >= max(2, len(pool.devices)):
            return False
        target = pool.retry_lane(exclude=exclude_label)
        if target is None:
            return False
        job.launch_retries += 1
        # Pin the retry to the surviving lane (the service's lane
        # resolver may re-route a session stop to its session's current
        # sticky lane at absorb time).
        job.lane = target.index
        events.record("job_lane_retry", severity="warning",
                      job_id=job.job_id, from_device=exclude_label,
                      to_device=target.label, retry=job.launch_retries)
        self.batcher.requeue(job)
        return True

    # ------------------------------------------------------------------

    def _process(self, batch: Batch) -> bool:
        """Run one batch; returns True when any job failed through the
        SERVICE-SIDE containment path (feeds the breaker's view of this
        batch — quality-gate failures are the client's data, not ours)."""
        import jax.numpy as jnp

        t0 = time.monotonic()
        for job in batch.jobs:
            job.mark_running()
        if self.lane_pool is not None:
            # Lane routing (serve/lanes.py): the lane's per-device
            # program, or the sharded cross-chip one for buckets past
            # the size threshold.
            key = self.lane_pool.route(batch.key, batch.size, self.lane)
        else:
            key = ProgramKey(bucket=batch.key, batch=batch.size)
        # Stashed for _handle_batch_failure: a fault in a SHARDED
        # launch must not be attributed to this worker's own lane
        # device (route() may answer differently after a degrade).
        batch.program_key = key
        contained = False
        with self.tracer.span("serve.batch", program=key.label(),
                              occupancy=batch.occupancy):
            compiled = self.cache.get(key)
            if self.fault_injector is not None and self.lane is not None:
                # Seeded device chaos (hw/faults.py): the launch
                # boundary is where a dead/NaN-emitting chip manifests.
                # Sharded launches consult the injector per SPAN MEMBER
                # (FaultySpan) — a rule naming one chip kills the whole
                # cross-chip program, exactly like a real mesh — so a
                # sharded-only workload exercises the probe-convict
                # attribution path under SL_DEVICE_FAULTS.
                if key.shards and key.span:
                    compiled = hwfaults.FaultySpan(
                        compiled, key.span, self.fault_injector)
                elif not key.shards:
                    compiled = hwfaults.FaultyDevice(
                        compiled, self.lane.label, self.fault_injector)
            calib = self.cache.placed_calib(key)
            with self.tracer.span("launch"):  # path: serve.batch.launch
                out = compiled(self.cache.stage(key, batch.stacked()),
                               calib)
                # Single readback of the dense batch result; everything
                # after is host-side numpy.
                points = np.asarray(out.points)
                colors = np.asarray(out.colors)
                valid = np.asarray(out.valid)
            if sanitize.enabled() and self.lane_pool is not None \
                    and self.lane is not None \
                    and self.lane_pool.multi_device:
                # Device-output integrity at the READBACK boundary: a
                # chip claiming validity over non-finite points is a
                # device fault — escalate the lane and retry the batch
                # on a survivor (DeviceOutputError → device-class path
                # in _handle_batch_failure), instead of containing it
                # per job as a client-data problem.
                masked = points[valid.astype(bool)]
                if masked.size and not np.isfinite(masked).all():
                    raise DeviceOutputError(
                        f"launch on {self.lane.label} returned "
                        "non-finite points under a claimed-valid mask "
                        "— NaN-emitting device output")
            # Lane health hears the clean LAUNCH here (before the
            # postprocess, whose per-job failures are not the chip's
            # fault): the failure streak resets the moment the device
            # answers with sane output — and before the jobs turn
            # terminal, so a caller observing a done job observes the
            # healthy lane too. Sharded launches stay out of LANE
            # health both ways (see _handle_batch_failure): a
            # cross-chip success is not evidence about THIS lane's
            # chip and must not reset a genuine lane-pinned failure
            # streak — it resets the SPAN's consecutive-fault streak
            # instead.
            if self.lane_pool is not None and key.shards:
                self.lane_pool.note_sharded_ok(key.span or ())
            if self.lane_pool is not None and self.lane is not None \
                    and not key.shards:
                self.lane_pool.note_launch_ok(self.lane.label)
                # NaN verdicts (deferred from _handle_batch_failure):
                # this batch decoded CLEAN here, so a job that NaN'd on
                # another lane convicts THAT chip — the same data on a
                # healthy device is fine.
                for job in batch.jobs:
                    nan_lane = getattr(job, "nan_lane", None)
                    if nan_lane is not None \
                            and nan_lane != self.lane.label:
                        job.nan_lane = None
                        self.lane_pool.note_launch_failure(
                            nan_lane, reason="nan_output")
            self._batches.inc()
            self._occupancy.observe(batch.occupancy)
            self._padded.inc(batch.size - batch.occupancy)
            self._lane_batches.inc()
            self._lane_jobs.inc(batch.occupancy)
            self._lane_occupancy.observe(batch.occupancy)
            if key.shards:
                self._sharded_batches.inc()
            with self.tracer.span("postprocess"):
                for i, job in enumerate(batch.jobs):
                    contained |= self._finish_job(
                        job, batch.key, points[i], colors[i], valid[i])
        per_job = (time.monotonic() - t0) / max(1, batch.occupancy)
        self.batcher.queue.observe_service_time(per_job)
        return contained

    def _finish_job(self, job, key, points, colors, valid) -> bool:
        """Postprocess one job; True iff it failed via the service-side
        (unexpected-exception) containment path."""
        # Correlation context covers the whole postprocess: a gate raise
        # (StopQualityError construction) journals with this job's id.
        with events.context(job_id=job.job_id):
            try:
                result, meta = self._postprocess(job, key, points, colors,
                                                 valid)
                job.complete(result, **meta)
            except ScanFault as e:
                log.warning("job %s failed: %s", job.job_id, e)
                job.fail(e)
            except Exception as e:
                # Containment boundary: an unexpected host-side error (a
                # meshing corner case, a writer bug) costs this job only
                # — but unlike a quality-gate fault it IS a service-side
                # exception, so the breaker hears about it.
                log.warning("job %s failed unexpectedly: %s", job.job_id, e)
                events.record("job_contained", severity="error",
                              message=str(e), exc_type=type(e).__name__)
                job.fail(e)
                if self.governor is not None:
                    self.governor.note_worker_failure()
                return True
        return False

    def _postprocess(self, job, key, points, colors,
                     valid) -> tuple[bytes, dict]:
        """Dense per-job lane → client artifact (PLY cloud or STL mesh).

        The coverage gate reads the job's ORIGINAL (pre-padding) pixel
        region: padded pixels are black and decode invalid by design, so
        counting them would punish small-in-bucket jobs."""
        if job.decode_sink is not None:
            # Streaming session stop: the sink (the session's ingest,
            # serve/sessions.py) owns gating — its covisibility/coverage
            # decisions are skip-and-bridge, not per-job failures. Runs
            # on this worker thread under the session lock. Coverage is
            # measured over the job's ORIGINAL pre-padding region here
            # (same rule as the one-shot gate below) and handed along —
            # the session only sees the padded bucket lane.
            import json as _json

            _, h, w = job.stack.shape
            vgrid = valid.reshape(key.height, key.width)[:h, :w]
            meta = job.decode_sink(points, colors, valid,
                                   coverage=float(vgrid.mean()),
                                   frame_shape=(key.height, key.width))
            return _json.dumps(meta).encode(), meta
        _, h, w = job.stack.shape
        vgrid = valid.reshape(key.height, key.width)[:h, :w]
        coverage = float(vgrid.mean())
        if not self.gates.coverage_ok(coverage):
            raise StopQualityError(
                f"decode coverage {coverage:.4f} below gate "
                f"{self.gates.min_coverage} — stack unusable "
                "(black/saturated/garbage upload?)")
        keep = valid.astype(bool)
        cloud = PointCloud(points=points[keep].astype(np.float32),
                           colors=colors[keep].astype(np.uint8))
        if sanitize.enabled():
            # Valid-masked triangulations must be finite — a NaN here is
            # a decode/triangulate bug, caught AT the containment
            # boundary (fails this job only) instead of shipping as a
            # poisoned mesh.
            sanitize.assert_finite(cloud.points, "serve.postprocess")
        meta = {"points": int(len(cloud)), "coverage": round(coverage, 4)}
        if job.result_format == "ply":
            return _ply_bytes(cloud), meta
        # STL / mesh_ply: the models/meshing tail (normals → solve →
        # extraction → weld) on this job's cloud. ``mesh_ply`` keeps the
        # representation's vertex colors (fusion/; STL cannot carry
        # them).
        from ..models import meshing

        # Sharded-bucket jobs carry their heavy Poisson solve across the
        # same device mesh the decode spanned (serve/lanes.py): the big
        # programs (splat, CG) shard instead of serializing on one chip.
        device_mesh = (self.lane_pool.solve_mesh(key)
                       if self.lane_pool is not None else None)
        mesh = meshing.mesh_from_cloud(
            cloud, mode="watertight", depth=self.mesh_depth,
            quantile_trim=0.0,
            representation=self.mesh_representation,
            device_mesh=device_mesh)
        meta.update(vertices=int(len(mesh.vertices)),
                    faces=int(len(mesh.faces)),
                    representation=self.mesh_representation)
        if len(mesh.faces) == 0:
            raise StopQualityError(
                f"meshing produced 0 faces from {len(cloud)} points — "
                "cloud too sparse for a watertight surface")
        if job.result_format == "mesh_ply":
            return _mesh_ply_bytes(mesh), meta
        return _stl_bytes(mesh), meta
