"""Overload governor: circuit breaker, load-shedding tiers, worker watchdog.

The bounded queue (jobs.py) protects memory; this module protects
*behavior* when the service is unhealthy or saturated:

* **circuit breaker** — a sliding window of worker outcomes. When the
  failure rate of genuinely service-side faults (batch launch failures,
  unexpected postprocess exceptions — NOT client-data quality gates)
  crosses the threshold, admissions are refused with a retryable
  rejection for a cooldown, then half-opened: the first success closes
  it. A broken device stops eating the queue's worth of doomed work.
* **load shedding** — graduated, cheapest first: past
  ``shed_preview_frac`` of queue capacity (or device-memory pressure)
  progressive session previews are suppressed (pure compute, no client
  is blocked on them); past ``shed_low_frac`` low-priority submits are
  refused with a retryable rejection while normal/high traffic still
  flows. Both tiers are visible as counters and flight events.
* **watchdog** — a thread that checks every worker's heartbeat. A worker
  wedged inside a launch past ``wedge_timeout_s`` is journaled (flight
  recorder + durability journal) and replaced with a fresh lane, so one
  hung device call does not silently zero the service's throughput.

Everything is advisory-at-admission (the queue remains the authoritative
gate) and all state is bounded.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from ..utils import events
from ..utils.log import get_logger
from .jobs import JobRejected

log = get_logger(__name__)

#: Shedding tiers, mild to severe.
LEVEL_NONE = 0
LEVEL_SHED_PREVIEWS = 1
LEVEL_SHED_LOW_PRIORITY = 2
LEVEL_BREAKER_OPEN = 3


@dataclasses.dataclass(frozen=True)
class GovernorParams:
    """Tuning surface (rides ServeConfig; docs/SERVING.md)."""

    enabled: bool = True
    # -- circuit breaker --------------------------------------------------
    breaker_window: int = 32          # worker outcomes considered
    breaker_min_samples: int = 8      # below this the breaker abstains
    breaker_failure_rate: float = 0.5
    breaker_cooldown_s: float = 5.0
    # -- load shedding ----------------------------------------------------
    shed_preview_frac: float = 0.50   # of queue capacity
    shed_low_frac: float = 0.80
    # Device-memory pressure (utils/telemetry gauges) at which shedding
    # starts regardless of queue depth; 0 disables the memory signal.
    memory_pressure_frac: float = 0.92
    # -- watchdog ---------------------------------------------------------
    watchdog: bool = True
    watchdog_interval_s: float = 1.0
    # Generous by design: a cold lazy compile (warmup off) is minutes on
    # a big program and must never be mistaken for a hang.
    wedge_timeout_s: float = 300.0
    # PER-DEVICE replacement budget: a hang that eats every fresh lane
    # on one chip must not grow one abandoned thread per wedge_timeout_s
    # forever. Counted per device lane (a dead chip burning its budget
    # used to disable the watchdog for every HEALTHY chip too — the
    # global-counter bug): at the cap the lane is ESCALATED to
    # device-dead when an escalate hook is wired (serve/lanes.py — the
    # pool re-pins its sessions and the probe path owns revival), else
    # the watchdog stops replacing that lane and journals an error.
    watchdog_max_restarts: int = 4


class CircuitBreaker:
    """Sliding-window failure-rate breaker with cooldown + half-open.

    The reusable core of the PR-8 governor's worker breaker, split out so
    the fleet tier (serve/fleet.py) can run ONE PER PEER: a sliding
    window of outcomes, an open state that lasts ``cooldown_s``, and
    half-open semantics — after cooldown the first probe is allowed
    through, and its success closes the breaker (clearing the window so
    stale failures cannot re-trip it instantly).

    Thread-safe; policy-free: it reports transitions (tripped / closed)
    and leaves events, metrics and what "failure" means to the caller.
    """

    def __init__(self, window: int = 32, min_samples: int = 8,
                 failure_rate: float = 0.5, cooldown_s: float = 5.0):
        self.min_samples = int(min_samples)
        self.failure_rate = float(failure_rate)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._outcomes: collections.deque[bool] = collections.deque(
            maxlen=max(1, int(window)))
        self._open_until = -float("inf")
        self._open_rate = 0.0

    def note_ok(self) -> bool:
        """Record one success. Returns True when this success CLOSED a
        half-open breaker (cooldown had lapsed and the probe worked)."""
        with self._lock:
            was_open = time.monotonic() < self._open_until
            self._outcomes.append(True)
            if was_open or self._open_until == -float("inf"):
                return False
            # Half-open probe succeeded: close fully, forget the window.
            self._open_until = -float("inf")
            self._outcomes.clear()
            return True

    def note_failure(self) -> tuple[bool, float, int]:
        """Record one failure. Returns (tripped_now, rate, samples)."""
        with self._lock:
            self._outcomes.append(False)
            n = len(self._outcomes)
            rate = sum(1 for ok in self._outcomes if not ok) / n
            now = time.monotonic()
            tripped = (n >= self.min_samples
                       and rate >= self.failure_rate
                       and now >= self._open_until)
            if tripped:
                self._open_until = now + self.cooldown_s
                self._open_rate = rate
            return tripped, rate, n

    def open_remaining(self) -> float | None:
        """Remaining cooldown seconds while open, else None (closed or
        half-open — probe traffic may flow)."""
        with self._lock:
            remaining = self._open_until - time.monotonic()
        return remaining if remaining > 0 else None

    @property
    def open_rate(self) -> float:
        """The failure rate observed at the last trip."""
        with self._lock:
            return self._open_rate


class BreakerOpenError(JobRejected):
    """Worker-exception rate tripped the breaker — retry after cooldown."""

    retryable = True

    def __init__(self, failure_rate: float, retry_after_s: float):
        super().__init__(
            f"service circuit breaker open (worker failure rate "
            f"{failure_rate:.0%}); retry in {retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


class LoadShedError(JobRejected):
    """Low-priority work shed under overload — retry later or raise the
    job's priority."""

    retryable = True

    def __init__(self, level: int, retry_after_s: float):
        super().__init__(
            "low-priority work shed under overload; retry in "
            f"{retry_after_s:.1f}s or submit with priority=normal")
        self.retry_after_s = retry_after_s
        self.level = level


class OverloadGovernor:
    """Breaker + shedding decisions over one service's queue/telemetry."""

    def __init__(self, params: GovernorParams, queue,
                 registry, telemetry=None, store=None):
        self.params = params
        self.queue = queue
        self.telemetry = telemetry
        self.store = store
        self._breaker = CircuitBreaker(
            window=params.breaker_window,
            min_samples=params.breaker_min_samples,
            failure_rate=params.breaker_failure_rate,
            cooldown_s=params.breaker_cooldown_s)
        # tier="preview" counts SHEDDING DECISIONS (one per stop
        # ingested while the tier is active) — the preview-due check and
        # covisibility gate run later in the session, so the per-preview
        # ground truth is the `preview_shed` flight events, not this
        # counter.
        self._shed_total = {
            tier: registry.counter("serve_shed_total",
                                   "overload-governor shed decisions "
                                   "(preview: per stop ingested while "
                                   "the tier is active)", tier=tier)
            for tier in ("preview", "low_priority", "breaker")}
        self._breaker_trips = registry.counter(
            "serve_breaker_trips_total",
            "circuit-breaker openings on worker-exception rate")
        self._level_gauge = registry.gauge(
            "serve_overload_level",
            "current shedding tier (0 none, 1 previews, "
            "2 low-priority, 3 breaker open)")
        self._restarts = registry.counter(
            "serve_worker_restarts_total",
            "wedged workers replaced by the watchdog")
        # Per-device replacement spend (the budget is per chip, not
        # global — a dead device must not disable the watchdog for the
        # healthy ones) + devices whose budget outcome already fired.
        self._restarts_by: dict[str, int] = {}
        self._budget_spent: set[str] = set()
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None

    # -- breaker -----------------------------------------------------------

    def note_worker_ok(self) -> None:
        if self._breaker.note_ok():
            events.record("breaker_closed", severity="info",
                          message="worker recovered; breaker closed")

    def note_worker_failure(self) -> None:
        p = self.params
        tripped, rate, n = self._breaker.note_failure()
        if tripped:
            self._breaker_trips.inc()
            events.record(
                "breaker_open", severity="error",
                message=f"worker failure rate {rate:.0%} over last "
                        f"{n} outcomes; shedding admissions for "
                        f"{p.breaker_cooldown_s:.1f}s",
                failure_rate=round(rate, 3))
            if self.store is not None:
                self.store.note("breaker_open",
                                failure_rate=round(rate, 3))

    def breaker_open(self) -> float | None:
        """Remaining cooldown seconds when open, else None."""
        return self._breaker.open_remaining()

    # -- shedding ----------------------------------------------------------

    def memory_pressure(self) -> float:
        if self.telemetry is None:
            return 0.0
        return self.telemetry.memory_pressure()

    def level(self) -> int:
        p = self.params
        if not p.enabled:
            return LEVEL_NONE
        if self.breaker_open() is not None:
            return LEVEL_BREAKER_OPEN
        frac = self.queue.depth() / max(1, self.queue.max_depth)
        mem = self.memory_pressure()
        mem_pressed = (p.memory_pressure_frac > 0
                       and mem >= p.memory_pressure_frac)
        if frac >= p.shed_low_frac:
            lvl = LEVEL_SHED_LOW_PRIORITY
        elif frac >= p.shed_preview_frac or mem_pressed:
            lvl = LEVEL_SHED_PREVIEWS
        else:
            lvl = LEVEL_NONE
        self._level_gauge.set(lvl)
        return lvl

    def shed_previews(self) -> bool:
        shed = self.level() >= LEVEL_SHED_PREVIEWS
        if shed:
            self._shed_total["preview"].inc()
        return shed

    def admit(self, priority: int = 1) -> None:
        """Raise the governor's rejection for this admission, if any.
        Runs BEFORE the queue's own gate; content-cache hits are served
        upstream of this call (a cached answer costs nothing and relieves
        load, so it flows even with the breaker open)."""
        if not self.params.enabled:
            return
        remaining = self.breaker_open()
        if remaining is not None:
            self._shed_total["breaker"].inc()
            self._level_gauge.set(LEVEL_BREAKER_OPEN)
            raise BreakerOpenError(self._breaker.open_rate, remaining)
        lvl = self.level()
        if lvl >= LEVEL_SHED_LOW_PRIORITY and priority >= 2:
            self._shed_total["low_priority"].inc()
            raise LoadShedError(lvl, self.queue.retry_hint())

    # -- watchdog ----------------------------------------------------------

    def start_watchdog(self, workers_fn, restart_fn,
                       escalate_fn=None) -> None:
        """``workers_fn()`` → current worker list; ``restart_fn(worker)``
        replaces one wedged worker and returns its successor;
        ``escalate_fn(worker)`` (optional — the device-loss tier) is
        called INSTEAD of a replacement once a worker's device has spent
        its per-device restart budget: same-device swapping a chip that
        wedges every fresh lane is the failure mode this escalates to
        device-dead. Returns True when it escalated (the watchdog stops
        touching that device; the probe path owns revival)."""
        if not (self.params.enabled and self.params.watchdog):
            return
        self._watch_stop.clear()
        self._watch_thread = threading.Thread(
            target=self._watch, args=(workers_fn, restart_fn,
                                      escalate_fn),
            name="serve-watchdog", daemon=True)
        self._watch_thread.start()

    def stop_watchdog(self) -> None:
        self._watch_stop.set()
        t = self._watch_thread
        if t is not None:
            t.join(timeout=5.0)
            self._watch_thread = None

    @staticmethod
    def _budget_key(worker) -> str:
        """Restart budgets are PER DEVICE (lanes sharing a chip share
        its budget); lane-less workers fall back to their name."""
        lane = getattr(worker, "lane", None)
        return lane.label if lane is not None else worker.name

    def reset_restart_budget(self, key: str) -> None:
        """A revived device (probe path) gets a fresh watchdog budget —
        its past wedges belonged to the failure the revival cleared."""
        self._restarts_by.pop(key, None)
        self._budget_spent.discard(key)

    def _watch(self, workers_fn, restart_fn, escalate_fn=None) -> None:
        p = self.params
        budget_spent = self._budget_spent
        while not self._watch_stop.wait(p.watchdog_interval_s):
            now = time.monotonic()
            for w in workers_fn():
                stalled = now - w.last_beat
                if not w.alive or getattr(w, "abandoned", False) \
                        or stalled <= p.wedge_timeout_s:
                    continue
                key = self._budget_key(w)
                if self._restarts_by.get(key, 0) \
                        >= p.watchdog_max_restarts:
                    if key in budget_spent:
                        continue
                    if escalate_fn is not None:
                        # A chip that wedges every fresh lane is DEAD,
                        # not unlucky: hand it to the lane-health tier
                        # (re-pin + probe-revive) instead of swapping
                        # onto the same device forever.
                        budget_spent.add(key)
                        events.record(
                            "watchdog_device_escalated", severity="error",
                            message=f"device {key} spent its "
                                    f"{p.watchdog_max_restarts}-restart "
                                    "budget and still wedges — "
                                    "escalating to device-dead",
                            worker=w.name, device=key)
                        if self.store is not None:
                            self.store.note("watchdog_device_escalated",
                                            worker=w.name, device=key)
                        try:
                            escalate_fn(w)
                        except Exception as e:
                            # Abandon only on SUCCESS: a still-live
                            # worker is what lets the next pass retry
                            # the escalation (abandoned workers are
                            # skipped at the top of the scan).
                            log.error("device escalation failed: %s", e)
                            budget_spent.discard(key)
                            continue
                        w.abandoned = True
                        continue
                    budget_spent.add(key)
                    events.record(
                        "watchdog_budget_exhausted", severity="error",
                        message=f"{p.watchdog_max_restarts} worker "
                                f"replacements spent on {key} and its "
                                "lanes still wedge — not replacing "
                                "further on this device (others keep "
                                "their budgets)",
                        worker=w.name, device=key)
                    continue
                w.abandoned = True
                self._restarts.inc()
                self._restarts_by[key] = self._restarts_by.get(key, 0) + 1
                events.record(
                    "worker_wedged", severity="error",
                    message=f"worker {w.name} made no progress for "
                            f"{stalled:.0f}s; starting a replacement "
                            "lane", worker=w.name,
                    stalled_s=round(stalled, 1))
                if self.store is not None:
                    self.store.note("worker_wedged", worker=w.name,
                                    stalled_s=round(stalled, 1))
                try:
                    repl = restart_fn(w)
                except Exception as e:
                    log.error("worker restart failed: %s", e)
                    continue
                events.record("worker_restarted", severity="warning",
                              worker=w.name, replacement=repl.name)

    def stats(self) -> dict:
        remaining = self.breaker_open()
        return {
            "enabled": self.params.enabled,
            "level": self.level(),
            "breaker_open_s": (round(remaining, 2)
                               if remaining is not None else None),
            "worker_restarts": int(self._restarts.value),
            "worker_restarts_by_device": dict(self._restarts_by),
            # Autoscaler signals (router /fleet/signals aggregates
            # these across replicas).
            "memory_pressure": round(self.memory_pressure(), 4),
            "shed_total": {tier: int(c.value)
                           for tier, c in self._shed_total.items()},
        }
