"""Multi-stop streaming sessions for the reconstruction service.

One :class:`ServeSession` wraps one `stream.IncrementalSession` behind a
lock: stops are submitted as ordinary jobs whose ``decode_sink`` hands
the batch-decoded arrays to the session (so session stops ride the SAME
admission queue → bucketed batcher → warmed program cache as one-shot
jobs — full batcher interop, including coalescing stops from different
sessions into one launch), previews are serialized lazily on demand, and
finalize lands the result as a terminal job in the service's ordinary
job registry so the existing ``GET /result`` path serves it.

The registry is bounded two ways: at most ``max_sessions`` live
(unfinalized) sessions — above it ``POST /session`` is refused with a
retryable rejection (the admission-queue rule applied to sessions) —
and EVERY session, live or finalized, expires ``session_ttl_s`` after
its last activity (finalized ones are additionally evicted oldest-first
past the cap). A client that crashes mid-scan therefore frees its slot
and its model buffers after the idle TTL instead of pinning them
forever.

Ordering: within one worker, stops complete in submission order (batches
preserve queue order and the postprocess loop is sequential). With
``workers > 1`` two batches can interleave — submit a session's next
stop after the previous stop's job is terminal (the natural capture
cadence), or keep one worker per device.
"""

from __future__ import annotations

import io
import threading
import time
import uuid
from collections import OrderedDict

import numpy as np

from ..io.stl import write_stl
from ..stream import IncrementalSession, StreamParams
from ..utils import events
from ..utils.log import get_logger
from .jobs import DONE, FAILED, JobRejected, ServeError, StackFormatError

log = get_logger(__name__)

#: ``POST /session`` body keys a client may override per session. The
#: merge/registration surface stays server-side (it keys compiled
#: programs; per-session drift would mint fresh compiles — exactly what
#: the warmed steady state forbids). ``representation`` picks the
#: preview/final scene representation ("tsdf" — the default,
#: integrate-don't-re-solve | "archival" — TSDF previews, watertight
#: Poisson final artifact | "poisson" — the legacy re-solve lane |
#: "splat" — the fusion/splat dispatch, docs/STREAMING.md +
#: docs/RENDERING.md; a non-default choice compiles its programs on
#: first use unless the replica warmed that lane too; "splat" adds the
#: GET /session/<id>/render + /splats surface and result_format
#: "render_png").
SESSION_OPTION_KEYS = ("preview_every", "preview_depth", "final_depth",
                       "expected_stops", "method", "covis",
                       "representation")


class SessionLimitError(JobRejected):
    """Session registry at capacity — finish or delete one, then retry."""

    retryable = True

    def __init__(self, limit: int):
        super().__init__(f"session limit reached ({limit} live sessions); "
                         "finalize or delete one and retry")
        self.retry_after_s = None


class UnknownSessionError(ServeError):
    """No such session (never created, or evicted) — maps to HTTP 404."""


class SessionResultEvicted(ServeError):
    """The session finalized, but its terminal result job fell out of the
    bounded job registry — the artifact is gone; re-scan. Maps to HTTP
    410 (the one-shot result-eviction semantics applied to sessions)."""


class ServeSession:
    """One streaming session: lock, lifecycle stamps, lazy preview bytes."""

    def __init__(self, session_id: str, session: IncrementalSession,
                 bucket_pixels: int, preview_shed=None, lane=None):
        self.session_id = session_id
        self.session = session
        self.bucket_pixels = bucket_pixels
        # Overload hook (serve/governor.py): polled per ingested stop;
        # True suppresses the progressive preview for that stop (the
        # cheapest sheddable work — the last preview keeps serving).
        self.preview_shed = preview_shed
        # Sticky device lane (serve/lanes.py): every stop job carries
        # this lane's affinity AND the session's own jit programs (fuse,
        # refine, preview) run under the lane device — warmed per lane
        # at replica start, so placement and failover adoption are both
        # compile-free.
        self.lane = lane
        # How many times this session's sticky lane moved (device-loss
        # re-pins AND revival rebalances) — the chaos tests' migration
        # evidence, surfaced in status_dict.
        self.lane_moves = 0
        self.lock = threading.Lock()
        self.created_t = time.monotonic()
        self.last_t = self.created_t
        self.stops_submitted = 0
        self.result_job_id: str | None = None
        self._preview_cache: tuple[int, bytes] | None = None
        self._pending: list = []  # submitted stop Jobs not yet terminal

    # ------------------------------------------------------------------

    def device_ctx(self):
        """``jax.default_device(lane)`` for sticky-lane sessions (jit
        keys placement, so the per-lane warmup is what keeps lane
        compute compile-free), a no-op otherwise."""
        if self.lane is None:
            import contextlib

            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.lane.device)

    def repin(self, lane) -> None:
        """Move the session's sticky lane — the device-loss re-pin and
        the revival rebalance (serve/lanes.py) share this path, so
        migrating BACK is as compile-free and bitwise as migrating
        away. The session's device-resident state (model
        buffers, retained preps, preview grids) is UNCOMMITTED jax
        arrays throughout (built from host arrays under the lane's
        ``default_device`` context), so the next ingest/finalize under
        the NEW lane's context transfers it lazily and hits the jit
        programs warmed per device at replica start — an explicit
        ``device_put`` here would mint COMMITTED arrays, whose distinct
        sharding signature recompiles every warmed program (and on a
        truly dead chip the copy-out would fail exactly like the
        compute; total on-device data loss is the fleet handoff
        replay's domain, docs/SERVING.md failure matrix)."""
        with self.lock:
            if lane is not self.lane:
                self.lane_moves += 1
            self.lane = lane

    def ingest(self, points, colors, valid, coverage=None,
               frame_shape=None) -> dict:
        """The job's ``decode_sink``: fuse one decoded stop. Runs on the
        worker thread; the lock serializes against preview/finalize —
        under the session's sticky lane device when one is assigned.
        ``frame_shape`` is the decoded bucket's (H, W) — the splat
        appearance lane's RGB supervision needs the pixel layout."""
        shed = bool(self.preview_shed()) if self.preview_shed else False
        with self.lock:
            self.session.suppress_previews = shed
            with self.device_ctx():
                res = self.session.add_decoded(points, colors, valid,
                                               coverage=coverage,
                                               frame_shape=frame_shape)
            self.last_t = time.monotonic()
            return {"session_id": self.session_id, **res.to_dict()}

    @staticmethod
    def _terminal(job) -> bool:
        # Plain status read — the prune below runs under the session
        # lock, where even a zero-timeout Event.wait is off-limits
        # (jaxlint blocking-under-lock).
        return job.status in (DONE, FAILED)

    def note_pending(self, job) -> None:
        with self.lock:
            self._pending = [j for j in self._pending
                             if not self._terminal(j)]
            self._pending.append(job)
            self.last_t = time.monotonic()

    def settle_pending(self, timeout_s: float = 120.0) -> bool:
        """Block until every already-submitted stop job is terminal —
        finalize must not close the ring under a stop the client was
        told 200 about. Called WITHOUT the session lock held (the
        pending jobs' sinks need it to finish). True when all settled."""
        deadline = time.monotonic() + timeout_s
        with self.lock:
            jobs = list(self._pending)
        ok = True
        for j in jobs:
            ok = j.wait(max(0.0, deadline - time.monotonic())) and ok
        with self.lock:
            self._pending = [j for j in self._pending
                             if not self._terminal(j)]
        return ok

    def preview_bytes(self) -> tuple[bytes, dict] | None:
        """Latest progressive preview as STL bytes (serialized once per
        emitted preview, then cached)."""
        with self.lock:
            mesh = self.session.preview
            meta = dict(self.session.preview_meta)
            if mesh is None:
                return None
            stamp = meta.get("stop", -1)
            if self._preview_cache is None \
                    or self._preview_cache[0] != stamp:
                buf = io.BytesIO()
                write_stl(buf, mesh)
                self._preview_cache = (stamp, buf.getvalue())
            return self._preview_cache[1], meta

    def status_dict(self) -> dict:
        with self.lock:
            out = {"session_id": self.session_id,
                   "stops_submitted": self.stops_submitted,
                   "age_s": round(time.monotonic() - self.created_t, 3),
                   **self.session.status_dict()}
            if self.lane is not None:
                out["device_lane"] = self.lane.label
                out["lane_moves"] = self.lane_moves
            if self.result_job_id is not None:
                out["result_job_id"] = self.result_job_id
            return out


class SessionManager:
    """Bounded registry of streaming sessions."""

    def __init__(self, stream_params: StreamParams, proj,
                 decode_cfg, tri_cfg, max_sessions: int = 8,
                 session_ttl_s: float = 3600.0, store=None,
                 preview_shed=None, replica_id: str | None = None,
                 lane_pool=None):
        self.stream_params = stream_params
        self.proj = proj
        self.decode_cfg = decode_cfg
        self.tri_cfg = tri_cfg
        self.max_sessions = max(1, int(max_sessions))
        self.session_ttl_s = float(session_ttl_s)
        # Durability journal (serve/store.py): session creations and
        # endings are appended so `--recover` rebuilds exactly the live
        # set. None = durability off.
        self.store = store
        self.preview_shed = preview_shed
        # Sticky device-lane placement (serve/lanes.py): sessions are
        # assigned the least-loaded lane at create/restore and release
        # it when they leave the registry. None = no lane dimension.
        self.lane_pool = lane_pool
        # Fleet tier: journaled session heads carry the replica id, so
        # handoff-aware recovery can compare the WAL's claim against the
        # shared stream's current owner (serve/store.py).
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._sessions: OrderedDict[str, ServeSession] = OrderedDict()

    # ------------------------------------------------------------------

    def _params_for(self, options: dict) -> StreamParams:
        import dataclasses

        bad = sorted(set(options) - set(SESSION_OPTION_KEYS))
        if bad:
            raise StackFormatError(
                f"unknown session option(s) {bad}; allowed: "
                f"{sorted(SESSION_OPTION_KEYS)}")
        overrides = {}
        for k in SESSION_OPTION_KEYS:
            if k in options and options[k] is not None:
                overrides[k] = options[k]
        if "method" in overrides \
                and overrides["method"] not in ("sequential", "posegraph"):
            raise StackFormatError(
                f"method must be 'sequential' or 'posegraph', got "
                f"{overrides['method']!r}")
        if "representation" in overrides \
                and overrides["representation"] not in ("poisson", "tsdf",
                                                        "splat",
                                                        "archival"):
            raise StackFormatError(
                f"representation must be 'poisson', 'tsdf', 'splat' or "
                f"'archival', got {overrides['representation']!r}")
        for k in ("preview_every", "preview_depth", "final_depth",
                  "expected_stops"):
            if k in overrides:
                try:
                    overrides[k] = int(overrides[k])
                except (TypeError, ValueError):
                    raise StackFormatError(f"session option {k!r} must "
                                           f"be an int")
        if "covis" in overrides:
            overrides["covis"] = bool(overrides["covis"])
        return dataclasses.replace(self.stream_params, **overrides)

    def create(self, options: dict | None = None,
               session_id: str | None = None,
               scan_id: str | None = None,
               journal: bool = True) -> ServeSession:
        options = dict(options or {})
        params = self._params_for(options)
        sid = session_id or uuid.uuid4().hex[:12]
        session = IncrementalSession(
            calib=None,  # serve stops arrive pre-decoded via the batcher
            col_bits=self.proj.col_bits, row_bits=self.proj.row_bits,
            params=params, decode_cfg=self.decode_cfg,
            tri_cfg=self.tri_cfg, scan_id=scan_id or f"serve-{sid}")
        lane = (self.lane_pool.assign_session(sid)
                if self.lane_pool is not None else None)
        entry = ServeSession(sid, session, bucket_pixels=0,
                             preview_shed=self.preview_shed, lane=lane)
        expired: list[str] = []
        evicted: list[str] = []
        with self._lock:
            # Idle-TTL expiry first — an abandoned (crashed-client) live
            # session must free its slot and model buffers, not pin them
            # forever.
            now = time.monotonic()
            expired = [k for k, s in self._sessions.items()
                       if now - s.last_t > self.session_ttl_s]
            for k in expired:
                del self._sessions[k]
            live = sum(1 for s in self._sessions.values()
                       if not s.session.finalized)
            if live >= self.max_sessions:
                if self.lane_pool is not None:  # undo the assignment
                    self.lane_pool.release_session(sid)
                raise SessionLimitError(self.max_sessions)
            self._sessions[sid] = entry
            # Evict oldest FINALIZED sessions past the cap (their result
            # already lives in the job registry).
            done = [k for k, s in self._sessions.items()
                    if s.session.finalized]
            excess = len(self._sessions) - self.max_sessions
            for k in done[:max(0, excess)]:
                del self._sessions[k]
                evicted.append(k)
        # Both eviction paths journal a flight event CARRYING THE SESSION
        # ID (and the durability journal's session_end), so a vanished
        # session is attributable in a `cli diagnose` bundle instead of
        # silently 404ing.
        for k in expired:
            if self.lane_pool is not None:
                self.lane_pool.release_session(k)
            events.record("session_expired", session_id=k,
                          severity="warning", reason="idle_ttl",
                          ttl_s=self.session_ttl_s)
            self._journal_end(k, "idle_ttl")
        for k in evicted:
            if self.lane_pool is not None:
                self.lane_pool.release_session(k)
            events.record("session_evicted", session_id=k,
                          severity="warning", reason="finalized_cap",
                          max_sessions=self.max_sessions)
            self._journal_end(k, "finalized_cap")
        events.record("session_created", scan_id=session.scan_id,
                      session_id=sid)
        if journal and self.store is not None:
            self.store.append({"op": "session", "session_id": sid,
                               "scan_id": session.scan_id,
                               "options": options,
                               "replica": self.replica_id})
        return entry

    def restore(self, session_id: str, options: dict,
                scan_id: str) -> ServeSession:
        """Recreate a journaled session during recovery: same id, same
        scan id, same options (⇒ same params/key schedule — the bitwise
        replay contract), WITHOUT re-journaling its creation."""
        return self.create(options, session_id=session_id,
                           scan_id=scan_id, journal=False)

    def _journal_end(self, session_id: str, reason: str) -> None:
        # The ending replica's id rides the op: the handoff sink
        # ignores an end from a NON-owner (a stale double-hosted copy
        # expiring after its session was adopted elsewhere). Always
        # SYNC: once this replica denies the session, the definitive-404
        # contract needs the end tombstone ON the handoff stream before
        # the router's adoption sweep can read it (a lazy end let a
        # survivor "adopt" the half-ended stream) — and every caller is
        # already on a path that blocks on a sync WAL append anyway.
        if self.store is not None:
            self.store.append({"op": "session_end",
                               "session_id": session_id,
                               "reason": reason,
                               "replica": self.replica_id})

    def get(self, session_id: str) -> ServeSession:
        with self._lock:
            entry = self._sessions.get(session_id)
        if entry is None:
            raise UnknownSessionError(
                f"unknown session {session_id!r} (never created, "
                "or evicted after finalize)")
        return entry

    def peek(self, session_id: str) -> ServeSession | None:
        """``get`` without the raise — the device-loss re-pin and lane
        resolution paths probe sessions that may have ended."""
        with self._lock:
            return self._sessions.get(session_id)

    def delete(self, session_id: str) -> None:
        with self._lock:
            entry = self._sessions.pop(session_id, None)
        if entry is None:
            raise UnknownSessionError(f"unknown session {session_id!r}")
        if self.lane_pool is not None:
            self.lane_pool.release_session(session_id)
        events.record("session_deleted", session_id=session_id,
                      stops_fused=entry.session.stops_fused)
        self._journal_end(session_id, "deleted")

    def stats(self) -> dict:
        with self._lock:
            entries = list(self._sessions.values())
        return {
            "sessions": len(entries),
            "live": sum(1 for e in entries
                        if not e.session.finalized),
            "max_sessions": self.max_sessions,
        }
