"""Device lanes: one worker lane per local accelerator chip.

Every `serve/` worker historically launched on the default device — the
fleet tier scaled *replicas* while each process left all but one chip
idle. This module gives the service its device dimension:

* :class:`DeviceLane` — one launch lane pinned to one ``jax.Device``.
  The lane's label (``"cpu:0"``, ``"tpu:3"``) rides the per-device
  :class:`~.cache.ProgramKey`, so AOT executables — and the
  zero-recompile steady-state assertion — stay per-chip.
* :class:`DeviceLanePool` — enumerates ``jax.local_devices()`` once,
  hands out lanes round-robin to the configured worker count, routes
  each (bucket, batch) to either a lane-pinned program or the sharded
  cross-chip tier (``shard_min_pixels``), and owns STICKY session →
  lane placement: a streaming session is assigned the least-loaded
  lane at creation and every stop it submits carries that lane's
  affinity, so the session's jit programs (fuse, refine, preview —
  warmed per lane at replica start) never migrate mid-scan.

Since the device-loss tier the pool also owns **lane health**: each
distinct device carries a healthy → suspect → dead state machine with
hysteresis (consecutive launch failures promote, mirroring the router's
readyz-miss detector; only a successful probe revives a dead device),
visible as ``serve_lane_state{device=}``. A dead transition fires the
service's ``on_device_dead`` hook, sticky sessions re-pin to surviving
lanes (``serve_lane_repins_total``), and the sharded big-bucket tier
re-forms its span from the LIVE device set — the widest power-of-two
width the survivors can fill, down the 8→4→2→off ladder — instead of
launching over a dead mesh member (docs/MESHING.md § shard degrade).
Spans are device SETS, not enumeration prefixes: chip 0 dying costs the
tier one member, not the whole span. Sharded launches feed the same
health machine through ``note_sharded_failure`` — N consecutive faults
on one span fire ``on_span_suspect`` so the service can probe each
member and convict the dead one (docs/ROBUSTNESS.md § probe-convict).
On revive, ``rebalance_sessions`` migrates the sessions that were moved
off the chip back home, with flap hysteresis.

The pool is pure bookkeeping — no threads, no device I/O. Constructing
one (without an explicit ``devices`` list) calls ``jax.local_devices()``,
which initializes the backend: set platform/topology flags
(``JAX_PLATFORMS``, ``--xla_force_host_platform_device_count``) before
building a service.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..utils import events, trace
from ..utils.log import get_logger
from .batcher import BucketKey
from .cache import ProgramKey

log = get_logger(__name__)

#: Lane (device) health states, mild to terminal. The numeric values are
#: the ``serve_lane_state{device=}`` gauge's encoding.
LANE_HEALTHY, LANE_SUSPECT, LANE_DEAD = "healthy", "suspect", "dead"
_STATE_VALUE = {LANE_HEALTHY: 0, LANE_SUSPECT: 1, LANE_DEAD: 2}


class _DeviceHealth:
    """Per-device failure hysteresis (the router's readyz-miss detector
    shape applied to launch outcomes): ``suspect_failures`` consecutive
    failures → suspect, ``dead_failures`` → dead; any success while not
    dead resets to healthy. Dead is sticky — only an explicit revive
    (the probe path) returns a device to service."""

    __slots__ = ("state", "failures", "dead_since", "reason")

    def __init__(self):
        self.state = LANE_HEALTHY
        self.failures = 0
        self.dead_since: float | None = None
        self.reason = ""


@dataclasses.dataclass(frozen=True)
class DeviceLane:
    """One launch lane: a worker index pinned to one device."""

    index: int            # lane number (== worker index)
    device: object        # jax.Device
    label: str            # "platform:id", the ProgramKey.device value

    def __repr__(self) -> str:  # device objects repr verbosely
        return f"DeviceLane({self.index}, {self.label})"


def device_label(device) -> str:
    return f"{device.platform}:{device.id}"


class DeviceLanePool:
    """Lane assignment + program routing over the local devices.

    ``n_lanes`` worker lanes spread round-robin over up to
    ``max_devices`` local devices (None = all). ``shard_min_pixels``
    selects the sharded cross-chip tier: a bucket whose padded pixel
    count meets the threshold dispatches ONE program spanning
    ``shard_devices`` chips (rows sharded over the mesh's space axis,
    `parallel/mesh.py`) instead of serializing on a single lane.
    """

    def __init__(self, n_lanes: int = 1, max_devices: int | None = None,
                 shard_min_pixels: int | None = None,
                 shard_devices: int = 0, devices=None,
                 registry: "trace.MetricsRegistry | None" = None,
                 suspect_failures: int = 2, dead_failures: int = 3,
                 sharded_suspect_failures: int = 2,
                 rebalance_flap_window_s: float = 300.0):
        if devices is None:
            import jax

            devices = jax.local_devices()
        devices = list(devices)
        if max_devices is not None:
            devices = devices[:max(1, int(max_devices))]
        if not devices:
            raise ValueError("no local devices to build lanes over")
        self.devices = devices
        n_lanes = max(1, int(n_lanes))
        self.lanes = [
            DeviceLane(i, devices[i % len(devices)],
                       device_label(devices[i % len(devices)]))
            for i in range(n_lanes)
        ]
        self.shard_min_pixels = shard_min_pixels
        # The sharded tier needs >= 2 chips to be worth a distinct
        # program; 0 = span every device the pool can see.
        self.shard_devices = (len(devices) if not shard_devices
                              else min(int(shard_devices), len(devices)))
        self._lock = threading.Lock()
        self._session_lane: dict[str, DeviceLane] = {}
        # Solve meshes are keyed by the span's device SET (sorted label
        # tuple), not a count — a 4-wide span over {1,2,3,4} and one
        # over {0,1,2,3} are different meshes.
        self._solve_meshes: dict[tuple, object] = {}
        # -- lane health (device-loss tier) ----------------------------
        self.registry = registry if registry is not None \
            else trace.REGISTRY
        self.suspect_failures = max(1, int(suspect_failures))
        self.dead_failures = max(self.suspect_failures,
                                 int(dead_failures))
        # One health record per LANE DEVICE (lanes sharing a chip share
        # its fate — a dead chip kills every lane pinned to it).
        self._health: dict[str, _DeviceHealth] = {
            ln.label: _DeviceHealth() for ln in self.lanes}
        # Fired by a healthy→…→dead transition, OUTSIDE the pool lock
        # (the service hooks its re-pin/worker-deactivation here; that
        # work takes other locks and must not nest under ours).
        self.on_device_dead = None  # callable(label) | None
        # -- sharded-fault attribution ---------------------------------
        # Sharded launches can't name the dead member from the launch
        # error alone; the pool counts consecutive faults per span and
        # fires ``on_span_suspect`` (outside the lock) at the threshold
        # so the service can probe-convict (docs/ROBUSTNESS.md).
        self.sharded_suspect_failures = max(
            1, int(sharded_suspect_failures))
        self._span_failures: dict[tuple, int] = {}
        self.on_span_suspect = None  # callable(span tuple) | None
        # -- revival rebalancing ---------------------------------------
        self.rebalance_flap_window_s = float(rebalance_flap_window_s)
        self._displaced: dict[str, set[str]] = {}
        self._revive_times: dict[str, list[float]] = {}
        self._revives: dict[str, int] = {}
        self._state_gauge = {
            label: self.registry.gauge(
                "serve_lane_state",
                "device-lane health (0 healthy, 1 suspect, 2 dead)",
                device=label)
            for label in self._health}
        self._dead_total = self.registry.counter(
            "serve_device_dead_total",
            "devices declared dead by lane-health escalation")
        self._repins = self.registry.counter(
            "serve_lane_repins_total",
            "sticky sessions re-pinned to a surviving lane after their "
            "device died")
        self._span_faults = self.registry.counter(
            "serve_sharded_span_faults_total",
            "device-class faults observed on sharded cross-chip "
            "launches (pre-attribution)")
        self._span_probes = self.registry.counter(
            "serve_sharded_span_probes_total",
            "probe-convict rounds triggered by consecutive sharded "
            "faults on one span")
        self._rebalances = self.registry.counter(
            "serve_lane_rebalances_total",
            "sticky sessions migrated back to their revived device")

    # -- lanes ---------------------------------------------------------

    def lane(self, index: int) -> DeviceLane:
        return self.lanes[index]

    @property
    def n_lanes(self) -> int:
        return len(self.lanes)

    def lanes_on(self, label: str) -> list[DeviceLane]:
        """Every lane pinned to the device ``label`` (the set the
        service deactivates/revives together — a chip dies whole)."""
        return [ln for ln in self.lanes if ln.label == label]

    def device_by_label(self, label: str):
        """The jax.Device behind a lane label, or None (the probe
        path's lookup)."""
        for d in self.devices:
            if device_label(d) == label:
                return d
        return None

    @property
    def multi_device(self) -> bool:
        """True when the lanes actually span more than one chip. A
        single-device pool routes through the HISTORICAL un-pinned
        program keys and takes no session placement — bit-identical to
        the pre-lane service (and its warmed program set)."""
        return len({ln.label for ln in self.lanes}) > 1

    def distinct_devices(self) -> list[DeviceLane]:
        """First lane per distinct device — the warmup iteration set
        (two lanes sharing a chip share its programs)."""
        seen: dict[str, DeviceLane] = {}
        for lane in self.lanes:
            seen.setdefault(lane.label, lane)
        return list(seen.values())

    # -- lane health (device-loss tier) --------------------------------

    def device_state(self, label: str) -> str:
        with self._lock:
            h = self._health.get(label)
            return h.state if h is not None else LANE_HEALTHY

    def live_devices(self) -> list[str]:
        with self._lock:
            return [d for d, h in self._health.items()
                    if h.state != LANE_DEAD]

    def dead_devices(self) -> list[str]:
        with self._lock:
            return [d for d, h in self._health.items()
                    if h.state == LANE_DEAD]

    def lane_alive(self, index: int) -> bool:
        """True while the lane's DEVICE is not dead (suspect lanes keep
        serving — hysteresis exists exactly so one flaky launch doesn't
        strand a chip's sticky sessions)."""
        if not (0 <= int(index) < len(self.lanes)):
            return False
        return self.device_state(self.lanes[int(index)].label) != LANE_DEAD

    def _set_state(self, h: _DeviceHealth, label: str,
                   state: str) -> None:
        h.state = state
        self._state_gauge[label].set(_STATE_VALUE[state])

    def note_launch_ok(self, label: str) -> None:
        """A clean launch on ``label``: resets the failure streak and
        demotes suspect back to healthy. A DEAD device stays dead — only
        the probe path revives it (a straggler batch completing after
        the death call must not un-kill the chip under the re-pin)."""
        with self._lock:
            h = self._health.get(label)
            if h is None or h.state == LANE_DEAD:
                return
            h.failures = 0
            if h.state != LANE_HEALTHY:
                self._set_state(h, label, LANE_HEALTHY)

    def note_launch_failure(self, label: str, reason: str = "") -> str:
        """A device-class launch failure on ``label``; returns the NEW
        state. Consecutive failures walk healthy → suspect → dead
        (``suspect_failures`` / ``dead_failures``); the dead transition
        counts ``serve_device_dead_total`` and fires ``on_device_dead``
        outside the pool lock."""
        dead_now = False
        with self._lock:
            h = self._health.get(label)
            if h is None:
                h = self._health[label] = _DeviceHealth()
                self._state_gauge.setdefault(label, self.registry.gauge(
                    "serve_lane_state",
                    "device-lane health (0 healthy, 1 suspect, 2 dead)",
                    device=label))
            if h.state == LANE_DEAD:
                return LANE_DEAD
            h.failures += 1
            h.reason = reason
            if h.failures >= self.dead_failures:
                self._set_state(h, label, LANE_DEAD)
                h.dead_since = time.monotonic()
                dead_now = True
            elif h.failures >= self.suspect_failures \
                    and h.state == LANE_HEALTHY:
                self._set_state(h, label, LANE_SUSPECT)
                events.record("lane_suspect", severity="warning",
                              device=label, reason=reason,
                              failures=h.failures)
            state = h.state
        if dead_now:
            self._dead_total.inc()
            events.record("device_dead", severity="error", device=label,
                          reason=reason,
                          message=f"device {label} declared dead after "
                                  f"{self.dead_failures} consecutive "
                                  f"launch failures ({reason})")
            log.error("device %s declared dead (%s)", label, reason)
            cb = self.on_device_dead
            if cb is not None:
                cb(label)
        return state

    def mark_device_dead(self, label: str, reason: str = "") -> bool:
        """Escalation entry (the watchdog's repeatedly-wedged-lane path
        and the probe-convict verdict on a sharded span member): declare
        ``label`` dead directly. True iff this call made the transition
        (idempotent — a second caller is a no-op). A span member that
        hosts no lane gets its health record created here — the sharded
        tier spans every pool device, not just the laned ones."""
        with self._lock:
            h = self._health.get(label)
            if h is None:
                if self.device_by_label(label) is None:
                    return False  # not a pool device at all
                h = self._health[label] = _DeviceHealth()
                self._state_gauge.setdefault(label, self.registry.gauge(
                    "serve_lane_state",
                    "device-lane health (0 healthy, 1 suspect, 2 dead)",
                    device=label))
            if h.state == LANE_DEAD:
                return False
            self._set_state(h, label, LANE_DEAD)
            h.dead_since = time.monotonic()
            h.reason = reason
        self._dead_total.inc()
        events.record("device_dead", severity="error", device=label,
                      reason=reason,
                      message=f"device {label} escalated to dead "
                              f"({reason})")
        log.error("device %s escalated to dead (%s)", label, reason)
        cb = self.on_device_dead
        if cb is not None:
            cb(label)
        return True

    def revive_device(self, label: str) -> bool:
        """The probe path's success: return a dead device to service
        (healthy, streak cleared). True iff it was dead. Each revive is
        timestamped — ``rebalance_sessions`` reads the recent-revive
        history as its flap hysteresis."""
        with self._lock:
            h = self._health.get(label)
            if h is None or h.state != LANE_DEAD:
                return False
            h.failures = 0
            h.dead_since = None
            h.reason = ""
            self._set_state(h, label, LANE_HEALTHY)
            now = time.monotonic()
            self._revives[label] = self._revives.get(label, 0) + 1
            times = self._revive_times.setdefault(label, [])
            times.append(now)
            # Bounded: only stamps inside the flap window matter.
            del times[:max(0, len(times) - 8)]
        events.record("device_revived", severity="info", device=label)
        log.info("device %s revived — rejoining the pool", label)
        return True

    def _healthy_lanes(self) -> list[DeviceLane]:
        """Lanes on non-dead devices (callers hold self._lock)."""
        return [ln for ln in self.lanes
                if self._health.get(ln.label) is None
                or self._health[ln.label].state != LANE_DEAD]

    def retry_lane(self, exclude: str | None = None) -> DeviceLane | None:
        """Least-loaded healthy lane (optionally excluding one device) —
        the cross-lane retry target for a batch that died on its chip.
        None when no healthy lane exists (single-device pool with its
        chip dead: the caller fails the work honestly)."""
        with self._lock:
            lanes = [ln for ln in self._healthy_lanes()
                     if exclude is None or ln.label != exclude]
            if not lanes:
                return None
            load: dict[int, int] = {ln.index: 0 for ln in self.lanes}
            for assigned in self._session_lane.values():
                load[assigned.index] = load.get(assigned.index, 0) + 1
            return min(lanes, key=lambda ln: (load[ln.index], ln.index))

    def repin_sessions(self, dead_label: str) -> dict[str, DeviceLane]:
        """Migrate every sticky session off ``dead_label`` onto
        least-loaded surviving lanes; returns {session_id: new lane}.
        Counts ``serve_lane_repins_total`` per migrated session. The
        caller (service) updates the live ServeSession entries — their
        per-device session programs were warmed at replica start, so
        adoption is compile-free (asserted by the lane-chaos gate)."""
        moved: dict[str, DeviceLane] = {}
        with self._lock:
            survivors = [ln for ln in self._healthy_lanes()
                         if ln.label != dead_label]
            if not survivors:
                return moved
            load: dict[int, int] = {ln.index: 0 for ln in survivors}
            for sid, assigned in self._session_lane.items():
                if assigned.index in load:
                    load[assigned.index] += 1
            for sid, assigned in list(self._session_lane.items()):
                if assigned.label != dead_label:
                    continue
                lane = min(survivors,
                           key=lambda ln: (load[ln.index], ln.index))
                load[lane.index] += 1
                self._session_lane[sid] = lane
                moved[sid] = lane
            if moved:
                # Remember who was displaced: revival rebalancing
                # brings exactly these sessions home.
                self._displaced.setdefault(
                    dead_label, set()).update(moved)
        for sid, lane in moved.items():
            self._repins.inc()
            events.record("session_lane_repin", severity="warning",
                          session_id=sid, from_device=dead_label,
                          to_device=lane.label)
        return moved

    def rebalance_sessions(self, label: str) -> dict[str, DeviceLane]:
        """Revival rebalancing: migrate the sticky sessions that were
        moved OFF ``label`` when it died back onto its lanes; returns
        {session_id: new lane}. Their per-device session programs were
        warmed at replica start (and re-warmed by the revive path), so
        the move is compile-free and finalize stays bitwise.

        Hysteresis: a chip revived more than once inside
        ``rebalance_flap_window_s`` is flapping — its displaced
        sessions stay on the survivors (kept recorded, so the next
        STABLE revival still brings them home) rather than thrashing
        back and forth with every blip."""
        moved: dict[str, DeviceLane] = {}
        with self._lock:
            now = time.monotonic()
            recent = [t for t in self._revive_times.get(label, ())
                      if now - t <= self.rebalance_flap_window_s]
            displaced = self._displaced.pop(label, set())
            if not displaced:
                return moved
            if len(recent) > 1:
                self._displaced[label] = displaced
                events.record(
                    "session_rebalance_deferred", severity="warning",
                    device=label, sessions=len(displaced),
                    revives_in_window=len(recent),
                    message=f"device {label} is flapping "
                            f"({len(recent)} revives in "
                            f"{self.rebalance_flap_window_s:.0f}s); "
                            "keeping displaced sessions on survivors")
                return moved
            targets = [ln for ln in self.lanes if ln.label == label]
            if not targets:
                return moved
            load: dict[int, int] = {ln.index: 0 for ln in targets}
            for assigned in self._session_lane.values():
                if assigned.index in load:
                    load[assigned.index] += 1
            for sid in sorted(displaced):
                cur = self._session_lane.get(sid)
                if cur is None or cur.label == label:
                    continue  # session ended, or already back home
                lane = min(targets,
                           key=lambda ln: (load[ln.index], ln.index))
                load[lane.index] += 1
                self._session_lane[sid] = lane
                moved[sid] = lane
        for sid, lane in moved.items():
            self._rebalances.inc()
            events.record("session_lane_rebalance", severity="info",
                          session_id=sid, to_device=label,
                          to_lane=lane.index)
        return moved

    # -- sharded-fault attribution -------------------------------------

    def note_sharded_ok(self, span) -> None:
        """A clean sharded launch over ``span``: the consecutive-fault
        streak resets (attribution fires only on CONSECUTIVE faults —
        an intermittently healthy span is the hysteresis's no-probe
        case)."""
        with self._lock:
            self._span_failures.pop(tuple(span), None)

    def note_sharded_failure(self, span, reason: str = "") -> int:
        """A device-class fault on a sharded launch over ``span``;
        returns the streak length. The launch error can't name WHICH
        mesh member died, so nothing escalates per device here — at
        ``sharded_suspect_failures`` consecutive faults the pool fires
        ``on_span_suspect(span)`` outside the lock and resets the
        streak (the probe verdict, not further counting, decides)."""
        span = tuple(span)
        fire = False
        with self._lock:
            n = self._span_failures.get(span, 0) + 1
            if n >= self.sharded_suspect_failures:
                self._span_failures.pop(span, None)
                fire = True
            else:
                self._span_failures[span] = n
        self._span_faults.inc()
        events.record("sharded_span_fault", severity="warning",
                      span=list(span), reason=reason, streak=n)
        if fire:
            self._span_probes.inc()
            log.warning(
                "span %s: %d consecutive sharded faults — requesting "
                "per-member probe conviction", "+".join(span), n)
            cb = self.on_span_suspect
            if cb is not None:
                cb(span)
        return n

    # -- program routing ----------------------------------------------

    def span_devices(self, assume_live: str | None = None) -> tuple:
        """The device SET the sharded tier spans RIGHT NOW: sorted
        labels of the widest power-of-two span (≤ ``shard_devices``,
        halving down the 8→4→2 ladder) fillable from the LIVE devices,
        taken in enumeration order with dead members skipped — so one
        early-order dead chip costs the span ONE member, not the whole
        tier. Empty tuple = tier off (fewer than 2 live chips).

        ``assume_live`` treats one (dead) label as live — the revive
        path warms the post-revival span's program BEFORE flipping the
        device back in, keeping the worker hot path compile-free."""
        k = self.shard_devices
        if k < 2:
            return ()
        with self._lock:
            dead = {d for d, h in self._health.items()
                    if h.state == LANE_DEAD}
        dead.discard(assume_live)
        live = [device_label(d) for d in self.devices
                if device_label(d) not in dead]
        while k >= 2:
            if len(live) >= k:
                return tuple(sorted(live[:k]))
            k //= 2
        return ()

    def effective_shard_devices(self) -> int:
        """The span WIDTH the sharded tier can honestly use right now
        (`span_devices`); 0 = tier off. Kept as the stats()/readyz
        scalar — the span set itself is what programs key on."""
        return len(self.span_devices())

    def span_for(self, key: BucketKey) -> tuple:
        """The device span a bucket's launch dispatches over: empty
        (lane-pinned program) unless the sharded tier is enabled, the
        live span covers >1 chip, the bucket meets the size threshold
        AND its row count splits evenly over the span (GSPMD would pad
        an uneven split; refusing keeps the dispatch decision — and the
        warmed program set — exact)."""
        if (self.shard_min_pixels is None
                or key.height * key.width < self.shard_min_pixels):
            return ()
        span = self.span_devices()
        if len(span) < 2 or key.height % len(span):
            return ()
        return span

    def shards_for(self, key: BucketKey) -> int:
        """Shard count for a bucket (``len(span_for(key))``): 0 means a
        lane-pinned program."""
        return len(self.span_for(key))

    def span_program_key(self, key: BucketKey, batch: int,
                         span) -> ProgramKey | None:
        """The sharded ProgramKey (bucket, batch) routes to over an
        EXPLICIT span — the warm paths' view (probe-convict re-form and
        revival compute their target span first, then warm its programs
        off the hot path). None when the bucket wouldn't shard over
        that span."""
        span = tuple(span)
        if (self.shard_min_pixels is None or len(span) < 2
                or key.height * key.width < self.shard_min_pixels
                or key.height % len(span)):
            return None
        return ProgramKey(bucket=key, batch=batch, shards=len(span),
                          span=span)

    def route(self, key: BucketKey, batch: int,
              lane: DeviceLane | None) -> ProgramKey:
        """The ProgramKey a (bucket, batch) launch uses from ``lane``:
        the sharded cross-chip program (set-keyed to the current live
        span) when the bucket qualifies, else the lane's per-device
        program."""
        span = self.span_for(key)
        if span:
            return ProgramKey(bucket=key, batch=batch, shards=len(span),
                              span=span)
        device = (lane.label if lane is not None and self.multi_device
                  else None)
        return ProgramKey(bucket=key, batch=batch, device=device)

    def span_jax_devices(self, span) -> list:
        """The jax.Device objects behind a span, in pool enumeration
        order (mesh row placement must not depend on label sort)."""
        want = set(span)
        return [d for d in self.devices if device_label(d) in want]

    def solve_mesh(self, key: BucketKey):
        """The `parallel/mesh.py` device mesh a sharded bucket's heavy
        postprocess solves (Poisson via ``mesh_from_cloud(device_mesh=
        …)``) span — None for lane-pinned buckets. Memoized: one Mesh
        object per device SET."""
        span = self.span_for(key)
        if not span:
            return None
        with self._lock:
            mesh = self._solve_meshes.get(span)
            if mesh is None:
                from ..parallel import mesh as pmesh

                mesh = pmesh.serve_space_mesh(
                    len(span), devices=self.span_jax_devices(span))
                self._solve_meshes[span] = mesh
            return mesh

    # -- sticky sessions ----------------------------------------------

    def assign_session(self, session_id: str) -> DeviceLane:
        """Sticky placement: the least-loaded lane (fewest live
        sessions; ties break toward the lowest index — deterministic,
        which the placement tests rely on). Idempotent per session.
        Dead-device lanes are skipped — a degraded pool places every
        new session on its surviving chips. The every-lane-dead
        degenerate no longer picks blindly across all lanes: it ranks
        by health state first (suspect before dead — a suspect chip may
        still answer; a dead one won't until a probe revives it), then
        load, so the least-doomed lane wins."""
        with self._lock:
            lane = self._session_lane.get(session_id)
            if lane is not None:
                return lane
            load = {ln.index: 0 for ln in self.lanes}
            for assigned in self._session_lane.values():
                load[assigned.index] = load.get(assigned.index, 0) + 1
            candidates = self._healthy_lanes()
            if candidates:
                lane = min(candidates, key=lambda ln: (load[ln.index],
                                                       ln.index))
            else:
                def rank(ln):
                    h = self._health.get(ln.label)
                    state = h.state if h is not None else LANE_HEALTHY
                    return (_STATE_VALUE[state], load[ln.index],
                            ln.index)
                lane = min(self.lanes, key=rank)
            self._session_lane[session_id] = lane
            return lane

    def lane_for_session(self, session_id: str) -> DeviceLane | None:
        with self._lock:
            return self._session_lane.get(session_id)

    def release_session(self, session_id: str) -> None:
        with self._lock:
            self._session_lane.pop(session_id, None)

    # ------------------------------------------------------------------

    def stats(self) -> dict:
        span = (self.span_devices()
                if self.shard_min_pixels is not None else ())
        with self._lock:
            now = time.monotonic()
            per_lane: dict[int, int] = {ln.index: 0 for ln in self.lanes}
            for lane in self._session_lane.values():
                per_lane[lane.index] = per_lane.get(lane.index, 0) + 1
            states = {label: h.state for label, h in self._health.items()}
            dead = sorted(d for d, s in states.items() if s == LANE_DEAD)
            health = {
                label: {
                    "state": h.state,
                    # Age, not the raw monotonic stamp — scrapers can't
                    # share this process's clock origin.
                    "dead_since_s": (round(now - h.dead_since, 3)
                                     if h.dead_since is not None
                                     else None),
                    "revives": self._revives.get(label, 0),
                }
                for label, h in self._health.items()}
            revives_total = sum(self._revives.values())
        return {
            "devices": [device_label(d) for d in self.devices],
            "lanes": [{"index": ln.index, "device": ln.label,
                       "state": states.get(ln.label, LANE_HEALTHY),
                       "sessions": per_lane.get(ln.index, 0)}
                      for ln in self.lanes],
            # Degraded-pool honesty (the /fleet/signals + /readyz
            # surface): how many chips the pool is actually running on,
            # each tracked device's state/death age/revive count, and
            # the exact span the sharded tier dispatches over.
            "devices_dead": dead,
            "devices_live": len(states) - len(dead),
            "device_health": health,
            "revives_total": revives_total,
            "span_devices": list(span),
            "shard_min_pixels": self.shard_min_pixels,
            "shard_devices": len(span),
        }
