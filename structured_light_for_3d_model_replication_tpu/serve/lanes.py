"""Device lanes: one worker lane per local accelerator chip.

Every `serve/` worker historically launched on the default device — the
fleet tier scaled *replicas* while each process left all but one chip
idle. This module gives the service its device dimension:

* :class:`DeviceLane` — one launch lane pinned to one ``jax.Device``.
  The lane's label (``"cpu:0"``, ``"tpu:3"``) rides the per-device
  :class:`~.cache.ProgramKey`, so AOT executables — and the
  zero-recompile steady-state assertion — stay per-chip.
* :class:`DeviceLanePool` — enumerates ``jax.local_devices()`` once,
  hands out lanes round-robin to the configured worker count, routes
  each (bucket, batch) to either a lane-pinned program or the sharded
  cross-chip tier (``shard_min_pixels``), and owns STICKY session →
  lane placement: a streaming session is assigned the least-loaded
  lane at creation and every stop it submits carries that lane's
  affinity, so the session's jit programs (fuse, refine, preview —
  warmed per lane at replica start) never migrate mid-scan.

The pool is pure bookkeeping — no threads, no device I/O. Constructing
one (without an explicit ``devices`` list) calls ``jax.local_devices()``,
which initializes the backend: set platform/topology flags
(``JAX_PLATFORMS``, ``--xla_force_host_platform_device_count``) before
building a service.
"""

from __future__ import annotations

import dataclasses
import threading

from ..utils.log import get_logger
from .batcher import BucketKey
from .cache import ProgramKey

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class DeviceLane:
    """One launch lane: a worker index pinned to one device."""

    index: int            # lane number (== worker index)
    device: object        # jax.Device
    label: str            # "platform:id", the ProgramKey.device value

    def __repr__(self) -> str:  # device objects repr verbosely
        return f"DeviceLane({self.index}, {self.label})"


def device_label(device) -> str:
    return f"{device.platform}:{device.id}"


class DeviceLanePool:
    """Lane assignment + program routing over the local devices.

    ``n_lanes`` worker lanes spread round-robin over up to
    ``max_devices`` local devices (None = all). ``shard_min_pixels``
    selects the sharded cross-chip tier: a bucket whose padded pixel
    count meets the threshold dispatches ONE program spanning
    ``shard_devices`` chips (rows sharded over the mesh's space axis,
    `parallel/mesh.py`) instead of serializing on a single lane.
    """

    def __init__(self, n_lanes: int = 1, max_devices: int | None = None,
                 shard_min_pixels: int | None = None,
                 shard_devices: int = 0, devices=None):
        if devices is None:
            import jax

            devices = jax.local_devices()
        devices = list(devices)
        if max_devices is not None:
            devices = devices[:max(1, int(max_devices))]
        if not devices:
            raise ValueError("no local devices to build lanes over")
        self.devices = devices
        n_lanes = max(1, int(n_lanes))
        self.lanes = [
            DeviceLane(i, devices[i % len(devices)],
                       device_label(devices[i % len(devices)]))
            for i in range(n_lanes)
        ]
        self.shard_min_pixels = shard_min_pixels
        # The sharded tier needs >= 2 chips to be worth a distinct
        # program; 0 = span every device the pool can see.
        self.shard_devices = (len(devices) if not shard_devices
                              else min(int(shard_devices), len(devices)))
        self._lock = threading.Lock()
        self._session_lane: dict[str, DeviceLane] = {}
        self._solve_meshes: dict[int, object] = {}

    # -- lanes ---------------------------------------------------------

    def lane(self, index: int) -> DeviceLane:
        return self.lanes[index]

    @property
    def n_lanes(self) -> int:
        return len(self.lanes)

    @property
    def multi_device(self) -> bool:
        """True when the lanes actually span more than one chip. A
        single-device pool routes through the HISTORICAL un-pinned
        program keys and takes no session placement — bit-identical to
        the pre-lane service (and its warmed program set)."""
        return len({ln.label for ln in self.lanes}) > 1

    def distinct_devices(self) -> list[DeviceLane]:
        """First lane per distinct device — the warmup iteration set
        (two lanes sharing a chip share its programs)."""
        seen: dict[str, DeviceLane] = {}
        for lane in self.lanes:
            seen.setdefault(lane.label, lane)
        return list(seen.values())

    # -- program routing ----------------------------------------------

    def shards_for(self, key: BucketKey) -> int:
        """Shard count for a bucket: 0 (lane-pinned program) unless the
        sharded tier is enabled, spans >1 chip, the bucket meets the
        size threshold AND its row count splits evenly over the mesh
        (GSPMD would pad an uneven split; refusing keeps the dispatch
        decision — and the warmed program set — exact)."""
        if (self.shard_min_pixels is None or self.shard_devices < 2
                or key.height * key.width < self.shard_min_pixels
                or key.height % self.shard_devices):
            return 0
        return self.shard_devices

    def route(self, key: BucketKey, batch: int,
              lane: DeviceLane | None) -> ProgramKey:
        """The ProgramKey a (bucket, batch) launch uses from ``lane``:
        the sharded cross-chip program when the bucket qualifies, else
        the lane's per-device program."""
        shards = self.shards_for(key)
        if shards:
            return ProgramKey(bucket=key, batch=batch, shards=shards)
        device = (lane.label if lane is not None and self.multi_device
                  else None)
        return ProgramKey(bucket=key, batch=batch, device=device)

    def solve_mesh(self, key: BucketKey):
        """The `parallel/mesh.py` device mesh a sharded bucket's heavy
        postprocess solves (Poisson via ``mesh_from_cloud(device_mesh=
        …)``) span — None for lane-pinned buckets. Memoized: one Mesh
        object per shard count."""
        shards = self.shards_for(key)
        if not shards:
            return None
        with self._lock:
            mesh = self._solve_meshes.get(shards)
            if mesh is None:
                from ..parallel import mesh as pmesh

                mesh = pmesh.serve_space_mesh(
                    shards, devices=self.devices[:shards])
                self._solve_meshes[shards] = mesh
            return mesh

    # -- sticky sessions ----------------------------------------------

    def assign_session(self, session_id: str) -> DeviceLane:
        """Sticky placement: the least-loaded lane (fewest live
        sessions; ties break toward the lowest index — deterministic,
        which the placement tests rely on). Idempotent per session."""
        with self._lock:
            lane = self._session_lane.get(session_id)
            if lane is not None:
                return lane
            load = {ln.index: 0 for ln in self.lanes}
            for assigned in self._session_lane.values():
                load[assigned.index] = load.get(assigned.index, 0) + 1
            lane = min(self.lanes, key=lambda ln: (load[ln.index],
                                                   ln.index))
            self._session_lane[session_id] = lane
            return lane

    def lane_for_session(self, session_id: str) -> DeviceLane | None:
        with self._lock:
            return self._session_lane.get(session_id)

    def release_session(self, session_id: str) -> None:
        with self._lock:
            self._session_lane.pop(session_id, None)

    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            per_lane: dict[int, int] = {ln.index: 0 for ln in self.lanes}
            for lane in self._session_lane.values():
                per_lane[lane.index] = per_lane.get(lane.index, 0) + 1
        return {
            "devices": [device_label(d) for d in self.devices],
            "lanes": [{"index": ln.index, "device": ln.label,
                       "sessions": per_lane.get(ln.index, 0)}
                      for ln in self.lanes],
            "shard_min_pixels": self.shard_min_pixels,
            "shard_devices": (self.shard_devices
                              if self.shard_min_pixels is not None else 0),
        }
