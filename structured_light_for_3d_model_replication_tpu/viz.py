"""Offline visualization: point clouds and meshes rendered to PNG.

The reference's quality loop leans on interactive Open3D viewers at every
stage — inlier/outlier coloring (`Old/StatisticalOutlierRemoval.py:66-71`),
before/after pair alignment (`Old/New360.py:72-73`), plane-split preview
(`Old/blackground_remove.py:23`), and the final mesh (`Old/360Merge.py:125`,
`Old/new360Merge.py:190`). This build is headless (TPU pods have no
display), so the equivalent is an offline renderer: numpy z-buffer splats
for clouds, batched barycentric rasterization for meshes, written to PNG by
a dependency-free encoder. Every reference "viewer moment" has a
corresponding helper here, wired to ``cli view`` and the GUI preview
buttons, and each is asserted on pixel content in ``tests/test_viz.py``.

All functions are pure host-side numpy: rendering is a debugging/preview
path, never on the device hot path.
"""

from __future__ import annotations

import numpy as np

# Default palette (RGB, 0-255). Matches the reference's viewer conventions:
# grey inliers / red outliers (`Old/StatisticalOutlierRemoval.py:66-68`),
# orange source / blue target for pairs (o3d example convention used by
# `Old/New360.py:63-66`).
INLIER_GREY = (200, 200, 200)
OUTLIER_RED = (230, 50, 40)
PAIR_ORANGE = (255, 166, 28)
PAIR_BLUE = (43, 120, 228)
PLANE_GREEN = (80, 200, 120)
MESH_BONE = (226, 221, 205)
BACKGROUND = (18, 20, 26)


# ----------------------------------------------------------------------
# PNG I/O: the shared stdlib-only encoder lives in `io/png.py` (the
# splat render endpoints and cli render need BYTES, not files); these
# wrappers keep the historical viz surface.
# ----------------------------------------------------------------------

def save_png(path, image: np.ndarray) -> None:
    """Write an (H, W, 3) uint8 image as an RGB PNG."""
    from .io.png import write_png

    write_png(path, image)


def load_png(path) -> np.ndarray:
    """Read back an RGB PNG written by :func:`save_png` (filter 0 only —
    round-trip/testing helper, not a general decoder)."""
    from .io.png import decode_png

    with open(path, "rb") as f:
        return decode_png(f.read())


# ----------------------------------------------------------------------
# Camera
# ----------------------------------------------------------------------

def _orbit_camera(points: np.ndarray, azim_deg: float, elev_deg: float,
                  zoom: float):
    """(R, eye, f_scale): world→camera rotation and eye position orbiting
    the cloud's bounding-sphere center. Camera looks +z at the center."""
    lo = np.min(points, axis=0)
    hi = np.max(points, axis=0)
    center = 0.5 * (lo + hi)
    radius = max(float(np.linalg.norm(hi - lo)) * 0.5, 1e-6)
    dist = zoom * radius

    az = np.deg2rad(azim_deg)
    el = np.deg2rad(elev_deg)
    # Eye on the orbit sphere; y is up (turntable axis convention).
    off = np.array([np.sin(az) * np.cos(el), np.sin(el),
                    -np.cos(az) * np.cos(el)])
    eye = center + dist * off
    fwd = center - eye
    fwd /= np.linalg.norm(fwd)
    up = np.array([0.0, -1.0, 0.0])  # image +y down
    right = np.cross(fwd, up)
    nr = np.linalg.norm(right)
    if nr < 1e-9:  # looking straight along y
        right = np.array([1.0, 0.0, 0.0])
    else:
        right /= nr
    dn = np.cross(fwd, right)
    R = np.stack([right, -dn, fwd])  # rows: x, y, z of camera frame
    return R, eye, radius


def _project(points: np.ndarray, R, eye, width, height, fov_scale=1.15):
    """Project world points with the orbit pinhole. Returns (u, v, z, ok)."""
    pc = (points - eye) @ R.T
    z = pc[:, 2]
    ok = z > 1e-6
    zs = np.where(ok, z, 1.0)
    f = fov_scale * min(width, height) * 0.5
    u = pc[:, 0] / zs * f + (width - 1) * 0.5
    v = pc[:, 1] / zs * f + (height - 1) * 0.5
    ok &= (u > -2) & (u < width + 1) & (v > -2) & (v < height + 1)
    return u, v, z, ok


def _blank(width, height, bg):
    img = np.empty((height, width, 3), np.uint8)
    img[:] = np.asarray(bg, np.uint8)
    return img


def _splat(img, zbuf, u, v, z, colors, point_px):
    """Z-buffered square splats of ``point_px`` pixels."""
    h, w = img.shape[:2]
    ui = np.round(u).astype(np.int64)
    vi = np.round(v).astype(np.int64)
    r = range(-(point_px // 2), point_px - point_px // 2)
    for dy in r:
        for dx in r:
            x = ui + dx
            y = vi + dy
            inb = (x >= 0) & (x < w) & (y >= 0) & (y < h)
            flat = y[inb] * w + x[inb]
            zz = z[inb]
            cc = colors[inb]
            # Two-pass z-buffer: scatter-min depth, then write colors where
            # the depth matches the winner (ties resolved arbitrarily —
            # fine for previews).
            np.minimum.at(zbuf.reshape(-1), flat, zz)
            win = zbuf.reshape(-1)[flat] == zz
            img.reshape(-1, 3)[flat[win]] = cc[win]


def render_points(points, colors=None, *, width: int = 960,
                  height: int = 720, azim: float = 30.0, elev: float = 20.0,
                  zoom: float = 2.1, point_px: int = 2,
                  bg=BACKGROUND, camera=None) -> np.ndarray:
    """Render a point cloud to an (H, W, 3) uint8 image.

    ``colors``: (N, 3) uint8/float per-point colors, or None for depth-cued
    grey. Empty clouds render as background. ``camera``: optional
    precomputed ``(R, eye)`` pose overriding the per-cloud orbit fit — for
    multi-panel renders that must share one viewpoint (see
    :func:`render_pair`).
    """
    pts = np.asarray(points, np.float64).reshape(-1, 3)
    img = _blank(width, height, bg)
    if pts.shape[0] == 0:
        return img
    if camera is None:
        R, eye, _ = _orbit_camera(pts, azim, elev, zoom)
    else:
        R, eye = camera
    u, v, z, ok = _project(pts, R, eye, width, height)
    if colors is None:
        # Depth cue: nearer → brighter.
        zn = (z - z.min()) / max(float(np.ptp(z)), 1e-9)
        g = (235 - 120 * zn).astype(np.uint8)
        cols = np.stack([g, g, g], axis=1)
    else:
        cols = np.asarray(colors)
        if cols.dtype != np.uint8:
            cols = np.clip(cols, 0, 255).astype(np.uint8)
        cols = np.broadcast_to(cols.reshape(-1, 3), pts.shape).copy()
    zbuf = np.full((height, width), np.inf, np.float64)
    _splat(img, zbuf, u[ok], v[ok], z[ok], cols[ok], point_px)
    return img


# ----------------------------------------------------------------------
# Mesh rendering: batched barycentric sample-splat with z-buffer.
# ----------------------------------------------------------------------

def render_mesh(vertices, faces, *, width: int = 960, height: int = 720,
                azim: float = 30.0, elev: float = 20.0, zoom: float = 2.1,
                color=MESH_BONE, bg=BACKGROUND) -> np.ndarray:
    """Render a triangle mesh with Lambert shading to (H, W, 3) uint8.

    Rasterization is vectorized sample-splatting: each face is covered by a
    G×G barycentric sample grid, G bucketed by the face's projected size so
    small faces stay cheap and large faces don't leave holes; samples are
    z-buffered square splats. Preview-grade (ties/edges are approximate),
    which is all the reference's viewer moments need.
    """
    verts = np.asarray(vertices, np.float64).reshape(-1, 3)
    tris = np.asarray(faces, np.int64).reshape(-1, 3)
    img = _blank(width, height, bg)
    if verts.shape[0] == 0 or tris.shape[0] == 0:
        return img
    R, eye, radius = _orbit_camera(verts, azim, elev, zoom)
    u, v, z, okv = _project(verts, R, eye, width, height)

    # Face shading: headlight Lambert + a little fill, on world normals.
    e1 = verts[tris[:, 1]] - verts[tris[:, 0]]
    e2 = verts[tris[:, 2]] - verts[tris[:, 0]]
    fn = np.cross(e1, e2)
    nn = np.linalg.norm(fn, axis=1, keepdims=True)
    fn = fn / np.maximum(nn, 1e-12)
    view = (verts[tris[:, 0]] + verts[tris[:, 1]] + verts[tris[:, 2]]) / 3.0
    vd = eye - view
    vd /= np.maximum(np.linalg.norm(vd, axis=1, keepdims=True), 1e-12)
    lam = np.abs(np.sum(fn * vd, axis=1))  # double-sided headlight
    key = np.array([0.25, 0.5, 0.83])  # a second light for shape reading
    lam2 = np.abs(fn @ key)
    shade = np.clip(0.18 + 0.66 * lam + 0.22 * lam2, 0.0, 1.0)
    base = np.asarray(color, np.float64)
    fcol = np.clip(shade[:, None] * base[None, :], 0, 255).astype(np.uint8)

    ok_f = okv[tris].all(axis=1)
    ut, vt, zt = u[tris], v[tris], z[tris]
    ext = np.maximum(ut.max(1) - ut.min(1), vt.max(1) - vt.min(1))

    zbuf = np.full((height, width), np.inf, np.float64)
    # Size buckets: G samples per edge ≈ projected pixel extent, so splat
    # coverage is gap-free at point_px=2.
    for g, lo, hi in ((2, 0.0, 3.0), (4, 3.0, 7.0), (8, 7.0, 15.0),
                      (16, 15.0, 31.0), (40, 31.0, np.inf)):
        sel = ok_f & (ext >= lo) & (ext < hi)
        if not np.any(sel):
            continue
        # Barycentric grid covering the triangle.
        a = np.linspace(0.0, 1.0, g + 1)
        bb, aa = np.meshgrid(a, a)
        keep = aa + bb <= 1.0 + 1e-9
        w0 = (1.0 - aa - bb)[keep]
        w1 = aa[keep]
        w2 = bb[keep]  # (S,)
        us = (ut[sel, 0, None] * w0 + ut[sel, 1, None] * w1
              + ut[sel, 2, None] * w2).ravel()
        vs = (vt[sel, 0, None] * w0 + vt[sel, 1, None] * w1
              + vt[sel, 2, None] * w2).ravel()
        zs = (zt[sel, 0, None] * w0 + zt[sel, 1, None] * w1
              + zt[sel, 2, None] * w2).ravel()
        cs = np.repeat(fcol[sel], w0.shape[0], axis=0)
        _splat(img, zbuf, us, vs, zs, cs, 2)
    return img


# ----------------------------------------------------------------------
# Reference "viewer moments"
# ----------------------------------------------------------------------

def render_inliers(points, keep_mask, **kw) -> np.ndarray:
    """Inlier/outlier coloring: grey survivors, red rejects — the offline
    twin of `Old/StatisticalOutlierRemoval.py:66-71`."""
    pts = np.asarray(points, np.float64).reshape(-1, 3)
    keep = np.asarray(keep_mask, bool).reshape(-1)
    cols = np.where(keep[:, None], np.uint8(INLIER_GREY),
                    np.uint8(OUTLIER_RED))
    return render_points(pts, cols, **kw)


def render_plane_split(points, plane_mask, **kw) -> np.ndarray:
    """Plane-segmentation preview: plane green, object grey — the offline
    twin of `Old/blackground_remove.py:23`."""
    pts = np.asarray(points, np.float64).reshape(-1, 3)
    pm = np.asarray(plane_mask, bool).reshape(-1)
    cols = np.where(pm[:, None], np.uint8(PLANE_GREEN),
                    np.uint8(INLIER_GREY))
    return render_points(pts, cols, **kw)


def render_pair(source, target, transform=None, *, width: int = 1280,
                height: int = 480, point_px: int = 2, azim: float = 30.0,
                elev: float = 20.0, zoom: float = 2.1, **kw) -> np.ndarray:
    """Before/after registration panel — the offline twin of
    `Old/New360.py:72-73`.

    Left half: source (orange) and target (blue) as given. Right half: the
    same pair with ``transform`` (4×4, applied to source). With
    ``transform=None`` both halves show the raw pair. BOTH panels share one
    camera, fitted to the union of {source, moved source, target} — a
    per-panel orbit fit would change viewpoint/scale when the transform
    moves the source, making the halves incomparable.
    """
    src = np.asarray(source, np.float64).reshape(-1, 3)
    dst = np.asarray(target, np.float64).reshape(-1, 3)
    half_w = width // 2

    if transform is not None:
        t = np.asarray(transform, np.float64).reshape(4, 4)
        moved = src @ t[:3, :3].T + t[:3, 3]
    else:
        moved = src
    union = np.concatenate([src, moved, dst], axis=0)
    cam = None
    if union.shape[0]:
        R, eye, _ = _orbit_camera(union, azim, elev, zoom)
        cam = (R, eye)

    def panel(s):
        pts = np.concatenate([s, dst], axis=0)
        cols = np.concatenate(
            [np.tile(np.uint8(PAIR_ORANGE), (len(s), 1)),
             np.tile(np.uint8(PAIR_BLUE), (len(dst), 1))], axis=0)
        return render_points(pts, cols, width=half_w, height=height,
                             point_px=point_px, camera=cam, **kw)

    left = panel(src)
    right = panel(moved)
    out = np.concatenate([left, right], axis=1)
    out[:, half_w - 1:half_w + 1] = 90  # seam
    return out
