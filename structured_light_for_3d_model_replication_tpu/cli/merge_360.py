"""``merge-360``: register and merge a folder of per-stop PLYs.

The GUI merge action (`server/gui.py:622-641` → `merge_pro_360`,
`server/processing.py:115-181`) plus the strictly-better pose-graph variant
from the legacy scripts (`Old/360Merge.py`, `Old/new360Merge.py`) behind
``--method``.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="merge-360",
        description="Register+merge a folder of .ply scans (numeric order)")
    p.add_argument("--input", "-i", required=True,
                   help="folder of per-stop .ply files")
    p.add_argument("--output", "-o", required=True, help="merged .ply")
    p.add_argument("--method", choices=("posegraph", "sequential"),
                   default="posegraph")
    p.add_argument("--voxel-size", type=float, default=0.02,
                   help="registration/cleanup voxel (reference default 0.02, "
                        "server/processing.py:115)")
    p.add_argument("--ransac-iterations", type=int, default=100_000)
    p.add_argument("--icp-iterations", type=int, default=30)
    p.add_argument("--max-points", type=int, default=16_384,
                   help="per-scan registration point cap")
    p.add_argument("--no-loop-closure", action="store_true",
                   help="pose-graph without the first↔last edge")
    g = p.add_argument_group("quality gates (docs/ROBUSTNESS.md)")
    g.add_argument("--no-gates", action="store_true",
                   help="disable the per-edge registration gates")
    g.add_argument("--min-edge-fitness", type=float, default=0.2,
                   help="reject ring edges below this ICP fitness "
                        "(consensus-repaired / down-weighted)")
    g.add_argument("--max-edge-rmse", type=float, default=None,
                   help="optional absolute inlier-RMSE ceiling per edge")
    g.add_argument("--step-deg", type=float, default=None,
                   help="commanded turntable advance per stop; anchors the "
                        "consensus repair of rejected edges")
    g.add_argument("--health-json", default=None, metavar="PATH",
                   help="write the merge health report (edge verdicts, "
                        "repairs) as JSON here")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from ..health import QualityGates, ScanHealthReport
    from ..models import merge

    params = merge.MergeParams(
        voxel_size=args.voxel_size,
        ransac_iterations=args.ransac_iterations,
        icp_iterations=args.icp_iterations,
        max_points=args.max_points,
        loop_closure=not args.no_loop_closure,
        step_deg=args.step_deg,
    )
    gates = None if args.no_gates else QualityGates(
        min_edge_fitness=args.min_edge_fitness,
        max_edge_rmse=args.max_edge_rmse)
    health = ScanHealthReport()
    merged = merge.merge_360_files(args.input, args.output, params=params,
                                   method=args.method, gates=gates,
                                   health=health)
    print(f"merged -> {args.output} ({len(merged)} points)", file=sys.stderr)
    if health.rejected_edges:
        print(f"degraded: {len(health.rejected_edges)} edge(s) rejected and "
              f"repaired (see --health-json)", file=sys.stderr)
    health.emit()
    if args.health_json:
        health.write(args.health_json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
