"""``process-cloud``: decode + triangulate capture folder(s) into PLYs.

CLI parity with the reference's two batch paths in one tool:

* `Old/process_cloud.py:221-236` — ``--input/--output/--calib`` single run;
* `multi_point_cloud_process.py` — one calibration + MANY scan folders
  (its batch GUI walks subfolders, `:242-257`), with the FIXED decode
  thresholds (white>40, contrast>10, `:36-38`); pass ``--thresholds fixed``
  for that behavior, default is the adaptive variant of
  `server/sl_system.py:526-535`.
"""

from __future__ import annotations

import argparse
import math
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="process-cloud",
        description="Decode+triangulate structured-light scan folders to PLY")
    p.add_argument("--input", "-i", required=True, nargs="+",
                   help="scan folder(s) with protocol-ordered frames; a "
                        "folder whose subfolders hold frames is treated as "
                        "a batch root")
    p.add_argument("--calib", "-c", required=True, help=".mat calibration")
    p.add_argument("--output", "-o", required=True,
                   help="output .ply (single input) or output dir (batch)")
    p.add_argument("--thresholds", choices=("adaptive", "fixed"),
                   default="adaptive")
    p.add_argument("--white-thresh", type=float, default=40.0)
    p.add_argument("--contrast-thresh", type=float, default=10.0)
    p.add_argument("--plane-axis", choices=("col", "row", "both"),
                   default="col",
                   help="triangulation planes (reference uses col only, "
                        "server/sl_system.py:624-629)")
    p.add_argument("--ascii", action="store_true",
                   help="ASCII PLY (reference-writer compatible) instead of "
                        "binary")
    return p


def has_frames(folder: str) -> bool:
    from ..io.images import list_frames

    try:
        return bool(list_frames(folder))
    except FileNotFoundError:
        return False


def _expand_batch(inputs):
    """A directory whose subdirectories contain frames is a batch root
    (`multi_point_cloud_process.py:242-257`)."""
    dirs = []
    for d in inputs:
        if has_frames(d):
            dirs.append(d)
            continue
        subs = sorted(
            os.path.join(d, s) for s in os.listdir(d)
            if os.path.isdir(os.path.join(d, s)))
        frame_subs = [s for s in subs if has_frames(s)]
        if not frame_subs:
            raise SystemExit(f"{d}: no frames and no frame subfolders")
        dirs.extend(frame_subs)
    return dirs


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import jax.numpy as jnp

    from ..config import DecodeConfig, TriangulationConfig
    from ..io import images as img_io
    from ..io import matcal
    from ..io import ply as ply_io
    from ..models import pipeline

    scan_dirs = _expand_batch(args.input)
    batch = len(scan_dirs) > 1
    if batch:
        os.makedirs(args.output, exist_ok=True)

    decode_cfg = DecodeConfig(
        mode=args.thresholds,
        white_thresh=args.white_thresh,
        contrast_thresh=args.contrast_thresh)
    tri_cfg = TriangulationConfig(plane_axis=args.plane_axis)

    calib = None
    for d in scan_dirs:
        stack = img_io.load_stack(d)
        f, h, w = stack.shape
        if calib is None:
            calib = matcal.load_calibration_mat(args.calib, h, w)
            col_bits = math.ceil(math.log2(calib.plane_cols.shape[0]))
            row_bits = math.ceil(math.log2(calib.plane_rows.shape[0]))
            expect = 2 + 2 * (col_bits + row_bits)
            if f != expect:
                raise SystemExit(
                    f"{d}: {f} frames but calibration implies {expect}")
        res = pipeline.reconstruct(jnp.asarray(stack), calib, col_bits,
                                   row_bits, decode_cfg=decode_cfg,
                                   tri_cfg=tri_cfg)
        cloud = pipeline.to_point_cloud(res)
        out = (os.path.join(args.output,
                            os.path.basename(d.rstrip("/")) + ".ply")
               if batch else args.output)
        ply_io.write_ply(out, cloud, binary=not args.ascii)
        print(f"{d}: {len(cloud)} points -> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
