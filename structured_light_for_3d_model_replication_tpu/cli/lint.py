"""``cli lint`` — the jaxlint gate as a first-class CLI tool.

A thin front end over ``python -m …analysis`` (docs/JAXLINT.md) so the
static-analysis suite sits next to ``serve``/``diagnose`` in the
operator's toolbox::

    cli lint                 # full two-pass check of the repo (cwd)
    cli lint --fast          # lexical rules only (seconds)
    cli lint --sarif out.sarif
    cli lint path/to/subtree --prune-baseline

Arguments before the first ``--`` flag are the paths to check
(default ``.``); every ``analysis`` flag passes through unchanged.
Exit codes are the gate's: 0 clean (warnings allowed), 1 new
error-tier violations, 2 usage / bad baseline / dead baseline entries.
"""

from __future__ import annotations


def main(argv=None) -> int:
    from ..analysis.__main__ import main as analysis_main

    argv = list(argv or [])
    if "--list-rules" in argv:
        return analysis_main(["--list-rules"])
    paths = []
    while argv and not argv[0].startswith("-"):
        paths.append(argv.pop(0))
    return analysis_main(["--check", *(paths or ["."]), *argv])
