"""``serve``: the continuous-batching reconstruction service.

Runs the `serve/` subsystem headless: bounded admission queue, bucketed
continuous batcher, warmed program cache, device worker(s), and the HTTP
front end (submit/status/result + /healthz + /metrics). SIGTERM/SIGINT
drain gracefully: in-flight jobs finish, new submissions get a retryable
503, workers exit, then the listener closes. docs/SERVING.md covers the
endpoints and tuning (bucket shapes, linger, queue bound).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def _parse_buckets(spec: str) -> tuple:
    out = []
    for part in spec.split(","):
        h, _, w = part.strip().partition("x")
        out.append((int(h), int(w)))
    if not out:
        raise ValueError(f"no buckets in {spec!r}")
    return tuple(out)


def build_parser() -> argparse.ArgumentParser:
    # Defaults come FROM ServeConfig (the documented tuning surface) so
    # the CLI, in-process users (bench, tests) and docs/SERVING.md can't
    # silently drift apart.
    from ..serve.service import ServeConfig

    d = ServeConfig()
    p = argparse.ArgumentParser(
        prog="serve",
        description="Continuous-batching scan-reconstruction service")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8090,
                   help="0 = pick a free port (printed on stderr)")
    p.add_argument("--queue-depth", type=int, default=d.queue_depth,
                   help="bounded admission queue; above it submits get "
                        "429 + Retry-After")
    p.add_argument("--linger-ms", type=float, default=d.linger_ms,
                   help="max wait for batch company before a partial "
                        "bucket flushes")
    p.add_argument("--workers", type=int, default=d.workers,
                   help="device launch lanes (one per chip: --workers 8 "
                        "on an 8-chip host runs 8 pinned lanes pulling "
                        "from one queue — docs/SERVING.md § multi-chip)")
    p.add_argument("--devices", type=int, default=d.devices,
                   help="spread worker lanes over at most this many "
                        "local devices (default: all visible)")
    p.add_argument("--shard-min-pixels", type=int,
                   default=d.shard_min_pixels,
                   help="buckets with padded H*W at or above this "
                        "dispatch ONE cross-chip sharded program "
                        "(camera rows over the device mesh) instead of "
                        "serializing on a single lane; unset = off")
    p.add_argument("--shard-devices", type=int, default=d.shard_devices,
                   help="chips the sharded big-bucket tier spans "
                        "(0 = all visible)")
    p.add_argument("--buckets",
                   default=",".join(f"{h}x{w}" for h, w in d.buckets),
                   help="comma-separated padded HxW shapes, e.g. "
                        "'1080x1920,2160x3840'")
    p.add_argument("--batch-sizes",
                   default=",".join(str(b) for b in d.batch_sizes),
                   help="allowed batch sizes (compiled per bucket)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip startup precompilation (first requests of "
                        "each shape will pay the compile)")
    p.add_argument("--mesh-depth", type=int, default=d.mesh_depth,
                   help="Poisson depth for STL results")
    p.add_argument("--max-sessions", type=int, default=d.max_sessions,
                   help="bounded live streaming-session registry "
                        "(docs/STREAMING.md); above it POST /session "
                        "gets a retryable 503")
    p.add_argument("--preview-depth", type=int,
                   default=d.stream.preview_depth,
                   help="coarse Poisson depth of per-stop session "
                        "previews (finalize uses the full depth)")
    p.add_argument("--representation",
                   choices=("tsdf", "archival", "poisson", "splat"),
                   default=d.stream.representation,
                   help="default session scene representation "
                        "(docs/STREAMING.md): 'tsdf' (default) previews "
                        "integrate incrementally (fusion/), finalize is "
                        "integrate-don't-re-solve, and meshes carry "
                        "vertex color; 'archival' keeps TSDF previews "
                        "but finalizes via the watertight Poisson solve "
                        "(the print/archive format); 'poisson' is the "
                        "legacy re-solve lane; 'splat' adds rendered "
                        "novel views (GET /session/<id>/render, "
                        "docs/RENDERING.md); per-session override via "
                        "the POST /session body")
    p.add_argument("--mesh-representation", choices=("poisson", "tsdf"),
                   default=d.mesh_representation,
                   help="scene representation for one-shot STL/mesh_ply "
                        "results (docs/MESHING.md)")
    p.add_argument("--no-session-warmup", action="store_true",
                   help="skip the session-lane program warmup (the "
                        "first session — or a failover adoption — will "
                        "pay those compiles)")
    p.add_argument("--proj-width", type=int, default=d.proj.width,
                   help="projector width (fixes the protocol bit count)")
    p.add_argument("--proj-height", type=int, default=d.proj.height)
    p.add_argument("--calib", default=None,
                   help="reference-layout .mat calibration; default is "
                        "the synthetic rig (per-bucket)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="max seconds to wait for in-flight jobs on "
                        "SIGTERM")
    # -- durability (docs/SERVING.md § durability) ----------------------
    p.add_argument("--store-dir", default=None,
                   help="journal volume (crash-safe WAL of admissions + "
                        "session stops, persistent content cache); "
                        "unset = in-memory service")
    p.add_argument("--recover", action="store_true",
                   help="replay the --store-dir journal at startup: "
                        "re-queue non-terminal jobs, rebuild live "
                        "sessions (requires --store-dir)")
    p.add_argument("--no-content-cache", action="store_true",
                   help="disable the content-hash result cache "
                        "(duplicate submits recompute)")
    p.add_argument("--stream-json", default=None,
                   help="JSON overrides for the session StreamParams, "
                        "e.g. '{\"method\":\"sequential\",\"merge\":"
                        "{\"voxel_size\":4.0}}' — a 'merge' sub-object "
                        "overrides MergeParams. Fixed at startup (it "
                        "keys compiled programs)")
    # -- fleet tier (docs/SERVING.md § fleet) ---------------------------
    p.add_argument("--replica-id", default=None,
                   help="stable replica identity (journaled session "
                        "heads, handoff ownership); default: random "
                        "per process")
    p.add_argument("--peers", default=None,
                   help="comma-separated peer base URLs — a local "
                        "content-cache miss consults their "
                        "GET /cache/<key> before computing")
    p.add_argument("--handoff-dir", default=None,
                   help="shared session-handoff store (requires "
                        "--store-dir): session ops stream there so a "
                        "survivor replica can adopt this replica's "
                        "live sessions after a crash. A local "
                        "directory, or an object-store spec "
                        "http://host:port[/prefix] — replicas then "
                        "share no filesystem (docs/SERVING.md § fleet)")
    p.add_argument("--tenant-rate", type=float,
                   default=d.tenant_rate_per_s,
                   help="per-tenant admission quota: sustained "
                        "admissions/s per X-Tenant (0 = off); refusals "
                        "are retryable 429s with per-tenant "
                        "serve_tenant_* metrics")
    p.add_argument("--tenant-burst", type=int, default=d.tenant_burst,
                   help="per-tenant token-bucket burst headroom")
    p.add_argument("--tenant-cost-weighted", action="store_true",
                   help="weight the per-tenant token spend by stack "
                        "MEGAPIXELS instead of 1-per-submit (a 4K scan "
                        "costs ~8x a 1080p one; --tenant-rate becomes "
                        "sustained megapixels/s)")
    p.add_argument("--router", action="store_true",
                   help="run the thin fleet FRONT ROUTER instead of a "
                        "replica: consistent-hash admission, sticky "
                        "sessions with handoff, /readyz-driven "
                        "failover + proactive re-pin (requires "
                        "--replicas)")
    p.add_argument("--replicas", default=None,
                   help="comma-separated replica base URLs the router "
                        "fronts (--router mode only)")
    p.add_argument("--check-interval", type=float, default=1.0,
                   help="router /readyz health-sweep period in seconds")
    p.add_argument("--router-id", default=None,
                   help="stable router identity (pin-board records, "
                        "detector-primary election); default: random "
                        "per process")
    p.add_argument("--router-peers", default=None,
                   help="comma-separated PEER ROUTER base URLs: peers "
                        "are health-probed, share the pin board, and "
                        "elect one detector primary (docs/SERVING.md "
                        "§ fleet, dual-router topology)")
    p.add_argument("--pin-store", default=None,
                   help="shared pin-board store for router HA: a local "
                        "directory or object-store spec "
                        "http://host:port[/prefix]; session pins are "
                        "generation-stamped last-writer-wins records "
                        "every peered router converges on")
    p.add_argument("--no-proactive-repin", action="store_true",
                   help="disable the failure detector's background "
                        "session adoption (failover falls back to the "
                        "lazy next-op re-pin)")
    return p


def _run_router(args) -> int:
    """``serve --router``: the thin fleet front (serve/router.py). It
    holds no reconstruction state and never touches a device, but the
    import of the serve package still pulls jax (service.py is a
    sibling), so run it where the repo's deps are installed."""
    import json

    from ..serve.fleet import transport_from_env
    from ..serve.router import FleetRouter, RouterHTTPServer

    replicas = [u.strip() for u in (args.replicas or "").split(",")
                if u.strip()]
    if not replicas:
        print("error: --router requires --replicas url1,url2,...",
              file=sys.stderr)
        return 2
    peers = [u.strip() for u in (args.router_peers or "").split(",")
             if u.strip()]
    router = FleetRouter(replicas,
                         check_interval_s=args.check_interval,
                         transport=transport_from_env(),
                         router_id=args.router_id,
                         router_peers=peers,
                         pin_store=args.pin_store,
                         proactive_repin=not args.no_proactive_repin)
    http = RouterHTTPServer(router, host=args.host,
                            port=args.port).start()
    # Machine-parseable readiness line (fleet smoke greps it).
    print(f"routing on :{http.port}", file=sys.stderr, flush=True)

    stop = threading.Event()

    def _graceful(signum, frame):
        print(f"signal {signum}: router stopping...", file=sys.stderr,
              flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    stop.wait()
    print(json.dumps(router.stats()), file=sys.stderr, flush=True)
    http.stop()
    print("router stopped", file=sys.stderr, flush=True)
    return 0


def _stream_params(base, spec: str | None):
    """Apply ``--stream-json`` overrides onto the default StreamParams
    (nested ``merge`` dict → MergeParams replace)."""
    import dataclasses

    if not spec:
        return base
    import json

    doc = json.loads(spec)
    if not isinstance(doc, dict):
        raise ValueError("--stream-json must be a JSON object")
    merge_over = doc.pop("merge", None)
    merge = base.merge
    if merge_over:
        merge = dataclasses.replace(merge, **merge_over)
    return dataclasses.replace(base, merge=merge, **doc)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.router:
        return _run_router(args)

    from ..config import ProjectorConfig
    from ..serve.service import (
        ReconstructionService,
        ServeConfig,
        ServeHTTPServer,
        fixed_calib_provider,
    )
    from ..utils import sanitize

    # SL_SANITIZE=1 arms the runtime sanitizers for a REAL service too
    # (docs/JAXLINT.md): the lock-order checker must install before the
    # service constructs its queue/cache/worker locks.
    sanitize.install_if_enabled()

    proj = ProjectorConfig(width=args.proj_width, height=args.proj_height)
    buckets = _parse_buckets(args.buckets)
    if args.calib is not None and len(buckets) != 1:
        # A .mat calibration describes ONE camera geometry; warmup of any
        # other bucket would die mid-start with a provider error. Refuse
        # the contradiction up front.
        print(f"error: --calib serves exactly one bucket, got "
              f"{args.buckets!r} — pass the single HxW matching the "
              "calibration's camera", file=sys.stderr)
        return 2
    if args.recover and args.store_dir is None:
        print("error: --recover requires --store-dir (the journal "
              "volume to replay)", file=sys.stderr)
        return 2
    if args.handoff_dir is not None and args.store_dir is None:
        print("error: --handoff-dir requires --store-dir (the handoff "
              "stream rides the WAL's group commit)", file=sys.stderr)
        return 2
    import dataclasses

    defaults = ServeConfig()
    try:
        stream = _stream_params(
            dataclasses.replace(defaults.stream,
                                preview_depth=args.preview_depth,
                                representation=args.representation),
            args.stream_json)
    except (ValueError, TypeError) as e:
        print(f"error: bad --stream-json: {e}", file=sys.stderr)
        return 2
    config = ServeConfig(
        proj=proj,
        queue_depth=args.queue_depth,
        linger_ms=args.linger_ms,
        workers=args.workers,
        devices=args.devices,
        shard_min_pixels=args.shard_min_pixels,
        shard_devices=args.shard_devices,
        buckets=buckets,
        batch_sizes=tuple(int(b) for b in args.batch_sizes.split(",")),
        warmup=not args.no_warmup,
        warmup_sessions=not args.no_session_warmup,
        mesh_depth=args.mesh_depth,
        mesh_representation=args.mesh_representation,
        max_sessions=args.max_sessions,
        store_dir=args.store_dir,
        content_cache=not args.no_content_cache,
        stream=stream,
        tenant_rate_per_s=args.tenant_rate,
        tenant_burst=args.tenant_burst,
        tenant_cost_weighted=args.tenant_cost_weighted,
        replica_id=args.replica_id,
        peers=tuple(u.strip() for u in (args.peers or "").split(",")
                    if u.strip()),
        handoff_dir=args.handoff_dir)

    calib_provider = None
    if args.calib is not None:
        from ..io.matcal import load_calibration_mat

        h, w = buckets[0]
        calib_provider = fixed_calib_provider(
            load_calibration_mat(args.calib, h, w))

    service = ReconstructionService(config, calib_provider=calib_provider)
    print("warming program cache..." if config.warmup else
          "warmup skipped (--no-warmup)", file=sys.stderr, flush=True)
    service.start(recover_from=True if args.recover else None)
    if args.recover:
        st = service.stats()
        print(f"recovered from {args.store_dir}: "
              f"{st['queue_depth']} job(s) re-queued, "
              f"{st['sessions']['live']} live session(s)",
              file=sys.stderr, flush=True)
    http = ServeHTTPServer(service, host=args.host, port=args.port).start()
    # Machine-parseable readiness line (the CI smoke script greps it).
    print(f"serving on :{http.port}", file=sys.stderr, flush=True)

    stop = threading.Event()

    def _graceful(signum, frame):
        print(f"signal {signum}: draining...", file=sys.stderr, flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    stop.wait()

    ok = service.drain(timeout=args.drain_timeout)
    http.stop()
    print("drained clean" if ok else "drain timed out", file=sys.stderr,
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
