"""``render``: novel-view PNGs from a splat scene (docs/RENDERING.md).

The offline half of the rendered-result surface: what a live session
serves through ``GET /session/<id>/render``, this tool reproduces from
a saved scene — the ``.npz`` a session exports via ``GET
/session/<id>/splats`` (or ``SplatScene.save``) renders to the SAME
pixels here (the serve↔CLI parity contract: same arrays, same compiled
render program). A colored ``.ply`` cloud works too: it is fused into a
TSDF and seeded on the spot (`splat.splat_scene_from_cloud` — the
appearance is the fused DC color; view-dependent SH needs a session's
captured frames).

Modes::

    render scene.npz -o view.png --az 30 --el 20      # saved scene
    render cloud.ply -o view.png --depth 7            # seed from cloud
    render scene.npz -o sweep_.png --sweep 12         # 12-view orbit
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="render",
        description="Render a splat scene (.npz) or colored cloud "
                    "(.ply) to novel-view PNGs")
    p.add_argument("input", help="scene .npz (GET /session/<id>/splats "
                                 "export) or a colored .ply cloud")
    p.add_argument("--output", "-o", required=True,
                   help="output .png (with --sweep N: frame index is "
                        "appended before the extension)")
    p.add_argument("--az", type=float, default=30.0,
                   help="orbit azimuth in degrees")
    p.add_argument("--el", type=float, default=20.0,
                   help="orbit elevation in degrees")
    p.add_argument("--zoom", type=float, default=2.1)
    p.add_argument("--size", default="384x288",
                   help="WxH (default 384x288; one compiled program "
                        "per size)")
    p.add_argument("--sweep", type=int, default=0, metavar="N",
                   help="render N views sweeping azimuth over 360° "
                        "(all through ONE compiled program)")
    p.add_argument("--depth", type=int, default=7,
                   help=".ply input: TSDF grid depth for the seeding "
                        "fuse (2^depth voxels per axis)")
    p.add_argument("--splats", type=int, default=8192,
                   help=".ply input: splat capacity")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        w, h = (int(x) for x in args.size.lower().split("x"))
    except ValueError:
        print(f"bad --size {args.size!r}, expected WxH", file=sys.stderr)
        return 2

    from ..io.png import write_png
    from ..splat import SplatParams, SplatScene, splat_scene_from_cloud

    if args.input.lower().endswith(".ply"):
        from ..io import ply as ply_io

        cloud = ply_io.read_ply(args.input)
        scene = splat_scene_from_cloud(
            cloud, SplatParams(capacity=args.splats), depth=args.depth)
        src = f"{len(cloud)} pts"
    else:
        scene = SplatScene.load(args.input)
        src = f"{scene.n_splats} splats"

    if scene.n_splats == 0:
        print(f"{args.input}: scene is empty (nothing to render)",
              file=sys.stderr)
        return 1

    if args.sweep > 0:
        base, ext = os.path.splitext(args.output)
        outs = []
        for k in range(args.sweep):
            az = args.az + 360.0 * k / args.sweep
            img = scene.render(azim=az, elev=args.el, width=w, height=h,
                               zoom=args.zoom)
            path = f"{base}{k:03d}{ext or '.png'}"
            write_png(path, img)
            outs.append(path)
        print(f"{args.input}: {src} -> {len(outs)} views "
              f"({outs[0]} .. {outs[-1]})", file=sys.stderr)
        return 0

    img = scene.render(azim=args.az, elev=args.el, width=w, height=h,
                       zoom=args.zoom)
    write_png(args.output, img)
    print(f"{args.input}: {src} -> {args.output} ({w}x{h}, "
          f"az {args.az:g}, el {args.el:g})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
