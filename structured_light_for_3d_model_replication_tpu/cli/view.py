"""``view``: render a cloud/mesh to PNG — the headless viewer.

The reference eyeballs every stage through interactive Open3D windows:
inlier/outlier coloring (`Old/StatisticalOutlierRemoval.py:66-71`),
before/after pair alignment (`Old/New360.py:72-73`), plane-split preview
(`Old/blackground_remove.py:23`) and the final mesh (`Old/360Merge.py:125`).
On a headless TPU host the equivalent is a PNG: this tool renders any
``.ply``/``.stl`` with the same coloring conventions via ``viz``.

Modes::

    view cloud.ply -o out.png                 # plain (stored colors/depth cue)
    view cloud.ply --outliers -o out.png      # SOR: inliers grey, rejects red
    view cloud.ply --plane -o out.png         # RANSAC plane green vs object
    view a.ply --compare b.ply [--icp] ...    # pair before|after panel
    view mesh.stl -o out.png                  # shaded mesh render
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="view",
                                description="Render a .ply/.stl to a PNG")
    p.add_argument("input", help="input .ply or .stl")
    p.add_argument("--output", "-o", required=True, help="output .png")
    p.add_argument("--outliers", action="store_true",
                   help="color statistical-outlier rejects red "
                        "(nb=20, std=2.0 — the reference defaults)")
    p.add_argument("--plane", action="store_true",
                   help="color the dominant RANSAC plane green")
    p.add_argument("--plane-threshold", type=float, default=None,
                   help="RANSAC plane distance threshold; default derives "
                        "from the cloud scale (bbox diagonal / 50 — ≈ the "
                        "reference's 10.0 on its mm-scale scans)")
    p.add_argument("--compare", metavar="OTHER",
                   help="second cloud: render a before|after pair panel")
    p.add_argument("--icp", action="store_true",
                   help="with --compare: register input onto OTHER "
                        "(RANSAC+ICP) and show the aligned pair on the right")
    p.add_argument("--no-color", action="store_true",
                   help="ignore stored colors (depth-cued grey)")
    p.add_argument("--azim", type=float, default=30.0)
    p.add_argument("--elev", type=float, default=20.0)
    p.add_argument("--zoom", type=float, default=2.1)
    p.add_argument("--size", default="960x720", help="WxH (default 960x720)")
    p.add_argument("--point-px", type=int, default=2)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        w, h = (int(x) for x in args.size.lower().split("x"))
    except ValueError:
        print(f"bad --size {args.size!r}, expected WxH", file=sys.stderr)
        return 2
    kw = dict(width=w, height=h, azim=args.azim, elev=args.elev,
              zoom=args.zoom)

    from .. import viz

    if args.input.lower().endswith(".stl"):
        from ..io import stl as stl_io

        mesh = stl_io.read_stl(args.input)
        img = viz.render_mesh(mesh.vertices, mesh.faces, **kw)
        viz.save_png(args.output, img)
        print(f"{args.input}: {len(mesh.faces)} faces -> {args.output}",
              file=sys.stderr)
        return 0

    from ..io import ply as ply_io

    cloud = ply_io.read_ply(args.input)
    pts = cloud.points
    colors = None if args.no_color else cloud.colors

    if args.compare:
        other = ply_io.read_ply(args.compare)
        transform = None
        if args.icp:
            import numpy as np

            from ..models import merge as merge_mod

            res, _ = merge_mod.register_pair_clouds(cloud, other)
            transform = np.asarray(res.transformation)
            print(f"icp: fitness={float(res.fitness):.3f} "
                  f"rmse={float(res.inlier_rmse):.4f}", file=sys.stderr)
        img = viz.render_pair(pts, other.points, transform,
                              width=2 * w, height=h, azim=args.azim,
                              elev=args.elev, zoom=args.zoom,
                              point_px=args.point_px)
    elif args.outliers:
        import jax.numpy as jnp
        import numpy as np

        from ..ops import pointcloud

        keep = pointcloud.statistical_outlier_removal(
            jnp.asarray(pts, jnp.float32), nb_neighbors=20, std_ratio=2.0)
        keep = np.asarray(keep)[: len(pts)]
        img = viz.render_inliers(pts, keep, point_px=args.point_px, **kw)
        print(f"outliers: {int((~keep).sum())}/{len(pts)} rejected",
              file=sys.stderr)
    elif args.plane:
        import jax.numpy as jnp
        import numpy as np

        from ..ops import segmentation

        thresh = args.plane_threshold
        if thresh is None:
            # Scale-free default: a fixed 10.0 is the reference's unit
            # choice; clouds in other units got an all-or-nothing preview.
            diag = float(np.linalg.norm(
                np.ptp(np.asarray(pts, np.float64), axis=0)))
            thresh = max(diag / 50.0, 1e-9)
        _, inl = segmentation.segment_plane(
            jnp.asarray(pts, jnp.float32), distance_threshold=thresh,
            num_iterations=1000)
        pm = np.asarray(inl)[: len(pts)]
        img = viz.render_plane_split(pts, pm, point_px=args.point_px, **kw)
        print(f"plane: {int(pm.sum())}/{len(pts)} points on the plane "
              f"(threshold {thresh:.3g})", file=sys.stderr)
    else:
        img = viz.render_points(pts, colors, point_px=args.point_px, **kw)

    viz.save_png(args.output, img)
    print(f"{args.input}: {len(pts)} pts -> {args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
