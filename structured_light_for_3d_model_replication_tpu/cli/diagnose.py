"""``cli diagnose`` — one support bundle for "why is this run slow/dead".

Bundles the observability layer's artifacts (docs/OBSERVABILITY.md) into
a single ``.tar.gz``:

==================  ======================================================
``env.json``         environment manifest: python/platform, jax + numpy +
                     scipy versions, backend, device list (+ memory
                     stats), JAX_*/XLA_* env vars
``metrics.json``     MetricsRegistry snapshot (JSON)
``metrics.prom``     the same registry as a Prometheus scrape, span
                     aggregates folded in
``spans.json``       tracer span totals + eviction count
``events.jsonl``     flight-recorder journal tail (correlated events)
``perfetto.json``    Chrome/Perfetto trace_event export of host spans —
                     open at ui.perfetto.dev
``telemetry.json``   compile counters/histogram + recompile storms +
                     device memory
``health.json``      a scan health report (``--health-json``), a live
                     service's /healthz (``--url``), or a stub naming
                     what was absent
``journal_*.jsonl``  any on-disk flight dumps passed via ``--journal``
``MANIFEST.json``    bundle index + creation time
==================  ======================================================

``--url`` additionally scrapes a running serve instance
(``remote_healthz.json`` / ``remote_metrics.prom`` /
``remote_events.jsonl``). ``--probe`` runs a tiny synthetic
reconstruction first so a fresh process ships real compile/span numbers
instead of empty tables.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import platform
import sys
import tarfile
import time

from ..utils import events, telemetry, trace
from ..utils.log import get_logger

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cli diagnose",
        description="bundle health + metrics + journal + env into a "
                    "support tarball (docs/OBSERVABILITY.md)")
    p.add_argument("--output", "-o", default=None,
                   help="output .tar.gz path "
                        "(default diagnose_<timestamp>.tar.gz)")
    p.add_argument("--url", default=None,
                   help="scrape a running serve instance "
                        "(http://host:port) for healthz/metrics/events")
    p.add_argument("--health-json", default=None, metavar="PATH",
                   help="include a scan health report "
                        "(scan-360 --health-json output)")
    p.add_argument("--journal", action="append", default=[],
                   metavar="PATH",
                   help="include an on-disk flight dump (repeatable)")
    p.add_argument("--events", type=int, default=1024,
                   help="journal tail length to include (default 1024)")
    p.add_argument("--probe", action="store_true",
                   help="run a tiny synthetic reconstruction first so "
                        "compile/span metrics are populated")
    return p


# ---------------------------------------------------------------------------
# Collectors
# ---------------------------------------------------------------------------


def _env_manifest() -> dict:
    out = {
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "python": sys.version,
        "platform": platform.platform(),
        "machine": platform.machine(),
        "pid": os.getpid(),
        "cwd": os.getcwd(),
        "argv": list(sys.argv),
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("JAX_", "XLA_", "SL_TPU_", "TPU_",
                                 "LIBTPU"))},
        "packages": {},
    }
    for name in ("numpy", "scipy", "PIL"):
        try:
            mod = __import__(name)
            out["packages"][name] = getattr(mod, "__version__", "?")
        except Exception:
            out["packages"][name] = None
    try:
        import jax
        import jaxlib

        out["packages"]["jax"] = jax.__version__
        out["packages"]["jaxlib"] = jaxlib.__version__
        out["jax"] = {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "devices": [
                {"id": d.id, "platform": d.platform,
                 "kind": getattr(d, "device_kind", "?"),
                 "memory_stats": _safe_memory_stats(d)}
                for d in jax.local_devices()],
        }
    except Exception as e:  # diagnose must work where jax is broken
        out["jax"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _safe_memory_stats(device) -> dict | None:
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def _scrape(url: str, path: str, timeout: float = 10.0) -> bytes:
    from urllib.request import urlopen

    with urlopen(url.rstrip("/") + path, timeout=timeout) as resp:
        return resp.read()


def _probe() -> dict:
    """A tiny end-to-end synthetic reconstruction: populates compile
    counters, spans, and the jit path, so a fresh diagnose carries real
    numbers. Kept miniature (32x16 projector, 16x24 camera) — seconds on
    CPU."""
    import jax.numpy as jnp
    import numpy as np

    from ..config import ProjectorConfig
    from ..models import pipeline, synthetic
    from ..ops.triangulate import make_calibration

    proj = ProjectorConfig(width=32, height=16)
    cam_h, cam_w = 16, 24
    with trace.span("diagnose.probe"):
        cam_K, proj_K, R, T = synthetic.default_calibration(cam_h, cam_w,
                                                            proj)
        stack, _ = synthetic.render_scan(synthetic.Scene(), cam_K, proj_K,
                                         R, T, cam_h, cam_w, proj)
        calib = make_calibration(cam_K, proj_K, R, T, cam_h, cam_w,
                                 proj_width=proj.width,
                                 proj_height=proj.height)
        res = pipeline.reconstruct(jnp.asarray(stack), calib,
                                   proj.col_bits, proj.row_bits)
        valid = int(np.asarray(res.valid).sum())
    return {"probe_points": valid, "cam": [cam_h, cam_w],
            "proj": [proj.width, proj.height]}


def collect(url: str | None = None, health_json: str | None = None,
            journals: list[str] | tuple = (), events_n: int = 1024,
            probe: bool = False) -> dict[str, bytes]:
    """Gather every bundle member as {filename: bytes}. Collection is
    fault-tolerant member by member: a broken source becomes an
    ``*_error`` note in the manifest, never a lost bundle."""
    members: dict[str, bytes] = {}
    errors: dict[str, str] = {}

    def _try(name: str, fn):
        try:
            members[name] = fn()
        except Exception as e:
            errors[name] = f"{type(e).__name__}: {e}"
            log.warning("diagnose: %s collection failed: %s", name, e)

    tel = telemetry.install_global()
    if probe:
        _try("probe.json",
             lambda: json.dumps(_probe(), indent=2).encode())

    _try("env.json",
         lambda: json.dumps(_env_manifest(), indent=2).encode())
    _try("metrics.json",
         lambda: json.dumps(trace.REGISTRY.snapshot(), indent=2).encode())
    _try("metrics.prom",
         lambda: trace.REGISTRY.prometheus_text(
             tracer=trace.GLOBAL).encode())
    _try("spans.json",
         lambda: json.dumps(
             {"totals": trace.GLOBAL.totals(),
              "evicted_spans": trace.GLOBAL.evicted_count},
             indent=2).encode())
    _try("events.jsonl", lambda: events.to_jsonl(events_n).encode())
    _try("perfetto.json",
         lambda: json.dumps(trace.GLOBAL.to_perfetto()).encode())
    _try("telemetry.json",
         lambda: json.dumps(tel.snapshot(), indent=2).encode())

    # health.json: explicit file > live service > stub naming the gap.
    if health_json is not None:
        _try("health.json", lambda: open(health_json, "rb").read())
    elif url is not None:
        _try("health.json", lambda: _scrape(url, "/healthz"))
    else:
        members["health.json"] = json.dumps(
            {"source": "none",
             "note": "no --health-json or --url given; see env.json for "
                     "process/device liveness"}, indent=2).encode()

    if url is not None:
        _try("remote_healthz.json", lambda: _scrape(url, "/healthz"))
        _try("remote_metrics.prom", lambda: _scrape(url, "/metrics"))
        _try("remote_events.jsonl",
             lambda: _scrape(url, f"/events?n={events_n}"))

    for j, path in enumerate(journals):
        _try(f"journal_{j:02d}_{os.path.basename(path)}",
             lambda p=path: open(p, "rb").read())

    members["MANIFEST.json"] = json.dumps(
        {"members": sorted(members) + ["MANIFEST.json"],
         "errors": errors,
         "created_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")},
        indent=2).encode()
    return members


def write_bundle(path: str, members: dict[str, bytes]) -> None:
    with tarfile.open(path, "w:gz") as tar:
        for name in sorted(members):
            data = members[name]
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    out = args.output or time.strftime("diagnose_%Y%m%d_%H%M%S.tar.gz")
    members = collect(url=args.url, health_json=args.health_json,
                      journals=args.journal, events_n=args.events,
                      probe=args.probe)
    write_bundle(out, members)
    size = os.path.getsize(out)
    print(f"diagnose bundle: {out} ({size} bytes, {len(members)} members)")
    for name in sorted(members):
        print(f"  {name} ({len(members[name])} bytes)")
    return 0
