"""``scan-360``: the fused pipeline — per-stop capture folders → merged PLY.

The whole post-capture path of the reference (per-stop `generate_cloud`
then the merge tab) as one device-resident run
(`models/scan360.scan_folders_to_cloud`). Stops are the subfolders of the
session dir, numerically sorted — the auto-scan layout
(`server/gui.py:703-740`).
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="scan-360",
        description="Decode, triangulate, register and merge a full 360° "
                    "session in one run")
    p.add_argument("--input", "-i", required=True,
                   help="session folder whose subfolders are per-stop scans")
    p.add_argument("--calib", "-c", required=True, help=".mat calibration")
    p.add_argument("--output", "-o", required=True, help="merged .ply")
    p.add_argument("--method", choices=("sequential", "posegraph"),
                   default="posegraph")
    p.add_argument("--voxel-size", type=float, default=0.02)
    p.add_argument("--max-points", type=int, default=16_384)
    p.add_argument("--stop-chunk", type=int, default=6,
                   help="stops decoded per device dispatch (HBM bound)")
    p.add_argument("--fused", action="store_true",
                   help="compile the whole pipeline into ONE device launch "
                        "(heavy cold compile; lowest latency warm)")
    p.add_argument("--step-deg", type=float, default=None,
                   help="commanded turntable advance per stop; feeds the "
                        "axis-consensus prior. Default: parsed from an "
                        "'..._<deg>deg_AUTO' session folder name when "
                        "present")
    p.add_argument("--stl", default=None,
                   help="also mesh the merged cloud to this path (watertight "
                        "screened Poisson by default; the full scan→print "
                        "path in one command). A .ply extension writes a "
                        "vertex-colored mesh instead of STL — pair it with "
                        "--representation tsdf to keep the scan's colors")
    p.add_argument("--mesh-depth", type=int, default=8)
    s = p.add_argument_group("streaming (docs/STREAMING.md)")
    s.add_argument("--stream", action="store_true",
                   help="fuse stops INCREMENTALLY (stream/): per-stop "
                        "coarse mesh previews while later stops are "
                        "still being read, covisibility gate on "
                        "redundant stops, same final merge math")
    s.add_argument("--preview-out", default=None, metavar="PATH",
                   help="progressive preview STL path (default "
                        "<output>.preview.stl), rewritten after every "
                        "fused stop")
    s.add_argument("--preview-depth", type=int, default=6,
                   help="coarse Poisson depth of the per-stop previews")
    s.add_argument("--preview-every", type=int, default=1,
                   help="emit a preview every N fused stops (0 = off)")
    s.add_argument("--representation",
                   choices=("tsdf", "archival", "poisson", "splat"),
                   default="tsdf",
                   help="scene representation (docs/STREAMING.md, batch "
                        "and --stream): 'tsdf' (default) fuses into a "
                        "brick volume (fusion/) — streaming stops "
                        "integrate instead of re-solving, finalize is "
                        "integrate-don't-re-solve too, and the final "
                        "mesh carries vertex color when --stl names a "
                        ".ply (STL drops color); 'archival' keeps the "
                        "TSDF previews but makes the FINAL artifact the "
                        "full-depth watertight Poisson solve (the "
                        "print/archive format); 'poisson' is the legacy "
                        "lane (coarse Poisson re-solve previews too); "
                        "'splat' adds the Gaussian appearance tier "
                        "(docs/RENDERING.md) — rendered previews "
                        "(--preview-render) and a saveable scene "
                        "(--save-scene). Streaming-only; the batch path "
                        "treats it as 'tsdf'")
    s.add_argument("--preview-render", action="store_true",
                   help="with --stream --representation splat: also "
                        "rewrite a rendered novel-view PNG "
                        "(<output>.preview.png) after every fused stop")
    s.add_argument("--save-scene", default=None, metavar="PATH",
                   help="with --stream --representation splat: save the "
                        "fitted splat scene (.npz) at the end — `cli "
                        "render` reproduces the renders offline")
    g = p.add_argument_group("quality gates (docs/ROBUSTNESS.md)")
    g.add_argument("--no-gates", action="store_true",
                   help="disable the quality gates (abort-on-anything "
                        "reference behavior)")
    g.add_argument("--min-coverage", type=float, default=0.02,
                   help="drop stops whose decoded-valid pixel fraction is "
                        "below this (bridged out of the ring)")
    g.add_argument("--min-edge-fitness", type=float, default=0.2,
                   help="reject ring edges below this ICP fitness "
                        "(consensus-repaired / down-weighted)")
    g.add_argument("--max-edge-rmse", type=float, default=None,
                   help="optional absolute inlier-RMSE ceiling per edge")
    g.add_argument("--health-json", default=None, metavar="PATH",
                   help="write the scan health report (per-stop coverage, "
                        "dropped stops, edge verdicts) as JSON here")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from ..io.images import numeric_sort
    from ..models import merge, scan360
    from .process_cloud import has_frames

    subs = numeric_sort([
        os.path.join(args.input, s) for s in os.listdir(args.input)
        if os.path.isdir(os.path.join(args.input, s))])
    stop_dirs = [s for s in subs if has_frames(s)]
    # A partially-captured stop (interrupted/failed mid-stack) would make
    # the stack np.stack ragged — keep only full-stack folders and say so.
    from ..io.images import list_frames

    counts = {d: len(list_frames(d)) for d in stop_dirs}
    if counts:
        full = max(counts.values())
        ragged = [d for d in stop_dirs if counts[d] < full]
        if ragged:
            print(f"skipping {len(ragged)} partial stop folder(s) "
                  f"(fewer than {full} frames): "
                  f"{[os.path.basename(d) for d in ragged]}",
                  file=sys.stderr)
            stop_dirs = [d for d in stop_dirs if counts[d] == full]
    if len(stop_dirs) < 2:
        raise SystemExit(f"{args.input}: need ≥2 per-stop frame folders, "
                         f"found {len(stop_dirs)}")

    step_deg = args.step_deg
    if step_deg is None:
        # The auto-scan layout encodes the commanded step in the session
        # folder name: "<base>_<deg>deg_AUTO" (`server/gui.py:703-740`).
        import re

        m = re.search(r"_(\d+(?:\.\d+)?)deg_AUTO$",
                      os.path.basename(os.path.normpath(args.input)))
        if m:
            step_deg = float(m.group(1))
            print(f"turntable step {step_deg}° (from session folder name)",
                  file=sys.stderr)

    from ..health import QualityGates, ScanHealthReport

    gates = None if args.no_gates else QualityGates(
        min_coverage=args.min_coverage,
        min_edge_fitness=args.min_edge_fitness,
        max_edge_rmse=args.max_edge_rmse)
    health = ScanHealthReport()

    # Physical stop labels from the auto-scan folder names ("…_<angle>deg_
    # scan") when the step is known: a session with capture-skipped stops
    # then reports health by REAL stop index and the ring bridges with
    # true step gaps.
    stop_labels = None
    if step_deg:
        import re as _re

        angles = []
        for d in stop_dirs:
            m = _re.search(r"_(\d+(?:\.\d+)?)deg_scan$",
                           os.path.basename(os.path.normpath(d)))
            if not m:
                angles = None
                break
            angles.append(float(m.group(1)))
        if angles:
            labs = [round(a / step_deg) for a in angles]
            if labs == sorted(set(labs)):
                stop_labels = labs
    if args.stream:
        return _run_stream(args, stop_dirs, step_deg, stop_labels, gates,
                           health)

    params = scan360.Scan360Params(
        merge=merge.MergeParams(voxel_size=args.voxel_size,
                                max_points=args.max_points,
                                step_deg=step_deg),
        method=args.method,
        fused=args.fused,
        stop_chunk=args.stop_chunk,
        gates=gates)
    merged, poses = scan360.scan_folders_to_cloud(
        stop_dirs, args.calib, output_path=args.output, params=params,
        health=health, stop_labels=stop_labels)
    print(f"{len(stop_dirs)} stops -> {args.output} ({len(merged)} points)",
          file=sys.stderr)
    if health.dropped_stops:
        print(f"degraded: stops {health.dropped_stops} dropped by the "
              f"coverage gate (see --health-json)", file=sys.stderr)
    if args.stl:
        from ..models import meshing

        # The batch path has no per-stop frames to fit appearance from —
        # 'splat' degrades to its geometry half (the colored TSDF mesh).
        if args.representation == "splat":
            args.representation = "tsdf"
        if args.representation == "tsdf" \
                and not args.stl.lower().endswith(".ply"):
            print("note: --representation tsdf meshes carry vertex color "
                  "only into a .ply output; STL drops it",
                  file=sys.stderr)
        # Terminal guard: a mesh failure (or an empty mesh) degrades to
        # "you still have the merged PLY" instead of crashing the run.
        try:
            if args.stl.lower().endswith(".ply"):
                from ..io import ply as ply_io

                # quantile_trim 0.0 = the mesh_360 watertight default —
                # the output extension must not change the geometry.
                mesh = meshing.mesh_from_cloud(
                    merged, depth=args.mesh_depth, quantile_trim=0.0,
                    representation=args.representation)
                ply_io.write_ply_mesh(args.stl, mesh)
            else:
                mesh = meshing.mesh_360(
                    merged, args.stl, depth=args.mesh_depth,
                    representation=args.representation)
        except Exception as e:
            health.note("meshing failed (%s) — merged cloud kept at %s",
                        e, args.output)
            print(f"meshing failed: {e} (cloud kept at {args.output})",
                  file=sys.stderr)
        else:
            if len(mesh.faces) == 0:
                health.note("mesh has zero faces — treat %s as unusable, "
                            "merged cloud kept at %s", args.stl, args.output)
            print(f"meshed -> {args.stl} ({len(mesh.faces)} faces)",
                  file=sys.stderr)
    health.emit()
    if args.health_json:
        health.write(args.health_json)
    return 0


def _run_stream(args, stop_dirs, step_deg, stop_labels, gates,
                health) -> int:
    """``--stream``: replay the stop folders through an incremental
    session — progressive previews after every fused stop, same final
    merge math as the batch path (stream/, docs/STREAMING.md)."""
    import math
    import time

    from ..io import images as img_io
    from ..io import matcal
    from ..io import ply as ply_io
    from ..io.stl import write_stl
    from ..models import merge
    from ..stream import IncrementalSession, StreamParams

    first = img_io.load_stack(stop_dirs[0])
    _, h, w = first.shape
    cal = matcal.load_calibration_mat(args.calib, h, w)
    col_bits = math.ceil(math.log2(cal.plane_cols.shape[0]))
    row_bits = math.ceil(math.log2(cal.plane_rows.shape[0]))
    expect = 2 + 2 * (col_bits + row_bits)
    if first.shape[0] != expect:
        raise SystemExit(
            f"stack has {first.shape[0]} frames but {col_bits}+{row_bits} "
            f"bits imply {expect}")
    labels = stop_labels or list(range(len(stop_dirs)))
    params = StreamParams(
        merge=merge.MergeParams(voxel_size=args.voxel_size,
                                max_points=args.max_points,
                                step_deg=step_deg),
        method=args.method,
        gates=gates,
        preview_depth=args.preview_depth,
        preview_every=args.preview_every,
        representation=args.representation,
        final_depth=args.mesh_depth,
        expected_stops=max(labels) + 1)
    sess = IncrementalSession(cal, col_bits, row_bits, params=params,
                              health=health)
    preview_path = args.preview_out or (args.output + ".preview.stl")
    render_path = args.output + ".preview.png"
    want_render = args.preview_render and args.representation == "splat"
    if args.preview_render and not want_render:
        print("--preview-render needs --representation splat; ignored",
              file=sys.stderr)
    t0 = time.monotonic()
    first_preview_s = None
    for k, d in enumerate(stop_dirs):
        stack = first if k == 0 else img_io.load_stack(d)
        res = sess.add_stop(stack, stop=labels[k])
        line = (f"stop {labels[k]}: {res.reason} "
                f"(coverage {res.coverage:.3f}"
                + (f", fitness {res.fitness:.3f}" if res.fitness is not None
                   else "")
                + f", {res.seconds:.1f}s)")
        print(line, file=sys.stderr)
        if want_render and res.fused:
            # Rendered novel-view preview (splat/, docs/RENDERING.md) —
            # rebuilt lazily from the volume + frame buffer after EVERY
            # fused stop (independent of the mesh-preview cadence, as
            # the flag promises).
            img = sess._mesher.render_image(30.0, 20.0)
            if img is not None:
                from ..io.png import write_png

                write_png(render_path, img)
        if res.preview and sess.preview is not None:
            if preview_path.lower().endswith(".ply"):
                ply_io.write_ply_mesh(preview_path, sess.preview)
            else:
                write_stl(preview_path, sess.preview)
            if first_preview_s is None:
                first_preview_s = time.monotonic() - t0
                print(f"first preview {first_preview_s:.1f}s after stop "
                      f"{labels[k]} -> {preview_path} "
                      f"({len(sess.preview.faces)} faces)"
                      + (f" + render -> {render_path}" if want_render
                         else ""),
                      file=sys.stderr)
    from ..health import ScanFault

    try:
        fin = sess.finalize(mesh=bool(args.stl))
    except ScanFault as e:
        # Degraded-capture terminal guard: too few fused stops (gates /
        # covisibility skipped the rest) must end with the health story
        # and whatever preview exists, not a traceback.
        health.note("stream finalize failed: %s", e)
        print(f"finalize failed: {e}"
              + (f" — latest preview kept at {preview_path}"
                 if first_preview_s is not None else ""),
              file=sys.stderr)
        health.emit()
        if args.health_json:
            health.write(args.health_json)
        return 1
    ply_io.write_ply(args.output, fin.cloud)
    print(f"{sess.stops_fused} fused / {sess.stops_skipped} skipped "
          f"stops -> {args.output} ({len(fin.cloud)} points)",
          file=sys.stderr)
    if args.save_scene:
        if args.representation == "splat":
            data = sess._mesher.scene_bytes()
            if data is not None:
                with open(args.save_scene, "wb") as f:
                    f.write(data)
                print(f"splat scene -> {args.save_scene} "
                      f"({len(data)} B; render offline with "
                      f"`cli render`)", file=sys.stderr)
        else:
            print("--save-scene needs --representation splat; ignored",
                  file=sys.stderr)
    if args.stl and fin.mesh is not None:
        colored = getattr(fin.mesh, "vertex_colors", None) is not None
        if args.stl.lower().endswith(".ply"):
            ply_io.write_ply_mesh(args.stl, fin.mesh)
        else:
            if colored:
                print("note: STL drops the mesh's vertex colors — name "
                      "a .ply with --stl to keep them", file=sys.stderr)
                colored = False
            write_stl(args.stl, fin.mesh)
        print(f"meshed -> {args.stl} ({len(fin.mesh.faces)} faces"
              f"{', colored' if colored else ''})",
              file=sys.stderr)
    health.emit()
    if args.health_json:
        health.write(args.health_json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
