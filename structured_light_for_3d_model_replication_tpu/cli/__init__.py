"""Command-line tools — the headless entry points of the framework.

The reference's only non-GUI entry is the legacy `Old/process_cloud.py`
argparse script (`:221-236`); every other workflow is reachable solely by
clicking through Tkinter (`server/gui.py`, `multi_point_cloud_process.py`).
Here every pipeline stage is a first-class CLI, runnable on a headless TPU
host:

================  ===========================================================
``process-cloud``  decode+triangulate scan folder(s) → PLY
                   (`Old/process_cloud.py`, `multi_point_cloud_process.py`)
``read-calib``     inspect a ``.mat`` calibration (`Old/read_calib.py`)
``merge-360``      register+merge a folder of PLYs (`server/gui.py:622-641`)
``scan-360``       full fused pipeline: stacks → merged cloud (new)
``mesh``           cloud → STL, watertight/surface (`server/gui.py:643-684`)
``scan``           drive a capture rig, real or virtual (`server/gui.py:686`)
``view``           render a .ply/.stl to PNG — the headless stand-in for the
                   reference's Open3D viewer moments (`Old/New360.py:72`,
                   `Old/StatisticalOutlierRemoval.py:66-71`)
``render``         novel-view PNGs from a splat scene (.npz from
                   ``GET /session/<id>/splats``) or a colored cloud —
                   the offline half of the rendered-result surface
                   (docs/RENDERING.md)
``serve``          continuous-batching reconstruction service: HTTP
                   submit/status/result over the batched pipeline
                   (docs/SERVING.md)
``diagnose``       support bundle: health + metrics + flight journal +
                   Perfetto spans + env manifest in one tarball
                   (docs/OBSERVABILITY.md)
``lint``           jaxlint static-analysis gate: lexical + cross-module
                   project rules, SARIF export (docs/JAXLINT.md)
================  ===========================================================

Invoke via ``python -m structured_light_for_3d_model_replication_tpu.cli <tool> [args]``.
"""

from __future__ import annotations

import sys

_TOOLS = {
    "diagnose": "diagnose",
    "lint": "lint",
    "process-cloud": "process_cloud",
    "read-calib": "read_calib",
    "render": "render",
    "merge-360": "merge_360",
    "scan-360": "scan_360",
    "mesh": "mesh",
    "scan": "scan",
    "serve": "serve",
    "view": "view",
}


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("tools:", ", ".join(sorted(_TOOLS)))
        return 0
    tool = argv[0]
    if tool not in _TOOLS:
        print(f"unknown tool {tool!r}; available: {', '.join(sorted(_TOOLS))}",
              file=sys.stderr)
        return 2
    import importlib

    mod = importlib.import_module(f".{_TOOLS[tool]}", __name__)
    return mod.main(argv[1:])
