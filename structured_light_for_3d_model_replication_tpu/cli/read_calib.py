"""``read-calib``: inspect a ``.mat`` calibration container.

Parity with `Old/read_calib.py:23-110`: prints camera/projector intrinsics
(fx/fy/cx/cy, skew), the stereo rotation as Euler angles, the translation,
the camera-frame projector center Oc = −RᵀT, and sanity stats over the
stored light-plane tables.
"""

from __future__ import annotations

import argparse

import numpy as np
import scipy.io


def _euler_deg(R: np.ndarray) -> tuple[float, float, float]:
    """ZYX (yaw-pitch-roll) Euler angles in degrees."""
    sy = float(np.hypot(R[0, 0], R[1, 0]))
    if sy > 1e-8:
        roll = np.arctan2(R[2, 1], R[2, 2])
        pitch = np.arctan2(-R[2, 0], sy)
        yaw = np.arctan2(R[1, 0], R[0, 0])
    else:  # gimbal lock
        roll = np.arctan2(-R[1, 2], R[1, 1])
        pitch = np.arctan2(-R[2, 0], sy)
        yaw = 0.0
    return tuple(np.degrees([yaw, pitch, roll]))


def _intrinsics(tag: str, K: np.ndarray) -> None:
    print(f"{tag} intrinsics:")
    print(f"  fx={K[0, 0]:.2f}  fy={K[1, 1]:.2f}  "
          f"cx={K[0, 2]:.2f}  cy={K[1, 2]:.2f}  skew={K[0, 1]:.4f}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="read-calib",
                                description=__doc__.splitlines()[0])
    p.add_argument("calib", help=".mat calibration file")
    args = p.parse_args(argv)

    data = scipy.io.loadmat(args.calib)
    cam_K = np.asarray(data["cam_K"], float)
    proj_K = np.asarray(data["proj_K"], float)
    R = np.asarray(data["R"], float)
    T = np.asarray(data["T"], float).reshape(3)

    _intrinsics("camera", cam_K)
    _intrinsics("projector", proj_K)

    yaw, pitch, roll = _euler_deg(R)
    print("stereo extrinsics (X_proj = R X_cam + T):")
    print(f"  R (ZYX Euler): yaw={yaw:+.3f}°  pitch={pitch:+.3f}°  "
          f"roll={roll:+.3f}°")
    print(f"  T (mm): [{T[0]:+.2f}, {T[1]:+.2f}, {T[2]:+.2f}]  "
          f"|T|={np.linalg.norm(T):.2f}")
    Oc = -R.T @ T
    print(f"  projector center Oc = -RᵀT (mm): "
          f"[{Oc[0]:+.2f}, {Oc[1]:+.2f}, {Oc[2]:+.2f}]")

    for key, axis in (("wPlaneCol", "column"), ("wPlaneRow", "row")):
        if key in data:
            planes = np.asarray(data[key], float).T  # stored (4, n)
            n = np.linalg.norm(planes[:, :3], axis=1)
            print(f"{key}: {planes.shape[0]} {axis} planes, "
                  f"|n| in [{n.min():.6f}, {n.max():.6f}]")
    if "Nc" in data:
        Nc = np.asarray(data["Nc"], float)
        print(f"Nc: {Nc.shape[1]} camera rays "
              f"(grid flattens to H*W; |ray| mean "
              f"{np.linalg.norm(Nc, axis=0).mean():.6f})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
