"""``mesh``: point cloud → STL surface.

The two GUI meshing actions (`server/gui.py:643-684` →
`ProcessingLogic.mesh_360` / `reconstruct_stl`, `server/processing.py:
184-310`) as one CLI: watertight screened-Poisson or the surface mode, with
density-quantile trimming and normal-orientation choice. Optional cleanup
passes mirror the Process tab (`remove_background` / `remove_outliers`,
`server/processing.py:24-76`).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mesh",
        description="Mesh a .ply cloud into an .stl (or a vertex-"
                    "colored mesh .ply)")
    p.add_argument("--input", "-i", required=True, help="input .ply")
    p.add_argument("--output", "-o", required=True,
                   help="output mesh: .stl, or .ply for a vertex-"
                        "colored PLY mesh (colors need "
                        "--representation tsdf and a colored cloud)")
    p.add_argument("--mode", choices=("watertight", "surface"),
                   default="watertight")
    p.add_argument("--depth", type=int, default=8,
                   help="Poisson octree-equivalent depth (2^depth virtual "
                        "grid; ≤8 dense, 9-16 band-sparse — the reference "
                        "defaults its octree to depth 10 and caps at 16)")
    p.add_argument("--trim", type=float, default=0.0,
                   help="density quantile to trim (0.0 = watertight "
                        "mesh_360 default, 0.02 = reconstruct_stl default)")
    p.add_argument("--orientation", choices=("radial", "tangent"),
                   default="radial",
                   help="normal orientation (server/processing.py:270-289)")
    p.add_argument("--radii", default="1,2,4",
                   help="surface mode: ball-pivot radii as multipliers of "
                        "the average NN distance (the reference GUI's "
                        "radii list, server/processing.py:222-235)")
    p.add_argument("--remove-background", action="store_true",
                   help="drop the dominant RANSAC plane first")
    p.add_argument("--remove-outliers", action="store_true",
                   help="statistical outlier removal first (20, 2.0)")
    p.add_argument("--preconditioner",
                   choices=("additive", "vcycle", "chebyshev", "jacobi"),
                   default="additive",
                   help="fine-band CG preconditioner of the deep (sparse) "
                        "Poisson path (docs/MESHING.md)")
    p.add_argument("--extraction", choices=("auto", "host", "device"),
                   default="auto",
                   help="iso-surface extractor: device marching on TPU "
                        "backends (auto), or force either engine")
    p.add_argument("--representation", choices=("poisson", "tsdf"),
                   default="poisson",
                   help="scene representation (docs/MESHING.md): "
                        "'poisson' watertight print path, 'tsdf' the "
                        "fused brick-grid path — open surfaces, "
                        "per-vertex COLOR carried into a .ply output")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from ..io import ply as ply_io
    from ..models import merge, meshing

    cloud = ply_io.read_ply(args.input)
    if args.remove_background:
        cloud = merge.remove_background(cloud)
    if args.remove_outliers:
        cloud = merge.remove_outliers(cloud)
    kw = dict(mode=args.mode, depth=args.depth,
              quantile_trim=args.trim, orientation_mode=args.orientation,
              radii_multipliers=args.radii,
              preconditioner=args.preconditioner,
              extraction=args.extraction,
              representation=args.representation)
    if args.output.lower().endswith(".ply"):
        mesh = meshing.mesh_from_cloud(cloud, **kw)
        ply_io.write_ply_mesh(args.output, mesh)
    else:
        mesh = meshing.reconstruct_stl(cloud, args.output, **kw)
    colored = getattr(mesh, "vertex_colors", None) is not None \
        and args.output.lower().endswith(".ply")
    print(f"{args.input}: {len(cloud)} pts -> {args.output} "
          f"({len(mesh.vertices)} verts, {len(mesh.faces)} faces"
          f"{', colored' if colored else ''})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
