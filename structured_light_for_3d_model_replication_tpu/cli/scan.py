"""``scan``: drive a capture rig — real hardware or the virtual simulator.

Headless version of the GUI's capture workflows (`server/gui.py`): single
scans, calibration poses, and the flagship auto-360 loop
(`server/gui.py:686-773`), with resume. ``--virtual`` swaps in the ray-traced
rig (`hw/rig.VirtualRig`) — the reference has no equivalent (its only mock is
a `time.sleep(2)` turntable stub, `server/gui.py:690-693`).

Real-hardware mode starts the pull-mode command server (`server/server.py`
semantics) for the phone browser client and, when ``--serial`` is given, the
ESP32 turntable driver (`server/arduino.py`).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="scan",
                                description="Capture scans, 360 sessions or "
                                            "calibration poses")
    p.add_argument("command", choices=("auto360", "single", "calib-pose"))
    p.add_argument("--name", default="scan", help="scan/session base name")
    p.add_argument("--session", default=".",
                   help="session root (dated layout created inside)")
    p.add_argument("--turns", type=int, default=12)
    p.add_argument("--degrees", type=float, default=30.0)
    p.add_argument("--pose", type=int, default=1,
                   help="calibration pose index")
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--virtual", action="store_true",
                   help="ray-traced virtual rig instead of hardware")
    p.add_argument("--port", type=int, default=5000,
                   help="pull-mode HTTP command server port")
    p.add_argument("--serial", default=None,
                   help="turntable serial port (e.g. /dev/ttyUSB0); "
                        "omit to scan without rotation control")
    p.add_argument("--push-host", default=None,
                   help="push-mode Android host base URL instead of the "
                        "pull-mode server (e.g. http://127.0.0.1:8765)")
    p.add_argument("--local-cam", type=int, default=None, metavar="ID",
                   help="local webcam device id (cv2.VideoCapture) instead "
                        "of a phone — the reference's no-phone capture rig "
                        "(Old/sl_calib_capture.py)")
    p.add_argument("--cam-size", default="1920x1080", metavar="WxH",
                   help="requested local-camera frame size")
    p.add_argument("--health-json", default=None, metavar="PATH",
                   help="write the capture health report (per-stop retries, "
                        "failed/skipped stops) as JSON — auto360 only")
    return p


def _build_rig(args):
    from ..config import ProjectorConfig
    from ..io.layout import SessionLayout
    from ..scanner import Scanner

    layout = SessionLayout.today(args.session).ensure()
    if args.virtual:
        from ..hw.rig import VirtualRig

        rig = VirtualRig()
        return Scanner(rig.camera, rig.projector, rig.turntable,
                       proj=rig.proj, layout=layout), None

    proj_cfg = ProjectorConfig()
    from ..hw.projector import WindowProjector

    projector = WindowProjector(proj_cfg)

    server = None
    if args.local_cam is not None:
        from ..hw.camera import LocalCamera

        w, h = (int(x) for x in args.cam_size.lower().split("x"))
        camera = LocalCamera(args.local_cam, width=w, height=h)
    elif args.push_host:
        from ..hw.camera import PushCamera

        camera = PushCamera(args.push_host)
    else:
        from ..hw.command_server import CommandServer

        server = CommandServer(port=args.port)
        server.start()
        print(f"command server on :{args.port} — point the phone client at "
              f"this host", file=sys.stderr)
        from ..hw.camera import PullCamera

        camera = PullCamera(server.channel)

    turntable = None
    if args.serial:
        from ..hw.turntable import SerialTurntable

        turntable = SerialTurntable(args.serial)

    return Scanner(camera, projector, turntable, proj=proj_cfg,
                   layout=layout), server


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    scanner, server = _build_rig(args)
    try:
        if args.command == "single":
            out = scanner.capture_scan(args.name)
        elif args.command == "calib-pose":
            out = scanner.capture_calibration_pose(args.pose)
        else:
            def progress(p):
                print(f"stop {p.stop}/{p.total_stops}: elapsed "
                      f"{p.elapsed_s:.0f}s avg {p.avg_stop_s:.1f}s "
                      f"remaining ~{p.remaining_s:.0f}s", file=sys.stderr)

            from ..health import ScanHealthReport

            health = ScanHealthReport()
            stops = scanner.auto_scan_360(
                args.name, degrees_per_turn=args.degrees, turns=args.turns,
                resume=not args.no_resume, on_progress=progress,
                health=health)
            out = f"{len(stops)} stops"
            if health.failed_stops:
                print(f"degraded: stops {health.failed_stops} failed and "
                      f"were skipped", file=sys.stderr)
            health.emit()
            if args.health_json:
                health.write(args.health_json)
        print(f"done: {out}", file=sys.stderr)
        return 0
    finally:
        if server is not None:
            server.stop()


if __name__ == "__main__":
    raise SystemExit(main())
