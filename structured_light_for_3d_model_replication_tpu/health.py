"""Failure containment: error taxonomy, quality gates, scan health report.

The reference treats any hardware hiccup as fatal — one frame-capture
timeout raises out of a 24-stop, ~20-minute 360° run
(`server/sl_system.py:468-471`), and no compute stage ever inspects the
quality signals it already produces (per-pixel ``valid`` masks, ICP
fitness/RMSE). Real-time reconstruction systems treat degraded or dropped
frames as the NORMAL case: AGS drops low-covisibility frames by design
(PAPERS.md: arxiv 2509.00433) and GS-ICP SLAM keeps tracking through bad
registrations instead of aborting (arxiv 2403.12550). This module is the
shared vocabulary of that failure-containment layer:

* the structured error taxonomy (:class:`ScanFault` and subclasses) every
  hw/orchestration layer raises instead of bare ``RuntimeError``;
* :class:`QualityGates` — the host-side thresholds applied to the device
  pipeline's existing health signals (decode coverage, edge fitness/RMSE);
* :func:`gate_edges` — the gate/repair pass over a registered ring
  (consensus-step replacement for the sequential chain, information
  down-weighting for the pose-graph path);
* :class:`ScanHealthReport` — the per-stop/per-edge record of what was
  retried, dropped, bridged and degraded, emitted as JSON through
  :mod:`.utils.log` and surfaced by ``scan-360`` / ``merge-360``.

Everything here is host-side numpy/stdlib: gates read back a handful of
scalars per stop/edge and never change device program shapes (see
`models/scan360`'s gated path for the static-shape contract).
"""

from __future__ import annotations

import dataclasses
import json
import math

import numpy as np

from .utils import events
from .utils.log import get_logger

log = get_logger(__name__)


# ---------------------------------------------------------------------------
# Error taxonomy
# ---------------------------------------------------------------------------


class ScanFault(RuntimeError):
    """Base of the structured scan-pipeline error taxonomy.

    Layers raise the specific subclass; orchestration catches ``ScanFault``
    to contain a failure (retry, skip, degrade) without masking genuine
    programming errors, which stay ordinary exceptions.

    Construction records a ``fault``-severity event in the flight
    recorder (`utils.events`), tagged with whatever correlation context
    (scan_id/job_id/stop) is ambient at the raise site — so every
    taxonomy failure ships the journal of events that led to it, and a
    configured dump directory gets the last-N events as JSONL. The hook
    is best-effort by design: observability must never turn a contained
    fault into a crash.

    ``flight_severity`` is the journal severity of that event; designed
    flow-control subclasses (serve's backpressure rejections) override
    it to "warning" so only genuine faults trigger dump-on-fault.
    """

    flight_severity = "fault"

    def __init__(self, *args):
        super().__init__(*args)
        try:
            events.fault(self)
        except Exception as e:  # pragma: no cover — never mask the fault
            log.debug("flight recorder unavailable at raise site: %s", e)


class CaptureError(ScanFault):
    """A frame capture failed (timeout, unreadable/truncated file) after
    the configured retries."""


class StopQualityError(ScanFault):
    """A stop (or the whole session) fell below the quality gates and no
    degradation path could salvage it."""


# ---------------------------------------------------------------------------
# Quality gates
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QualityGates:
    """Host-side thresholds on the pipeline's existing health signals.

    Frozen (hashable) so it can ride inside ``Scan360Params`` — which is
    itself an ``lru_cache`` key for the compiled pipeline programs.
    """

    # Minimum fraction of decoded-valid pixels per stop. A stop below it is
    # dropped from the ring (its merge contribution is masked out; its ring
    # neighbors are bridged). Synthetic/real objects typically fill 5–40 %
    # of the frame, an all-black or saturated stack decodes to ~0.
    min_coverage: float = 0.02
    # Minimum ICP fitness per ring edge (`RegistrationResult.fitness` —
    # inlier fraction at the correspondence radius). A failing edge is
    # replaced by the ring-consensus step (sequential) and down-weighted
    # (posegraph).
    min_edge_fitness: float = 0.2
    # Optional absolute inlier-RMSE ceiling per edge (scene units). None
    # disables the RMSE gate (fitness alone gates by default: RMSE of a
    # zero-fitness edge is meaningless).
    max_edge_rmse: float | None = None
    # Information-matrix scale applied to rejected edges on the pose-graph
    # path: the edge stays in the graph (connectivity) but barely votes.
    posegraph_down_weight: float = 1e-3

    def coverage_ok(self, coverage: float) -> bool:
        return bool(coverage >= self.min_coverage)

    def edge_ok(self, fitness: float, rmse: float) -> bool:
        if not math.isfinite(float(fitness)) or fitness < self.min_edge_fitness:
            return False
        if self.max_edge_rmse is not None and (
                not math.isfinite(float(rmse)) or rmse > self.max_edge_rmse):
            return False
        return True


# ---------------------------------------------------------------------------
# Health report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StopHealth:
    """One stop's capture + gate record."""

    index: int
    angle_deg: float | None = None
    # captured | resumed | failed (capture gave up) | dropped (gate)
    status: str = "captured"
    coverage: float | None = None
    retries: int = 0            # extra capture attempts that recovered
    stop_attempts: int = 1      # full-stack capture attempts
    faults: list[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.coverage is not None:
            d["coverage"] = round(float(self.coverage), 4)
        return d


@dataclasses.dataclass
class EdgeHealth:
    """One ring edge's registration + gate record. ``gap`` counts the
    commanded turntable steps the edge spans (> 1 = a bridge over dropped
    stops)."""

    src: int
    dst: int
    gap: int = 1
    fitness: float | None = None
    rmse: float | None = None
    verdict: str = "pass"       # pass | reject
    action: str = "kept"        # kept | bridged | replaced_consensus
    #                           | down_weighted

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k in ("fitness", "rmse"):
            if d[k] is not None:
                d[k] = round(float(d[k]), 4)
        return d


@dataclasses.dataclass
class ScanHealthReport:
    """Aggregated capture→merge→mesh health for one 360° session.

    Accumulated by whoever touches the run (scanner, gated pipeline, CLI)
    and emitted once as a JSON document — the machine-readable answer to
    "what did this scan survive".
    """

    stops: dict[int, StopHealth] = dataclasses.field(default_factory=dict)
    edges: list[EdgeHealth] = dataclasses.field(default_factory=list)
    notes: list[str] = dataclasses.field(default_factory=list)
    rotate_timeouts: int = 0
    # Correlation ID linking this report to flight-recorder events and
    # tracer spans of the same run (set by `scanner.auto_scan_360`).
    scan_id: str | None = None

    # -- accumulation -------------------------------------------------------

    def stop(self, index: int, angle_deg: float | None = None) -> StopHealth:
        """Get-or-create the record for a stop."""
        rec = self.stops.get(index)
        if rec is None:
            rec = StopHealth(index=index, angle_deg=angle_deg)
            self.stops[index] = rec
        elif angle_deg is not None and rec.angle_deg is None:
            rec.angle_deg = angle_deg
        return rec

    def note(self, message: str, *args) -> None:
        text = message % args if args else message
        self.notes.append(text)
        log.warning("health: %s", text)

    # -- queries ------------------------------------------------------------

    @property
    def dropped_stops(self) -> list[int]:
        return sorted(i for i, s in self.stops.items()
                      if s.status == "dropped")

    @property
    def failed_stops(self) -> list[int]:
        return sorted(i for i, s in self.stops.items()
                      if s.status == "failed")

    @property
    def recovered_stops(self) -> list[int]:
        """Stops that needed retries but ended up captured."""
        return sorted(i for i, s in self.stops.items()
                      if s.retries > 0 and s.status in ("captured",
                                                        "resumed"))

    @property
    def rejected_edges(self) -> list[EdgeHealth]:
        return [e for e in self.edges if e.verdict == "reject"]

    # -- emission -----------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            **({"scan_id": self.scan_id} if self.scan_id else {}),
            "stops": [self.stops[i].to_dict()
                      for i in sorted(self.stops)],
            "edges": [e.to_dict() for e in self.edges],
            "dropped_stops": self.dropped_stops,
            "failed_stops": self.failed_stops,
            "recovered_stops": self.recovered_stops,
            "rejected_edges": len(self.rejected_edges),
            "rotate_timeouts": self.rotate_timeouts,
            "retries_total": int(sum(s.retries
                                     for s in self.stops.values())),
            "notes": list(self.notes),
        }

    def to_json(self, indent: int | None = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")
        log.info("health report written to %s", path)

    def emit(self) -> None:
        """One structured log line carrying the whole report (JSON-lines
        consumers get it via ``SL_TPU_LOG_JSON``)."""
        log.info("scan health: %s", self.to_json(indent=None))


# ---------------------------------------------------------------------------
# so3 helpers (host-side: gate repair works on a handful of 4×4s)
# ---------------------------------------------------------------------------


def _log_so3_np(R: np.ndarray) -> np.ndarray:
    """Rotation vector of a (3, 3) rotation; safe near identity."""
    cos = np.clip((np.trace(R) - 1.0) / 2.0, -1.0, 1.0)
    th = float(np.arccos(cos))
    v = np.array([R[2, 1] - R[1, 2], R[0, 2] - R[2, 0], R[1, 0] - R[0, 1]],
                 np.float64)
    if th < 1e-6:
        return 0.5 * v
    return v * (th / (2.0 * np.sin(th)))


def _exp_so3_np(w: np.ndarray) -> np.ndarray:
    """Rodrigues: rotation vector → (3, 3) rotation."""
    th = float(np.linalg.norm(w))
    if th < 1e-12:
        return np.eye(3)
    k = w / th
    K = np.array([[0, -k[2], k[1]], [k[2], 0, -k[0]], [-k[1], k[0], 0]],
                 np.float64)
    return np.eye(3) + np.sin(th) * K + (1 - np.cos(th)) * (K @ K)


def consensus_step_np(Ts: np.ndarray,
                      step_deg: float | None = None) -> np.ndarray | None:
    """Robust common per-step transform of a turntable ring (numpy port of
    `models.merge._consensus_step`, for host-side gate repair): median of
    the edge screws, trusting only edges whose rotation magnitude lands
    near the commanded step when it is known. Returns None when no edge
    survives the trust filter (nothing to vote with)."""
    Ts = np.asarray(Ts, np.float64)
    if Ts.shape[0] == 0:
        return None
    w = np.stack([_log_so3_np(T[:3, :3]) for T in Ts])
    t = Ts[:, :3, 3]
    if step_deg is not None:
        step = abs(float(step_deg)) * np.pi / 180.0
        ang = np.linalg.norm(w, axis=1)
        trusted = np.abs(ang - step) <= 0.35 * step
        if not trusted.any():
            trusted = np.ones_like(trusted)
        w, t = w[trusted], t[trusted]
    T = np.eye(4)
    T[:3, :3] = _exp_so3_np(np.median(w, axis=0))
    T[:3, 3] = np.median(t, axis=0)
    return T


def _matrix_power_T(T: np.ndarray, n: int) -> np.ndarray:
    out = np.eye(4)
    for _ in range(n):
        out = out @ T
    return out


# ---------------------------------------------------------------------------
# Ring edge construction (THE (src, dst, gap) convention, in one place)
# ---------------------------------------------------------------------------


def ring_edges(labels, loop: bool = False,
               span: int | None = None) -> list[tuple[int, int, int]]:
    """``(src, dst, gap)`` per ring edge over PHYSICAL stop labels, in the
    order every consumer shares: sequential edges ``labels[j+1]→labels[j]``
    first, then the optional loop edge ``labels[0]→labels[-1]``.

    ``gap`` counts commanded turntable steps: a label jump (a stop skipped
    at capture or dropped by a gate) makes the edge a bridge, and the
    consensus repair in :func:`gate_edges` raises the step transform to
    exactly that power. ``span`` is the full ring's step count for the
    loop edge's wrap-around gap (default: ``max(labels) + 1``)."""
    labels = [int(x) for x in labels]
    if any(b <= a for a, b in zip(labels, labels[1:])):
        raise ValueError(f"stop labels must be strictly increasing, "
                         f"got {labels}")
    edges = [(labels[j + 1], labels[j], labels[j + 1] - labels[j])
             for j in range(len(labels) - 1)]
    if loop:
        span = span if span is not None else max(labels) + 1
        edges.append((labels[0], labels[-1],
                      (labels[0] - labels[-1]) % span or span))
    return edges


# ---------------------------------------------------------------------------
# Edge gating
# ---------------------------------------------------------------------------


def gate_edges(
    edges: list[tuple[int, int, int]],
    Ts: np.ndarray,
    fit: np.ndarray,
    rmse: np.ndarray,
    infos: np.ndarray,
    gates: QualityGates,
    step_deg: float | None = None,
    report: ScanHealthReport | None = None,
):
    """Gate a registered ring's edges; repair the rejects.

    ``edges`` lists ``(src, dst, gap)`` per edge, aligned with ``Ts``
    (E, 4, 4), ``fit``/``rmse`` (E,), ``infos`` (E, 6, 6). Returns
    ``(Ts2, infos2, edge_health)`` where

    * rejected edges' transforms are replaced by the ring-consensus step
      raised to the edge's gap (the sequential chain then keeps the
      commanded geometry instead of a slid/failed ICP result), when a
      consensus exists — a ring with no passing gap-1 edge keeps the
      measured transforms and only records the verdicts;
    * rejected edges' information matrices are scaled by
      ``gates.posegraph_down_weight`` so the pose-graph path keeps
      connectivity but the edge barely votes.
    """
    Ts = np.array(np.asarray(Ts, np.float64), copy=True)
    infos = np.array(np.asarray(infos, np.float64), copy=True)
    fit = np.asarray(fit, np.float64)
    rmse = np.asarray(rmse, np.float64)
    ok = np.array([gates.edge_ok(fit[i], rmse[i])
                   for i in range(len(edges))], bool)
    health: list[EdgeHealth] = []
    step_T = None
    if not ok.all():
        base = [Ts[i] / 1.0 for i in range(len(edges))
                if ok[i] and edges[i][2] == 1]
        step_T = consensus_step_np(np.stack(base) if base else
                                   np.zeros((0, 4, 4)), step_deg)
    for i, (src, dst, gap) in enumerate(edges):
        e = EdgeHealth(src=src, dst=dst, gap=gap,
                       fitness=float(fit[i]), rmse=float(rmse[i]),
                       verdict="pass" if ok[i] else "reject",
                       action="kept" if gap == 1 else "bridged")
        if not ok[i]:
            infos[i] = infos[i] * gates.posegraph_down_weight
            if step_T is not None:
                Ts[i] = _matrix_power_T(step_T, gap)
                e.action = "replaced_consensus"
            else:
                e.action = "down_weighted"
            log.warning(
                "edge %d→%d rejected (fitness=%.3f rmse=%.4f) — %s",
                src, dst, fit[i], rmse[i], e.action)
            events.record("edge_rejected", severity="warning",
                          message=f"edge {src}->{dst} {e.action}",
                          scan_id=(report.scan_id if report is not None
                                   else None),
                          src=src, dst=dst, gap=gap,
                          fitness=round(float(fit[i]), 4),
                          rmse=round(float(rmse[i]), 4), action=e.action)
        health.append(e)
    if report is not None:
        report.edges.extend(health)
    return Ts.astype(np.float32), infos.astype(np.float32), health
