"""jaxlint — repo-native static analysis for JAX/TPU hazards.

Usage::

    python -m structured_light_for_3d_model_replication_tpu.analysis --check .

The framework (:mod:`.core`) is AST-only and stdlib-only; the built-in
rules (:mod:`.rules`) target the hazard classes this codebase has
actually shipped: unguarded pallas imports, host syncs inside jit,
implicit dtypes in the ops layer, ``static_argnames`` mistakes, jitted
reads of mutable globals, and PRNG key reuse.  See ``docs/JAXLINT.md``
for the workflow (running, suppressing, updating the baseline).
"""

from .core import (  # noqa: F401
    BASELINE_NAME,
    FileContext,
    REGISTRY,
    Rule,
    Violation,
    apply_baseline,
    iter_python_files,
    lint_file,
    lint_path,
    load_baseline,
    make_baseline,
    register,
)
from . import rules  # noqa: F401  (importing registers the built-in rules)
