"""jaxlint — repo-native static analysis for JAX/TPU hazards.

Usage::

    python -m structured_light_for_3d_model_replication_tpu.analysis --check .

Two passes (docs/JAXLINT.md):

* the **lexical** fast path (:mod:`.core` + :mod:`.rules`): per-file AST
  rules for the hazard classes this codebase has actually shipped —
  unguarded pallas imports, host syncs inside jit, implicit dtypes in
  the ops layer, ``static_argnames`` mistakes, jitted reads of mutable
  globals, PRNG key reuse;
* the **project** pass (:mod:`.project` over :mod:`.callgraph` +
  :mod:`.locks`): cross-module dataflow rules — lock-order inversions,
  blocking calls under locks, unlocked shared state across thread entry
  points, jit statics fed from loop variables, shape scalars at traced
  positions, and the warn-tier sharding-readiness family paving the
  multi-chip PR.

Everything is AST-only and stdlib-only, so the gate runs where jax
itself is absent. The runtime complements live in `utils/sanitize.py`
(``SL_SANITIZE=1``).
"""

from .core import (  # noqa: F401
    BASELINE_NAME,
    FileContext,
    REGISTRY,
    Rule,
    Violation,
    apply_baseline,
    iter_python_files,
    lint_file,
    lint_path,
    load_baseline,
    make_baseline,
    register,
    to_sarif,
)
from . import rules  # noqa: F401  (importing registers the built-in rules)
from .project import (  # noqa: F401
    PROJECT_REGISTRY,
    ProjectIndex,
    ProjectRule,
    build_index,
    project_lint,
    register_project,
    rule_severity,
)
from . import rules_concurrency  # noqa: F401  (registers project rules)
from . import rules_recompile    # noqa: F401
from . import rules_sharding     # noqa: F401
