"""Sharding-readiness rule family (WARN tier) — paving the multi-chip PR.

The ROADMAP's next tier shards the big Poisson/marching programs across
chips (`parallel/` mesh + pjit patterns). Two properties make a jit
entry point shard-ready, and both are annotations this rule can see:

* **donation** — the megabyte-scale scratch buffers on the
  ``poisson_sparse``/``marching_jax``/``scan360`` path should declare
  ``donate_argnums``/``donate_argnames`` so XLA reuses input memory
  instead of doubling the working set per chip;
* **sharding annotations** — public jit entry points should carry
  explicit ``in_shardings``/``out_shardings`` (or be wrapped by the
  `parallel/` mesh helpers) so the multi-chip PR can flip them from
  replicated to sharded without re-deriving the layout.

These are *warnings*, ratcheted separately through the baseline:
missing donation on today's single-chip path costs memory, not
correctness, and CPU CI cannot validate donation semantics at all (XLA
CPU ignores donation). The warn tier keeps the debt visible on every
lint run without blocking unrelated PRs.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .project import ProjectIndex, ProjectRule, register_project
from .rules import dotted

_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
_DONATE_KEYS = {"donate_argnums", "donate_argnames"}
_SHARD_KEYS = {"in_shardings", "out_shardings", "in_axis_resources",
               "out_axis_resources"}


def _kwarg_names(call: ast.Call) -> set[str]:
    return {k.arg for k in call.keywords if k.arg}


@register_project
class ShardingReadinessRule(ProjectRule):
    """jit sites on the heavy scan→mesh path missing donation and/or
    sharding annotations. Scoped to the modules the multi-chip PR will
    shard; one finding per jit site naming exactly what is missing."""

    name = "sharding-readiness"
    description = ("jit site on the poisson/marching/scan360 path "
                   "missing donate_argnums and/or sharding annotations "
                   "(warn tier — multi-chip paving)")
    severity = "warn"
    path_filter = ("ops/poisson_sparse", "ops/marching_jax",
                   "models/pipeline", "models/scan360")

    def check_project(self, index: ProjectIndex) -> Iterator:
        seen_calls: set[int] = set()
        # Decorated functions (both @jax.jit and @partial(jax.jit, …)).
        for fn in index.graph.functions.values():
            rel = fn.module.rel_path
            if not self.applies_to(rel):
                continue
            if fn.jit_call is not None:
                seen_calls.add(id(fn.jit_call))
                kw = _kwarg_names(fn.jit_call)
                v = self._site(index, rel, fn.jit_call, fn.name, kw)
                if v:
                    yield v
            elif fn.jitted and any(dotted(d) in _JIT_NAMES
                                   for d in fn.node.decorator_list):
                dec = next(d for d in fn.node.decorator_list
                           if dotted(d) in _JIT_NAMES)
                v = self._site(index, rel, dec, fn.name, set())
                if v:
                    yield v
        # Wrapping calls: `run = jax.jit(body, …)` — the scan360 idiom.
        for mod in index.graph.modules.values():
            if not self.applies_to(mod.rel_path):
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and id(node) not in \
                        seen_calls and dotted(node.func) in _JIT_NAMES:
                    label = (dotted(node.args[0]) if node.args else None) \
                        or "<lambda>"
                    v = self._site(index, mod.rel_path, node, label,
                                   _kwarg_names(node))
                    if v:
                        yield v

    def _site(self, index, rel_path, node, label, kwargs: set[str]):
        missing = []
        if not kwargs & _DONATE_KEYS:
            missing.append("donate_argnums (buffer donation)")
        if not kwargs & _SHARD_KEYS:
            missing.append("in_shardings/out_shardings")
        if not missing:
            return None
        return self.report(
            index, rel_path, node,
            f"jit site {label!r} on the scan->mesh path lacks "
            f"{' and '.join(missing)} — the multi-chip PR needs "
            "donation to keep per-chip memory flat and explicit "
            "shardings to flip from replicated to sharded "
            "(docs/JAXLINT.md, ROADMAP multi-chip item)")
