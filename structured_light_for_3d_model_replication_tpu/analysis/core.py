"""jaxlint core — rule registry, suppression comments, baseline ratchet.

The framework is AST-only and imports nothing outside the stdlib, so the
lint gate runs in a bare-python CI job (and in deployments where jax
itself is absent).  Rules live in :mod:`.rules`; each is a small class
registered under a kebab-case name and reporting :class:`Violation`
records against one parsed file at a time.

Three mechanisms keep the gate adoptable on a codebase that already has
violations:

* **suppressions** — ``# jaxlint: disable=RULE[,RULE2]`` on (or on a
  comment line directly above) the offending line silences those rules
  there; ``disable=all`` silences everything.  Suppressions are the
  mechanism for *justified* hazards — put the justification in the same
  comment.
* **baseline** — a committed JSON file (:data:`BASELINE_NAME`) holding
  per-(file, rule) grandfathered violation COUNTS.  The check fails only
  when a (file, rule) pair exceeds its baselined count, so new
  violations are blocked while old ones are paid down incrementally
  (count-based, not line-based, so unrelated edits don't shift entries).
* **per-rule path scoping** — a rule can restrict itself to path
  substrings (e.g. dtype discipline only under ``ops/``) and exempt
  designated files (e.g. ``*_pallas.py`` kernel modules ARE the
  sanctioned pallas import sites).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from collections import defaultdict
from pathlib import Path
from typing import Iterable, Iterator

__all__ = [
    "BASELINE_NAME", "FileContext", "Rule", "REGISTRY", "Violation",
    "apply_baseline", "iter_python_files", "lint_context", "lint_file",
    "lint_path", "load_baseline", "make_baseline", "parse_file",
    "register", "to_sarif",
]

BASELINE_NAME = "jaxlint_baseline.json"

# Directory parts never linted (caches, VCS internals, virtualenvs).
SKIP_DIR_PARTS = {"__pycache__", ".git", ".venv", "venv", "node_modules",
                  "build", "dist", ".eggs"}


@dataclasses.dataclass(frozen=True, order=True)
class Violation:
    """One finding: ``path:line:col: [rule] message``."""

    path: str          # posix path relative to the checked root
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


class FileContext:
    """One parsed file: source, AST, and the suppression table."""

    def __init__(self, rel_path: str, source: str, tree: ast.Module):
        self.rel_path = rel_path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> dict[int, set[str]]:
        """line → suppressed rule names.  A trailing comment applies to
        its own line; a comment-only line applies to the next code
        line (for statements whose line is already full)."""
        table: dict[int, set[str]] = {}
        pending: set[str] = set()
        for lineno, text in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(text)
            rules = ({r.strip() for r in m.group(1).split(",") if r.strip()}
                     if m else set())
            stripped = text.strip()
            if rules and stripped.startswith("#"):
                pending |= rules          # standalone comment → next code line
                continue
            if stripped and not stripped.startswith("#"):
                line_rules = rules | pending
                pending = set()
                if line_rules:
                    table[lineno] = line_rules
        return table

    def suppressed(self, rule: str, node: ast.AST) -> bool:
        """True when any source line spanned by ``node`` carries a
        ``disable=`` for this rule (multi-line calls can put the comment
        on whichever line fits)."""
        start = getattr(node, "lineno", None)
        if start is None:
            return False
        end = getattr(node, "end_lineno", None) or start
        for line in range(start, end + 1):
            rules = self.suppressions.get(line)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


class Rule:
    """Base class: subclass, set ``name``/``description``, implement
    :meth:`check`, and decorate with :func:`register`."""

    name: str = ""
    description: str = ""
    # "error" findings gate (exit 1); "warn" findings are reported and
    # ratcheted through the baseline but never fail the check.
    severity: str = "error"
    # Lint only files whose relative posix path contains one of these
    # substrings (empty tuple = every file).
    path_filter: tuple[str, ...] = ()
    # Skip files with any of these path PARTS (e.g. "tests") …
    exempt_parts: tuple[str, ...] = ()
    # … or with any of these filename suffixes (e.g. "_pallas.py").
    exempt_suffixes: tuple[str, ...] = ()

    def applies_to(self, rel_path: str) -> bool:
        if self.path_filter and not any(s in rel_path
                                        for s in self.path_filter):
            return False
        parts = rel_path.split("/")
        if any(p in parts for p in self.exempt_parts):
            return False
        if any(parts[-1].endswith(s) for s in self.exempt_suffixes):
            return False
        return True

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        raise NotImplementedError

    def report(self, ctx: FileContext, node: ast.AST,
               message: str) -> Violation | None:
        """Build a Violation unless a suppression comment covers it."""
        if ctx.suppressed(self.name, node):
            return None
        return Violation(ctx.rel_path, getattr(node, "lineno", 1),
                         getattr(node, "col_offset", 0), self.name, message)


REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    rule = cls()
    if not rule.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if rule.name in REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    REGISTRY[rule.name] = rule
    return cls


# ---------------------------------------------------------------------------
# Running the rules
# ---------------------------------------------------------------------------


def iter_python_files(root: Path) -> Iterator[Path]:
    if root.is_file():
        yield root
        return
    for p in sorted(root.rglob("*.py")):
        rel_parts = p.relative_to(root).parts
        if any(part in SKIP_DIR_PARTS or part.startswith(".")
               for part in rel_parts[:-1]):
            continue
        yield p


def parse_file(path: Path, rel_path: str):
    """(FileContext, None) or (None, parse-error Violation)."""
    try:
        source = path.read_text(encoding="utf-8")
    except (OSError, UnicodeDecodeError) as exc:
        # Same non-baselinable channel as a syntax error: an unreadable
        # file must fail the gate with a pointer, not a traceback.
        return None, Violation(rel_path, 1, 0, "parse-error",
                               f"could not read: {exc}")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        # Unparseable files fail the gate outright (parse-error is not a
        # registered rule, so it can neither be suppressed nor baselined).
        return None, Violation(rel_path, exc.lineno or 1, exc.offset or 0,
                               "parse-error",
                               f"could not parse: {exc.msg}")
    return FileContext(rel_path, source, tree), None


def lint_context(ctx: FileContext,
                 rules: Iterable[Rule] | None = None) -> list[Violation]:
    """Run the (lexical) rules over an already-parsed file."""
    out: list[Violation] = []
    for rule in (rules if rules is not None else REGISTRY.values()):
        if rule.applies_to(ctx.rel_path):
            out.extend(rule.check(ctx))
    out.sort()
    return out


def lint_file(path: Path, rel_path: str,
              rules: Iterable[Rule] | None = None) -> list[Violation]:
    ctx, err = parse_file(path, rel_path)
    if err is not None:
        return [err]
    return lint_context(ctx, rules)


def lint_path(root: Path,
              rules: Iterable[Rule] | None = None) -> list[Violation]:
    """Lint every ``*.py`` under ``root`` (or ``root`` itself if a file).
    Violation paths are posix-relative to ``root``."""
    root = root.resolve()
    out: list[Violation] = []
    for path in iter_python_files(root):
        rel = (path.name if root.is_file()
               else path.relative_to(root).as_posix())
        out.extend(lint_file(path, rel, rules))
    out.sort()
    return out


# ---------------------------------------------------------------------------
# SARIF 2.1.0 export
# ---------------------------------------------------------------------------

SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(violations: list[Violation],
             rules_meta: dict[str, tuple[str, str]]) -> dict:
    """SARIF 2.1.0 document for the given findings.

    ``rules_meta`` maps rule name → (description, severity); severities
    map warn→"warning", everything else →"error". Columns are
    1-indexed per the SARIF spec (Violation.col is 0-indexed AST
    col_offset)."""
    used = sorted({v.rule for v in violations} | set(rules_meta))
    rule_index = {name: i for i, name in enumerate(used)}
    rules = [{
        "id": name,
        "shortDescription": {
            "text": rules_meta.get(name, ("", "error"))[0]
                    or name},
        "defaultConfiguration": {
            "level": ("warning"
                      if rules_meta.get(name, ("", "error"))[1] == "warn"
                      else "error")},
    } for name in used]
    results = [{
        "ruleId": v.rule,
        "ruleIndex": rule_index[v.rule],
        "level": ("warning"
                  if rules_meta.get(v.rule, ("", "error"))[1] == "warn"
                  else "error"),
        "message": {"text": v.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": v.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, v.line),
                           "startColumn": v.col + 1},
            },
        }],
    } for v in violations]
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "jaxlint",
                "informationUri": "docs/JAXLINT.md",
                "rules": rules,
            }},
            "results": results,
            # No columnKind declared: startColumn comes from ast
            # col_offset (a UTF-8 byte offset), which is neither of the
            # declarable units — on the rare non-ASCII line it is a
            # best-effort approximation, and declaring a unit it does
            # not honor would just mis-anchor viewers confidently.
        }],
    }


# ---------------------------------------------------------------------------
# Baseline (count-based ratchet)
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> dict:
    data = json.loads(path.read_text(encoding="utf-8"))
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a jaxlint baseline "
                         "(expected an object with an 'entries' list)")
    return data


def baseline_counts(data: dict) -> dict[tuple[str, str], int]:
    return {(e["path"], e["rule"]): int(e["count"])
            for e in data.get("entries", [])}


def apply_baseline(violations: list[Violation], data: dict | None):
    """Split findings into (new, grandfathered_count, stale_entries).

    A (path, rule) group within its baselined count is grandfathered in
    full.  A group EXCEEDING its count surfaces every member (a count
    ratchet cannot tell old from new occurrences, so the whole group is
    shown for triage).  Entries whose current count dropped are reported
    stale so the baseline can be ratcheted down.
    """
    counts = baseline_counts(data) if data else {}
    groups: dict[tuple[str, str], list[Violation]] = defaultdict(list)
    for v in violations:
        groups[(v.path, v.rule)].append(v)
    new: list[Violation] = []
    grandfathered = 0
    for key, vs in sorted(groups.items()):
        allowed = counts.get(key, 0)
        if key[1] != "parse-error" and len(vs) <= allowed:
            grandfathered += len(vs)
        else:
            new.extend(vs)
    stale = [(path, rule, len(groups.get((path, rule), ())), allowed)
             for (path, rule), allowed in sorted(counts.items())
             if len(groups.get((path, rule), ())) < allowed]
    return new, grandfathered, stale


def make_baseline(violations: list[Violation],
                  old_data: dict | None = None) -> dict:
    """Baseline document grandfathering the given violations, keeping
    any human-written justifications from ``old_data``."""
    old_just = {}
    if old_data:
        old_just = {(e["path"], e["rule"]): e.get("justification", "")
                    for e in old_data.get("entries", [])}
    groups: dict[tuple[str, str], int] = defaultdict(int)
    for v in violations:
        if v.rule == "parse-error":
            continue    # apply_baseline never honors parse-error entries
        groups[(v.path, v.rule)] += 1
    entries = [
        {"path": path, "rule": rule, "count": count,
         "justification": old_just.get(
             (path, rule), "TODO: justify or fix (see docs/JAXLINT.md)")}
        for (path, rule), count in sorted(groups.items())
    ]
    return {
        "comment": "jaxlint grandfathered violations — see docs/JAXLINT.md. "
                   "Each entry allows `count` violations of `rule` in "
                   "`path`; exceeding it fails the gate.",
        "entries": entries,
    }
