"""Recompile-hazard rule family: jit callsites that churn the compile cache.

A jit program recompiles whenever its *static* signature changes — and a
static fed from a Python loop variable changes every iteration. The
complementary mistake is feeding a *shape-derived* Python int to a
TRACED position: the callee cannot do shape math with a tracer (it
raises at trace time) and, where it slips through as a weak-typed
constant instead, the value is baked into the program — one compile per
distinct value. Both are invisible per-file (the callsite and the jit
decorator live in different modules), hence project rules over the call
graph:

* ``jit-static-from-loop`` — a call to a project-jitted function where
  an argument mapped to a ``static_argnames`` parameter mentions an
  enclosing ``for``-loop target. One compile per iteration by
  construction (PR-5's recompile-storm detector sees it at runtime;
  this sees it in review).
* ``jit-traced-shape-scalar`` — an argument at a traced position that is
  ``len(x)`` / ``x.shape[i]`` / ``x.size`` / ``x.ndim``: shape-derived
  Python ints are almost always meant to be static (mark them in
  ``static_argnames``, or compute the quantity inside the jitted body
  from the traced operand itself).
"""

from __future__ import annotations

import ast
from typing import Iterator

from .callgraph import FunctionInfo
from .project import ProjectIndex, ProjectRule, register_project


def _loop_targets(node) -> set[str]:
    out: set[str] = set()
    t = node.target
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        out.update(e.id for e in t.elts if isinstance(e, ast.Name))
    return out


def _names_in(expr: ast.expr) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _map_args(callee: FunctionInfo, call: ast.Call):
    """[(param_name | None, arg_expr)] for the call's positional +
    keyword arguments against the callee's parameter list. Methods are
    not project-jitted here (jit wraps functions), so no self-shift."""
    out = []
    params = list(callee.params)
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break    # positional mapping unknowable past *args
        out.append((params[i] if i < len(params) else None, arg))
    for kw in call.keywords:
        if kw.arg is not None:
            out.append((kw.arg, kw.value))
    return out


def _resolved_jit_calls(index: ProjectIndex, fn: FunctionInfo):
    """(callee, call) for this function's calls that resolve to a
    project-jitted function."""
    for name, call in fn.calls:
        callee = index.graph._resolve(fn.module, fn, name)
        if callee is not None and callee.jitted:
            yield callee, call


@register_project
class StaticFromLoopRule(ProjectRule):
    """``static_argnames`` fed from a loop variable → compile per
    iteration. Blind spot: loops over a single-element iterable are
    technically fine — suppress with a justification there."""

    name = "jit-static-from-loop"
    description = ("jit static argument fed from an enclosing for-loop "
                   "variable (one compile per iteration)")

    def check_project(self, index: ProjectIndex) -> Iterator:
        for fn in index.graph.functions.values():
            loops = [n for n in ast.walk(fn.node)
                     if isinstance(n, (ast.For, ast.AsyncFor))]
            if not loops:
                continue
            jit_calls = list(_resolved_jit_calls(index, fn))
            if not jit_calls:
                continue
            for loop in loops:
                targets = _loop_targets(loop)
                if not targets:
                    continue
                body_calls = {id(n) for s in loop.body
                              for n in ast.walk(s)
                              if isinstance(n, ast.Call)}
                for callee, call in jit_calls:
                    if id(call) not in body_calls or \
                            not callee.static_names:
                        continue
                    for param, arg in _map_args(callee, call):
                        if param in callee.static_names and \
                                _names_in(arg) & targets:
                            v = self.report(
                                index, fn.module.rel_path, call,
                                f"static argument {param!r} of jitted "
                                f"{callee.name}() is fed from loop "
                                f"variable(s) "
                                f"{sorted(_names_in(arg) & targets)} — "
                                "one XLA compile per iteration; hoist "
                                "the static out of the loop or make the "
                                "argument traced")
                            if v:
                                yield v


_SHAPE_ATTRS = {"shape", "size", "ndim"}


def _is_shape_scalar(expr: ast.expr) -> bool:
    """len(x), x.shape[i], x.size, x.ndim — shape-derived Python ints."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and expr.func.id == "len" and len(expr.args) == 1:
        return True
    if isinstance(expr, ast.Subscript):
        base = expr.value
        return (isinstance(base, ast.Attribute)
                and base.attr == "shape")
    if isinstance(expr, ast.Attribute) and expr.attr in ("size", "ndim"):
        return True
    return False


@register_project
class TracedShapeScalarRule(ProjectRule):
    """A shape-derived Python int passed at a TRACED jit position.

    Only fires when the callee declares ``static_argnames`` for other
    parameters (the author is shape-aware — an undeclared-statics callee
    may genuinely consume the value as data) and the argument is
    *directly* ``len(...)``/``.shape[...]``/``.size``/``.ndim``."""

    name = "jit-traced-shape-scalar"
    description = ("shape-derived Python scalar (len/.shape/.size) "
                   "passed at a traced jit position")

    def check_project(self, index: ProjectIndex) -> Iterator:
        for fn in index.graph.functions.values():
            for callee, call in _resolved_jit_calls(index, fn):
                if not callee.static_names:
                    continue
                for param, arg in _map_args(callee, call):
                    if param is None or param in callee.static_names:
                        continue
                    if _is_shape_scalar(arg):
                        v = self.report(
                            index, fn.module.rel_path, call,
                            f"argument {param!r} of jitted "
                            f"{callee.name}() receives "
                            f"{ast.unparse(arg) if hasattr(ast, 'unparse') else 'a shape scalar'} "
                            "— a shape-derived Python int at a traced "
                            "position (trace error if used for shape "
                            "math, per-value constant otherwise); add "
                            f"it to static_argnames or derive it inside "
                            "from the traced operand")
                        if v:
                            yield v
