"""Pass 1 of the project engine: cross-module symbol table + call graph.

The lexical rules (:mod:`.rules`) see one file at a time; the hazard
classes the ROADMAP's multi-chip tier dies on — lock-order inversions
across ``serve/``, jit statics fed from loop variables two modules away,
shared mutable state reached from several thread entry points — are only
visible with a project-wide view. This module builds that view, still
AST-only and stdlib-only (the gate must run where jax is absent):

* **modules** — every parsed file with its dotted module name and an
  import alias table (``trace`` → ``pkg.utils.trace``), resolved through
  relative imports.
* **functions** — every function/method with its jit status (including
  ``functools.partial(jax.jit, …)`` decorators and ``jax.jit(fn)``
  wrapping assignments), declared static/donated argument names, and the
  calls it makes (dotted, unresolved).
* **call graph** — best-effort resolution of callsites to project
  functions: bare names to the same module, ``self.m()`` to the same
  class, ``alias.f()`` through the import table. Unresolvable calls
  (dynamic dispatch, external libraries) are simply absent — every
  consumer of the graph must treat it as an under-approximation.
* **thread entry points** — functions handed to ``threading.Thread(
  target=…)``, ``run()`` methods of ``Thread`` subclasses, and
  ``do_GET``-style handler methods of ``BaseHTTPRequestHandler``
  subclasses (each request runs on its own thread under
  ``ThreadingHTTPServer``). Reachability from these roots is what the
  concurrency rules consume.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from .rules import dotted, jit_decorator_call, is_jitted

__all__ = ["FunctionInfo", "ModuleInfo", "CallGraph", "build_call_graph"]

_HANDLER_METHODS = ("do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD",
                    "do_PATCH")
_THREAD_BASES = {"Thread", "threading.Thread"}
_HANDLER_BASES = {"BaseHTTPRequestHandler", "StreamRequestHandler",
                  "BaseRequestHandler"}


def module_name(rel_path: str) -> str:
    """'pkg/serve/jobs.py' → 'pkg.serve.jobs' ('pkg/__init__.py' → 'pkg')."""
    parts = rel_path[:-3].split("/") if rel_path.endswith(".py") \
        else rel_path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclasses.dataclass
class ModuleInfo:
    rel_path: str
    module: str                      # dotted name
    tree: ast.Module
    # local alias → fully dotted target ("trace" → "pkg.utils.trace",
    # "Job" → "pkg.serve.jobs.Job").
    imports: dict = dataclasses.field(default_factory=dict)
    # top-level function / class names defined here.
    defs: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class FunctionInfo:
    """One function or method, with everything pass 2 asks about."""

    qname: str                       # "pkg.mod:Class.meth" | "pkg.mod:fn"
    module: ModuleInfo
    node: ast.AST                    # FunctionDef | AsyncFunctionDef
    cls: str | None = None           # enclosing class name
    jitted: bool = False
    jit_call: ast.Call | None = None  # decorator Call carrying jit kwargs
    static_names: tuple = ()         # literal static_argnames, if any
    donated: bool = False            # donate_argnums/donate_argnames given
    sharded: bool = False            # in_shardings/out_shardings given
    params: tuple = ()               # positional-or-keyword parameter names
    # [(dotted callee text, ast.Call)] — unresolved callsites.
    calls: list = dataclasses.field(default_factory=list)

    @property
    def name(self) -> str:
        return self.node.name


def _package_of(module: str, is_init: bool) -> str:
    if is_init:
        return module
    return module.rpartition(".")[0]


def _resolve_relative(package: str, level: int, mod: str | None) -> str:
    """Absolute dotted target of ``from <level dots><mod> import …``."""
    parts = package.split(".") if package else []
    if level > 1:
        parts = parts[: max(0, len(parts) - (level - 1))]
    if mod:
        parts += mod.split(".")
    return ".".join(parts)


def _collect_imports(info: ModuleInfo, is_init: bool) -> None:
    package = _package_of(info.module, is_init)
    for node in ast.walk(info.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                info.imports[name] = target
        elif isinstance(node, ast.ImportFrom):
            base = (_resolve_relative(package, node.level, node.module)
                    if node.level else (node.module or ""))
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                info.imports[name] = (f"{base}.{alias.name}" if base
                                      else alias.name)


_JIT_WRAPPERS = {"jax.jit", "jit", "jax.pjit", "pjit"}


def _jit_kwargs(call: ast.Call) -> dict[str, ast.expr]:
    return {k.arg: k.value for k in call.keywords if k.arg}


def _literal_strings(node: ast.expr | None) -> tuple:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
        return tuple(out)
    return ()


def _function_info(mod: ModuleInfo, fn, cls: str | None) -> FunctionInfo:
    qual = f"{cls}.{fn.name}" if cls else fn.name
    info = FunctionInfo(qname=f"{mod.module}:{qual}", module=mod, node=fn,
                        cls=cls, jitted=is_jitted(fn),
                        params=tuple(a.arg for a in (fn.args.posonlyargs
                                                     + fn.args.args
                                                     + fn.args.kwonlyargs)))
    for dec in fn.decorator_list:
        call = jit_decorator_call(dec)
        if call is not None:
            info.jit_call = call
            kw = _jit_kwargs(call)
            info.static_names = _literal_strings(kw.get("static_argnames"))
            info.donated = ("donate_argnums" in kw
                            or "donate_argnames" in kw)
            info.sharded = ("in_shardings" in kw or "out_shardings" in kw
                            or "in_axis_resources" in kw
                            or "out_axis_resources" in kw)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name:
                info.calls.append((name, node))
    return info


class CallGraph:
    """The project symbol table + resolved call edges + thread roots."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}      # rel_path → info
        self.by_module: dict[str, ModuleInfo] = {}    # dotted → info
        self.functions: dict[str, FunctionInfo] = {}  # qname → info
        # caller qname → set of callee qnames.
        self.callees: dict[str, set] = {}
        self.thread_roots: set[str] = set()
        # "module:Class" for every project class.
        self.classes: set[str] = set()
        # ("module:Class", attr) → "module:Class" — inferred instance-
        # attribute types (self.x = Ctor(...) / self.x = annotated_param).
        self.attr_types: dict[tuple, str] = {}
        # method name → set of "module:Class.method" (unique-name
        # fallback resolution for obj.method() calls).
        self._methods_by_name: dict[str, set] = {}

    # -- building ----------------------------------------------------------

    def add_module(self, rel_path: str, tree: ast.Module) -> ModuleInfo:
        mod = ModuleInfo(rel_path=rel_path, module=module_name(rel_path),
                         tree=tree)
        _collect_imports(mod, rel_path.endswith("__init__.py"))
        self.modules[rel_path] = mod
        self.by_module[mod.module] = mod
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                mod.defs.add(stmt.name)
        # Functions: top-level and one class level deep (methods).
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, stmt, None)
            elif isinstance(stmt, ast.ClassDef):
                self.classes.add(f"{mod.module}:{stmt.name}")
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._add_function(mod, sub, stmt.name)
        return mod

    def _add_function(self, mod: ModuleInfo, fn, cls: str | None) -> None:
        info = _function_info(mod, fn, cls)
        self.functions[info.qname] = info
        if cls is not None:
            self._methods_by_name.setdefault(fn.name, set()).add(
                info.qname)

    def _resolve_class(self, mod: ModuleInfo, name: str) -> str | None:
        """Dotted expression text → 'module:Class' when it names a
        project class (directly or through the import table)."""
        if not name:
            return None
        if f"{mod.module}:{name}" in self.classes:
            return f"{mod.module}:{name}"
        head, _, rest = name.partition(".")
        target = mod.imports.get(head)
        if target is None:
            return None
        full = f"{target}.{rest}" if rest else target
        mod_part, _, cls_part = full.rpartition(".")
        key = f"{mod_part}:{cls_part}"
        return key if key in self.classes else None

    def _infer_attr_types(self) -> None:
        """self.x = Ctor(...) and self.x = <annotated ctor param> give
        instance attributes a class, so self.x.m() / obj.x.m() chains
        resolve to real methods."""
        for info in self.functions.values():
            if info.cls is None:
                continue
            owner = f"{info.module.module}:{info.cls}"
            ann = {}
            args = info.node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.annotation is not None:
                    text = None
                    if isinstance(a.annotation, ast.Constant) and \
                            isinstance(a.annotation.value, str):
                        text = a.annotation.value.strip().split("|")[0] \
                            .strip().strip('"')
                    else:
                        text = dotted(a.annotation)
                    if text:
                        ann[a.arg] = text
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    cls_key = None
                    if isinstance(node.value, ast.Call):
                        cls_key = self._resolve_class(
                            info.module, dotted(node.value.func) or "")
                    elif isinstance(node.value, ast.Name) and \
                            node.value.id in ann:
                        cls_key = self._resolve_class(
                            info.module, ann[node.value.id])
                    if cls_key is not None:
                        self.attr_types[(owner, t.attr)] = cls_key

    def finalize(self) -> None:
        """Resolve call edges and thread roots (after every module is in)."""
        self._infer_attr_types()
        # jax.jit(fn) / pjit(fn) wrapping assignments also make fn jitted:
        # `reconstruct = jax.jit(_reconstruct)` is the scan360 idiom.
        for mod in self.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted(node.func)
                if fname in _JIT_WRAPPERS and node.args:
                    target = self._resolve(mod, None,
                                           dotted(node.args[0]) or "")
                    if target is not None:
                        target.jitted = True
        for info in self.functions.values():
            for name, call in info.calls:
                target = self._resolve(info.module, info, name)
                if target is not None:
                    self.callees.setdefault(info.qname, set()).add(
                        target.qname)
                if name.split(".")[-1] == "Thread":
                    self._thread_target(info, call)
        for info in self.functions.values():
            if info.cls is None:
                continue
            cls_node = next((s for s in info.module.tree.body
                             if isinstance(s, ast.ClassDef)
                             and s.name == info.cls), None)
            bases = {dotted(b) or "" for b in cls_node.bases} \
                if cls_node else set()
            base_tails = {b.split(".")[-1] for b in bases}
            if info.name == "run" and base_tails & _THREAD_BASES:
                self.thread_roots.add(info.qname)
            if info.name in _HANDLER_METHODS and (
                    base_tails & _HANDLER_BASES
                    or any(b.endswith("Handler") for b in base_tails)):
                self.thread_roots.add(info.qname)

    def _thread_target(self, caller: FunctionInfo, call: ast.Call) -> None:
        target_expr = next((k.value for k in call.keywords
                            if k.arg == "target"), None)
        if target_expr is None:
            return
        resolved = self._resolve(caller.module, caller,
                                 dotted(target_expr) or "")
        if resolved is not None:
            self.thread_roots.add(resolved.qname)

    # -- resolution --------------------------------------------------------

    # Method names too generic for the unique-name fallback: they
    # collide with dict/list/set/str/file/threading builtins, so a
    # lexical match would mis-resolve container calls to project code.
    _GENERIC_METHODS = frozenset({
        "get", "pop", "append", "add", "update", "clear", "remove",
        "extend", "insert", "discard", "copy", "read", "write", "close",
        "flush", "keys", "values", "items", "setdefault", "popleft",
        "appendleft", "sort", "split", "join", "strip", "format",
        "encode", "decode", "wait", "set", "start", "run", "put",
        "send", "recv", "acquire", "release", "item", "mean", "sum",
        "reshape", "astype", "count", "index", "search", "match",
        "group", "open", "seek", "tell", "getvalue", "inc", "dec",
    })

    def _resolve(self, mod: ModuleInfo, caller: FunctionInfo | None,
                 name: str) -> FunctionInfo | None:
        """Best-effort: a dotted callsite text → a project FunctionInfo."""
        if not name:
            return None
        head, _, rest = name.partition(".")
        # self.m() → method of the caller's class (same module);
        # self.attr[.attr…].m() → through the inferred attribute types.
        if head == "self" and caller is not None and caller.cls and rest:
            parts = rest.split(".")
            if len(parts) == 1:
                return self.functions.get(
                    f"{mod.module}:{caller.cls}.{parts[0]}")
            cur = f"{mod.module}:{caller.cls}"
            for attr in parts[:-1]:
                cur = self.attr_types.get((cur, attr))
                if cur is None:
                    break
            if cur is not None:
                hit = self.functions.get(f"{cur}.{parts[-1]}")
                if hit is not None:
                    return hit
            return self._unique_method(parts[-1])
        # Bare name → same-module function.
        if not rest:
            return self.functions.get(f"{mod.module}:{head}")
        # alias.path → through the import table.
        target = mod.imports.get(head)
        if target is not None:
            full = f"{target}.{rest}"
            mod_part, _, fn_part = full.rpartition(".")
            hit = self.functions.get(f"{mod_part}:{fn_part}")
            if hit is not None:
                return hit
            # `from .mod import Class` + Class.method chains — one more
            # split: pkg.mod.Class.method → pkg.mod:Class.method.
            mod2, _, cls_part = mod_part.rpartition(".")
            if mod2:
                hit = self.functions.get(f"{mod2}:{cls_part}.{fn_part}")
                if hit is not None:
                    return hit
            return None
        # obj.m() on an untyped local: unique-method-name fallback.
        return self._unique_method(name.rsplit(".", 1)[-1])

    def _unique_method(self, method: str) -> FunctionInfo | None:
        """The project-wide unique method of this name, unless the name
        is generic enough to collide with builtins."""
        if method in self._GENERIC_METHODS or method.startswith("__"):
            return None
        cands = self._methods_by_name.get(method, ())
        if len(cands) == 1:
            return self.functions.get(next(iter(cands)))
        return None

    # -- queries -----------------------------------------------------------

    def reachable(self, root: str) -> set[str]:
        """qnames reachable from ``root`` over resolved call edges
        (including ``root`` itself)."""
        seen = {root}
        frontier = [root]
        while frontier:
            cur = frontier.pop()
            for nxt in self.callees.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen

    def iter_jitted(self) -> Iterator[FunctionInfo]:
        for info in self.functions.values():
            if info.jitted:
                yield info
