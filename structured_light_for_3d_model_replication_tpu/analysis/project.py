"""Pass 2 driver: project-wide (dataflow-aware) rules over the call graph.

`jaxlint v1` rules are lexical and per-file — that stays the fast path
(:func:`~.core.lint_path`). This module adds the *project* pass:

1. **pass 1** parses every file once and builds the
   :class:`~.callgraph.CallGraph` (symbol table, resolved call edges,
   thread entry points) plus the :class:`~.locks.LockModel` (declared
   locks, acquisition order, calls made under locks);
2. **pass 2** runs every registered :class:`ProjectRule` over that index.

Project rules report plain :class:`~.core.Violation` records, honor the
same ``# jaxlint: disable=RULE`` suppression comments (via the per-file
:class:`~.core.FileContext`), ratchet through the same baseline, and may
declare ``severity = "warn"`` — warn-tier findings are reported and
baselined but never fail the gate (the sharding-readiness family paves
the multi-chip PR without blocking unrelated work).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator

from .callgraph import CallGraph
from .core import FileContext, Violation, iter_python_files, parse_file
from .locks import LockModel, build_lock_model

__all__ = ["ProjectIndex", "ProjectRule", "PROJECT_REGISTRY",
           "register_project", "build_index", "project_lint",
           "rule_severity"]


class ProjectIndex:
    """Everything pass 2 reads: parsed files + call graph + lock model."""

    def __init__(self, root: Path):
        self.root = root
        self.contexts: dict[str, FileContext] = {}   # rel_path → ctx
        self.parse_errors: list[Violation] = []
        self.graph = CallGraph()
        self.locks: LockModel | None = None

    @classmethod
    def build(cls, root: Path) -> "ProjectIndex":
        """Parse every file ONCE; the driver reuses ``contexts`` for the
        lexical pass (no second read/parse) and ``parse_errors`` carries
        the unreadable/unparseable files both passes must report."""
        root = root.resolve()
        index = cls(root)
        for path in iter_python_files(root):
            rel = (path.name if root.is_file()
                   else path.relative_to(root).as_posix())
            ctx, err = parse_file(path, rel)
            if err is not None:
                index.parse_errors.append(err)
                continue
            index.contexts[rel] = ctx
            index.graph.add_module(rel, ctx.tree)
        index.graph.finalize()
        index.locks = build_lock_model(index.graph)
        return index

    def context_for(self, module) -> FileContext | None:
        """FileContext of a ModuleInfo (for suppression checks)."""
        return self.contexts.get(module.rel_path)


class ProjectRule:
    """Like :class:`~.core.Rule` but checked once against the whole
    project index. ``severity`` is ``"error"`` (gates) or ``"warn"``
    (reported + ratcheted, never fails the gate)."""

    name: str = ""
    description: str = ""
    severity: str = "error"
    # Findings are only REPORTED for files matching these (same semantics
    # as core.Rule): the index itself always covers the whole tree.
    path_filter: tuple = ()
    exempt_parts: tuple = ("tests", "scripts")
    exempt_suffixes: tuple = ()

    def applies_to(self, rel_path: str) -> bool:
        if self.path_filter and not any(s in rel_path
                                        for s in self.path_filter):
            return False
        parts = rel_path.split("/")
        if any(p in parts for p in self.exempt_parts):
            return False
        if any(parts[-1].endswith(s) for s in self.exempt_suffixes):
            return False
        return True

    def check_project(self, index: ProjectIndex) -> Iterator[Violation]:
        raise NotImplementedError

    def report(self, index: ProjectIndex, rel_path: str, node: ast.AST,
               message: str) -> Violation | None:
        if not self.applies_to(rel_path):
            return None
        ctx = index.contexts.get(rel_path)
        if ctx is not None and ctx.suppressed(self.name, node):
            return None
        return Violation(rel_path, getattr(node, "lineno", 1),
                         getattr(node, "col_offset", 0), self.name,
                         message)


PROJECT_REGISTRY: dict[str, ProjectRule] = {}


def register_project(cls: type[ProjectRule]) -> type[ProjectRule]:
    rule = cls()
    if not rule.name:
        raise ValueError(f"project rule {cls.__name__} has no name")
    if rule.name in PROJECT_REGISTRY:
        raise ValueError(f"duplicate project rule name {rule.name!r}")
    PROJECT_REGISTRY[rule.name] = rule
    return cls


def build_index(root: Path) -> ProjectIndex:
    return ProjectIndex.build(Path(root))


def project_lint(root: Path, rules=None,
                 index: ProjectIndex | None = None) -> list[Violation]:
    """Run every project rule over ``root`` (or a prebuilt index);
    Violation paths are posix-relative to ``root`` (same contract as
    core.lint_path). Parse errors are NOT included — the caller's
    lexical pass owns reporting those."""
    if index is None:
        index = ProjectIndex.build(Path(root))
    out: list[Violation] = []
    for rule in (rules if rules is not None else PROJECT_REGISTRY.values()):
        out.extend(v for v in rule.check_project(index) if v is not None)
    out.sort()
    return out


def rule_severity(name: str) -> str:
    """'error' | 'warn' for a registered rule name (lexical or project);
    unknown names — parse-error included — gate as errors."""
    from .core import REGISTRY

    rule = PROJECT_REGISTRY.get(name) or REGISTRY.get(name)
    return getattr(rule, "severity", "error")
