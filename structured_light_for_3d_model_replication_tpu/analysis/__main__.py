"""jaxlint CLI.

``python -m structured_light_for_3d_model_replication_tpu.analysis
--check .`` lints every ``*.py`` under the given roots and exits 0 iff
no violations beyond the committed baseline
(``jaxlint_baseline.json`` at the first checked root) remain.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path

from .core import (BASELINE_NAME, REGISTRY, apply_baseline, lint_path,
                   load_baseline, make_baseline)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m structured_light_for_3d_model_replication_tpu"
             ".analysis",
        description="jaxlint: static analysis for JAX/TPU hazards "
                    "(see docs/JAXLINT.md)")
    p.add_argument("--check", nargs="+", metavar="PATH",
                   help="files or directories to lint")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help=f"baseline file (default: <first PATH>/"
                        f"{BASELINE_NAME} when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to grandfather the current "
                        "violations (keeps existing justifications)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-violation output (summary only)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for name in sorted(REGISTRY):
            print(f"{name}: {REGISTRY[name].description}")
        return 0
    if not args.check:
        build_parser().print_usage(sys.stderr)
        print("error: --check PATH is required (or --list-rules)",
              file=sys.stderr)
        return 2

    roots = [Path(p) for p in args.check]
    for root in roots:
        if not root.exists():
            print(f"error: no such path: {root}", file=sys.stderr)
            return 2

    baseline_path = (Path(args.baseline) if args.baseline else
                     _default_baseline(roots[0]))
    # Violation paths are reported — and matched against the baseline —
    # relative to the baseline's directory, so a subtree invocation
    # (`--check <pkg>/ops` from the repo root) still matches the repo
    # baseline's repo-root-relative entry paths.
    anchor = baseline_path.parent.resolve()

    violations = []
    covered = []   # anchored path prefixes this run actually linted
    for root in roots:
        vs = lint_path(root)
        base = root.resolve()
        is_file = base.is_file()
        if is_file:
            base = base.parent
        try:
            prefix = base.relative_to(anchor).as_posix()
            if prefix == ".":
                prefix = ""
        except ValueError:
            prefix = None    # root outside the anchor: keep root-relative
        if prefix:
            vs = [dataclasses.replace(v, path=f"{prefix}/{v.path}")
                  for v in vs]
        if prefix is not None:
            covered.append(f"{prefix}/{root.name}".lstrip("/")
                           if is_file else prefix)
        violations.extend(vs)
    baseline = None
    if not args.no_baseline and baseline_path.exists() \
            and not args.update_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as exc:
            print(f"error: bad baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2

    if args.update_baseline:
        old = None
        if baseline_path.exists():
            try:
                old = load_baseline(baseline_path)
            except (ValueError, json.JSONDecodeError) as exc:
                print(f"error: bad baseline {baseline_path}: {exc}",
                      file=sys.stderr)
                return 2
        doc = make_baseline(violations, old)
        if old is not None:
            # A subtree run sees only its own violations — keep old
            # entries for paths this run did not lint, or a scoped
            # --update-baseline would silently drop the rest of the
            # repo's grandfathered entries.
            def _was_linted(path: str) -> bool:
                return any(c == "" or path == c or path.startswith(c + "/")
                           for c in covered)
            kept = [e for e in old.get("entries", [])
                    if not _was_linted(e["path"])]
            doc["entries"] = sorted(kept + doc["entries"],
                                    key=lambda e: (e["path"], e["rule"]))
        baseline_path.write_text(json.dumps(doc, indent=2) + "\n",
                                 encoding="utf-8")
        n_gf = sum(e["count"] for e in doc["entries"])
        print(f"jaxlint: wrote {baseline_path} grandfathering "
              f"{n_gf} violation(s) in "
              f"{len(doc['entries'])} (file, rule) group(s)")
        if n_gf < len(violations):
            print(f"jaxlint: {len(violations) - n_gf} parse-error "
                  "violation(s) NOT baselined (unparseable files always "
                  "fail the gate — fix them)", file=sys.stderr)
        return 0

    new, grandfathered, stale = apply_baseline(violations, baseline)

    if not args.quiet:
        for v in new:
            print(v.format())
        for path, rule, have, allowed in stale:
            print(f"jaxlint: stale baseline entry {path} [{rule}]: "
                  f"allows {allowed}, found {have} — ratchet it down with "
                  f"--update-baseline", file=sys.stderr)

    summary = (f"jaxlint: {len(new)} new violation(s), "
               f"{grandfathered} grandfathered, "
               f"{len(REGISTRY)} rules")
    print(summary, file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


def _default_baseline(root: Path) -> Path:
    """Nearest baseline at or ABOVE the checked root, so subtree
    invocations honor the committed repo baseline; falls back to
    ``<root>/jaxlint_baseline.json`` when none exists up the tree."""
    base = (root if root.is_dir() else root.parent).resolve()
    for d in (base, *base.parents):
        cand = d / BASELINE_NAME
        if cand.exists():
            return cand
    return base / BASELINE_NAME


if __name__ == "__main__":
    sys.exit(main())
