"""jaxlint CLI.

``python -m structured_light_for_3d_model_replication_tpu.analysis
--check .`` lints every ``*.py`` under the given roots — the lexical
fast path plus the cross-module project pass (``--fast`` skips the
latter) — and exits 0 iff no *error-tier* violations beyond the
committed baseline (``jaxlint_baseline.json`` at the first checked
root) remain. Warn-tier findings (the sharding-readiness family) are
reported and ratcheted but never gate.

Exit codes: 0 clean (modulo baseline, warnings allowed), 1 new
error-tier violations, 2 usage errors / bad baseline / DEAD baseline
entries (entries matching no current violation — fix with
``--prune-baseline``).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path

from .core import (BASELINE_NAME, REGISTRY, apply_baseline, lint_context,
                   lint_path, load_baseline, make_baseline, to_sarif)
from .project import (PROJECT_REGISTRY, ProjectIndex, project_lint,
                      rule_severity)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m structured_light_for_3d_model_replication_tpu"
             ".analysis",
        description="jaxlint: static analysis for JAX/TPU hazards "
                    "(see docs/JAXLINT.md)")
    p.add_argument("--check", nargs="+", metavar="PATH",
                   help="files or directories to lint")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help=f"baseline file (default: <first PATH>/"
                        f"{BASELINE_NAME} when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to grandfather the current "
                        "violations (keeps existing justifications)")
    p.add_argument("--prune-baseline", action="store_true",
                   help="drop dead baseline entries (no matching "
                        "violation) and ratchet stale counts down, then "
                        "run the check against the pruned baseline")
    p.add_argument("--fast", action="store_true",
                   help="lexical rules only (skip the cross-module "
                        "project pass)")
    p.add_argument("--sarif", metavar="FILE", default=None,
                   help="also write the reported findings as SARIF 2.1.0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-violation output (summary only)")
    return p


def _all_rules_meta() -> dict[str, tuple[str, str]]:
    meta = {name: (r.description, getattr(r, "severity", "error"))
            for name, r in REGISTRY.items()}
    meta.update({name: (r.description, r.severity)
                 for name, r in PROJECT_REGISTRY.items()})
    return meta


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        meta = _all_rules_meta()
        for name in sorted(meta):
            desc, severity = meta[name]
            tier = " [warn]" if severity == "warn" else ""
            scope = ("project"
                     if name in PROJECT_REGISTRY else "lexical")
            print(f"{name} ({scope}{tier}): {desc}")
        return 0
    if not args.check:
        build_parser().print_usage(sys.stderr)
        print("error: --check PATH is required (or --list-rules)",
              file=sys.stderr)
        return 2

    roots = [Path(p) for p in args.check]
    for root in roots:
        if not root.exists():
            print(f"error: no such path: {root}", file=sys.stderr)
            return 2

    baseline_path = (Path(args.baseline) if args.baseline else
                     _default_baseline(roots[0]))
    # Violation paths are reported — and matched against the baseline —
    # relative to the baseline's directory, so a subtree invocation
    # (`--check <pkg>/ops` from the repo root) still matches the repo
    # baseline's repo-root-relative entry paths.
    anchor = baseline_path.parent.resolve()

    violations = []
    covered = []   # anchored path prefixes this run actually linted
    for root in roots:
        if args.fast:
            vs = lint_path(root)
        else:
            # One parse feeds both passes: the index's FileContexts run
            # the lexical rules, then the project rules.
            index = ProjectIndex.build(root)
            vs = list(index.parse_errors)
            for ctx in index.contexts.values():
                vs.extend(lint_context(ctx))
            vs.extend(project_lint(root, index=index))
            vs.sort()
        base = root.resolve()
        is_file = base.is_file()
        if is_file:
            base = base.parent
        try:
            prefix = base.relative_to(anchor).as_posix()
            if prefix == ".":
                prefix = ""
        except ValueError:
            prefix = None    # root outside the anchor: keep root-relative
        if prefix:
            vs = [dataclasses.replace(v, path=f"{prefix}/{v.path}")
                  for v in vs]
        if prefix is not None:
            covered.append(f"{prefix}/{root.name}".lstrip("/")
                           if is_file else prefix)
        violations.extend(vs)
    baseline = None
    if not args.no_baseline and baseline_path.exists() \
            and not args.update_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as exc:
            print(f"error: bad baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2

    # A --fast run never produces project-rule findings, so baseline
    # entries for those rules are out of scope: they must be neither
    # declared dead/stale nor dropped by --update/--prune (their absence
    # says nothing — the rules were not run).
    rules_not_run = set(PROJECT_REGISTRY) if args.fast else set()

    def _was_linted(path: str) -> bool:
        return any(c == "" or path == c or path.startswith(c + "/")
                   for c in covered)

    def _entry_in_scope(path: str, rule_name: str) -> bool:
        """Could THIS run have produced violations for the entry? Only
        then does the entry's absence mean anything. Rule path_filters
        match ROOT-relative paths, so a subtree run strips the prefix
        before asking the rule (`--check <pkg>/ops` renames decode.py's
        path to 'decode.py', which no longer matches the 'ops/' filter —
        the rule did not run there, the entry is NOT dead)."""
        if rule_name in rules_not_run:
            return False
        rule = REGISTRY.get(rule_name) or PROJECT_REGISTRY.get(rule_name)
        if rule is None:
            # Unknown (renamed/removed) rule: genuinely prunable debt —
            # scope by path coverage alone.
            return _was_linted(path)
        for c in covered:
            if c == "" or path == c or path.startswith(c + "/"):
                rel = path[len(c):].lstrip("/") if c else path
                if rule.applies_to(rel):
                    return True
        return False

    if args.update_baseline:
        return _update_baseline(args, baseline_path, violations,
                                _entry_in_scope)

    if args.prune_baseline and baseline is not None:
        baseline = _prune(baseline_path, baseline, violations,
                          _entry_in_scope)

    new, grandfathered, stale = apply_baseline(violations, baseline)
    new_errors = [v for v in new if rule_severity(v.rule) != "warn"]
    new_warns = [v for v in new if rule_severity(v.rule) == "warn"]
    # Dead entries: baselined (path, rule) pairs this run's rules
    # actually covered that match zero current violations. Stale-but-
    # alive entries (count dropped, not to zero) stay a warning; dead
    # ones fail the check — a baseline full of ghosts ratchets nothing.
    dead = [(path, rule, have, allowed)
            for path, rule, have, allowed in stale
            if have == 0 and _entry_in_scope(path, rule)]
    shown = [s for s in stale if s[2] > 0 or s in dead]

    if not args.quiet:
        for v in new_errors:
            print(v.format())
        for v in new_warns:
            print(f"warning: {v.format()}")
        for path, rule, have, allowed in shown:
            kind = "DEAD" if (path, rule, have, allowed) in dead \
                else "stale"
            print(f"jaxlint: {kind} baseline entry {path} [{rule}]: "
                  f"allows {allowed}, found {have} — fix with "
                  f"--prune-baseline", file=sys.stderr)

    if args.sarif:
        doc = to_sarif(sorted(new_errors + new_warns), _all_rules_meta())
        Path(args.sarif).write_text(json.dumps(doc, indent=2) + "\n",
                                    encoding="utf-8")

    per_rule: dict[str, int] = {}
    for v in new_errors + new_warns:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    detail = ", ".join(f"{r}: {n}" for r, n in sorted(per_rule.items()))
    summary = (f"jaxlint: {len(new_errors)} new violation(s), "
               f"{len(new_warns)} warning(s), "
               f"{grandfathered} grandfathered, "
               f"{len(REGISTRY) + len(PROJECT_REGISTRY)} rules"
               + (f" [{detail}]" if detail else ""))
    print(summary, file=sys.stderr if new_errors else sys.stdout)
    if new_errors:
        return 1
    if dead:
        print(f"jaxlint: {len(dead)} dead baseline entr"
              f"{'y' if len(dead) == 1 else 'ies'} — run "
              "--prune-baseline", file=sys.stderr)
        return 2
    return 0


def _update_baseline(args, baseline_path: Path, violations,
                     entry_in_scope) -> int:
    old = None
    if baseline_path.exists():
        try:
            old = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as exc:
            print(f"error: bad baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2
    doc = make_baseline(violations, old)
    if old is not None:
        # A subtree (or --fast) run sees only its own violations — keep
        # old entries this run's rules could not have re-observed, or a
        # scoped --update-baseline would silently drop the rest of the
        # repo's grandfathered entries.
        kept = [e for e in old.get("entries", [])
                if not entry_in_scope(e["path"], e["rule"])]
        doc["entries"] = sorted(kept + doc["entries"],
                                key=lambda e: (e["path"], e["rule"]))
    baseline_path.write_text(json.dumps(doc, indent=2) + "\n",
                             encoding="utf-8")
    n_gf = sum(e["count"] for e in doc["entries"])
    print(f"jaxlint: wrote {baseline_path} grandfathering "
          f"{n_gf} violation(s) in "
          f"{len(doc['entries'])} (file, rule) group(s)")
    if n_gf < len(violations):
        print(f"jaxlint: {len(violations) - n_gf} parse-error "
              "violation(s) NOT baselined (unparseable files always "
              "fail the gate — fix them)", file=sys.stderr)
    return 0


def _prune(baseline_path: Path, baseline: dict, violations,
           entry_in_scope) -> dict:
    """Drop in-scope entries with no matching violation; ratchet
    in-scope counts down to the observed count. Justifications survive;
    entries this run's rules could not have re-observed (unlinted
    paths, filter-stripped subtree paths, --fast project rules) are
    untouchable."""
    from collections import defaultdict

    current: dict[tuple, int] = defaultdict(int)
    for v in violations:
        current[(v.path, v.rule)] += 1
    entries = []
    dropped = ratcheted = 0
    for e in baseline.get("entries", []):
        key = (e["path"], e["rule"])
        if not entry_in_scope(e["path"], e["rule"]):
            entries.append(e)
            continue
        have = current.get(key, 0)
        if have == 0:
            dropped += 1
            continue
        if have < int(e["count"]):
            e = dict(e, count=have)
            ratcheted += 1
        entries.append(e)
    doc = dict(baseline, entries=entries)
    baseline_path.write_text(json.dumps(doc, indent=2) + "\n",
                             encoding="utf-8")
    print(f"jaxlint: pruned {baseline_path}: {dropped} dead entr"
          f"{'y' if dropped == 1 else 'ies'} removed, "
          f"{ratcheted} count(s) ratcheted down", file=sys.stderr)
    return doc


def _default_baseline(root: Path) -> Path:
    """Nearest baseline at or ABOVE the checked root, so subtree
    invocations honor the committed repo baseline; falls back to
    ``<root>/jaxlint_baseline.json`` when none exists up the tree."""
    base = (root if root.is_dir() else root.parent).resolve()
    for d in (base, *base.parents):
        cand = d / BASELINE_NAME
        if cand.exists():
            return cand
    return base / BASELINE_NAME


if __name__ == "__main__":
    sys.exit(main())
