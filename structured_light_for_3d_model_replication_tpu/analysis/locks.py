"""Lock model: who acquires what, in which order, holding it over what.

Built on pass 1's :class:`~.callgraph.CallGraph`. Locks are identified
*declaratively* — ``self.X = threading.Lock()/RLock()/Condition(…)`` in a
method body, or a module-level ``X = threading.Lock()`` — and acquisition
sites are ``with <lockexpr>:`` blocks whose expression resolves to a
declared lock:

* ``self.X`` → the enclosing class's lock ``X``;
* a bare ``X`` → the module-level lock;
* ``anything.X`` → the unique class in the project declaring a lock
  attribute ``X`` (cross-object references like ``job._lock`` resolve
  because ``Job`` is the only class with a ``_lock``… when it is not
  unique the site is skipped, never guessed).

``Condition(self.Y)`` aliases to ``Y`` — acquiring the condition IS
acquiring the wrapped lock, so ``with self._not_empty:`` vs
``with self._lock:`` cannot manufacture a phantom ordering.

The model is instance-collapsed (one node per *declaration*, not per
runtime object), which is the usual static compromise: cross-instance
inversions of the same class's lock are invisible (self-edges are
dropped — re-acquisition of one instance and nested acquisition of two
instances are indistinguishable lexically), and ``.acquire()`` /
``.release()`` call pairs are not tracked (only ``with``). The runtime
sanitizer (`utils/sanitize.py`) covers the per-instance cases.
"""

from __future__ import annotations

import ast
import dataclasses

from .callgraph import CallGraph, FunctionInfo
from .rules import dotted

__all__ = ["LockModel", "Acquisition", "LockEdge", "build_lock_model"]

_LOCK_CTORS = {"Lock", "RLock", "threading.Lock", "threading.RLock"}
_CONDITION_CTORS = {"Condition", "threading.Condition"}


@dataclasses.dataclass(frozen=True)
class LockDecl:
    key: str          # "module:Class.attr" | "module:attr"
    rel_path: str
    lineno: int
    reentrant: bool   # RLock


@dataclasses.dataclass
class Acquisition:
    """One ``with <lock>:`` block."""

    key: str
    node: ast.With    # the with statement
    item: ast.expr    # the lock expression
    fn: FunctionInfo


@dataclasses.dataclass
class LockEdge:
    """``held`` was held while ``acquired`` was taken at ``node``.
    ``via`` names the callee chain for interprocedural edges ('' when
    the nested acquisition is in the same function)."""

    held: str
    acquired: str
    node: ast.AST
    fn: FunctionInfo
    via: str = ""


class LockModel:
    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.decls: dict[str, LockDecl] = {}
        self.aliases: dict[str, str] = {}       # condition attr → lock key
        # lock attr name → set of declaring keys (for unique-attr lookup).
        self._by_attr: dict[str, set] = {}
        self.acquisitions: list[Acquisition] = []
        self.edges: list[LockEdge] = []
        # qname → set of lock keys the function may acquire (direct).
        self.direct: dict[str, set] = {}
        # qname → transitive closure over the call graph.
        self.closure: dict[str, set] = {}
        # (lock key, ast.Call, FunctionInfo) for every call made while
        # lexically inside a with-lock body (innermost lock).
        self.calls_under_lock: list[tuple] = []
        # qname → [(start_lineno, end_lineno)] of with-lock statements:
        # the unlocked-shared-state rule checks each ACCESS for lexical
        # containment (per access, not per function — a function that
        # locks one access and forgets the next must still flag).
        self.lock_regions: dict[str, list] = {}

    # -- pass A: declarations ---------------------------------------------

    def _declare(self, key: str, rel_path: str, lineno: int,
                 reentrant: bool) -> None:
        if key not in self.decls:
            self.decls[key] = LockDecl(key, rel_path, lineno, reentrant)
            self._by_attr.setdefault(key.rsplit(".", 1)[-1].split(":")[-1],
                                     set()).add(key)

    def collect_declarations(self) -> None:
        for mod in self.graph.modules.values():
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call):
                    ctor = dotted(stmt.value.func) or ""
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self._module_decl(mod, t.id, ctor, stmt)
            for info in self.graph.functions.values():
                if info.module is not mod or info.cls is None:
                    continue
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.value, ast.Call):
                        ctor = dotted(node.value.func) or ""
                        for t in node.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                self._attr_decl(mod, info, t.attr, ctor,
                                                node)

    def _module_decl(self, mod, name: str, ctor: str, stmt) -> None:
        if ctor in _LOCK_CTORS:
            self._declare(f"{mod.module}:{name}", mod.rel_path,
                          stmt.lineno, ctor.endswith("RLock"))
        elif ctor in _CONDITION_CTORS:
            self._declare(f"{mod.module}:{name}", mod.rel_path,
                          stmt.lineno, True)

    def _attr_decl(self, mod, info, attr: str, ctor: str, node) -> None:
        key = f"{mod.module}:{info.cls}.{attr}"
        if ctor in _LOCK_CTORS:
            self._declare(key, mod.rel_path, node.lineno,
                          ctor.endswith("RLock"))
        elif ctor in _CONDITION_CTORS:
            # Condition(self.Y) aliases to Y; a bare Condition() is its
            # own (reentrant-ish) lock.
            arg = node.value.args[0] if node.value.args else None
            base = self._resolve_expr(info, arg) if arg is not None \
                else None
            if base is not None:
                self.aliases[key] = base
                self._by_attr.setdefault(attr, set()).add(key)
            else:
                self._declare(key, mod.rel_path, node.lineno, True)

    # -- resolution --------------------------------------------------------

    def _canon(self, key: str | None) -> str | None:
        seen = set()
        while key in self.aliases and key not in seen:
            seen.add(key)
            key = self.aliases[key]
        return key

    def _resolve_expr(self, fn: FunctionInfo,
                      expr: ast.expr) -> str | None:
        mod = fn.module.module
        if isinstance(expr, ast.Name):
            return self._canon_or_none(f"{mod}:{expr.id}")
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and fn.cls:
                key = self._canon_or_none(f"{mod}:{fn.cls}.{expr.attr}")
                if key is not None:
                    return key
            # anything.X → unique declaring class project-wide.
            cands = {self._canon(k)
                     for k in self._by_attr.get(expr.attr, ())}
            if len(cands) == 1:
                return next(iter(cands))
        return None

    def _canon_or_none(self, key: str) -> str | None:
        key = self._canon(key)
        return key if key in self.decls else None

    # -- pass B: acquisitions & edges --------------------------------------

    def collect_acquisitions(self) -> None:
        for info in self.graph.functions.values():
            self._walk_body(info, info.node.body, held=[])

    def _walk_body(self, fn: FunctionInfo, body: list, held: list) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run later, not under this lock
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                keys_here = []
                for item in stmt.items:
                    key = self._resolve_expr(fn, item.context_expr)
                    if key is not None:
                        self.acquisitions.append(
                            Acquisition(key, stmt, item.context_expr, fn))
                        for h in held + keys_here:
                            if h != key:
                                self.edges.append(
                                    LockEdge(h, key, stmt, fn))
                        keys_here.append(key)
                        self.direct.setdefault(fn.qname, set()).add(key)
                        self.lock_regions.setdefault(fn.qname, []).append(
                            (stmt.lineno,
                             getattr(stmt, "end_lineno", stmt.lineno)))
                    elif held:
                        # Non-lock context expr entered while a lock is
                        # held: `with open(path) as f:` — the call in
                        # the item IS executed under the lock.
                        for node in _walk_skip_lambdas(item.context_expr):
                            if isinstance(node, ast.Call):
                                self.calls_under_lock.append(
                                    (held[-1], node, fn, tuple(held)))
                self._walk_body(fn, stmt.body, held + keys_here)
                continue
            if held:
                # Calls in THIS statement's expressions only — nested
                # statement bodies are covered by the recursion below
                # (and lambda bodies run later, not under this lock).
                for expr in ast.iter_child_nodes(stmt):
                    if not isinstance(expr, ast.expr):
                        continue
                    for node in _walk_skip_lambdas(expr):
                        if isinstance(node, ast.Call):
                            self.calls_under_lock.append(
                                (held[-1], node, fn, tuple(held)))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    self._walk_body(fn, sub, held)
            for handler in getattr(stmt, "handlers", []):
                self._walk_body(fn, handler.body, held)

    # -- pass C: transitive closure + interprocedural edges ----------------

    def compute_closure(self) -> None:
        closure = {q: set(keys) for q, keys in self.direct.items()}
        changed = True
        while changed:
            changed = False
            for q in list(self.graph.functions):
                acc = closure.setdefault(q, set())
                for callee in self.graph.callees.get(q, ()):
                    extra = closure.get(callee, set()) - acc
                    if extra:
                        acc |= extra
                        changed = True
        self.closure = closure

    def interprocedural_edges(self) -> None:
        """held-lock → every lock a callee (transitively) may acquire,
        for calls made inside with-lock bodies."""
        for held, call, fn, _stack in self.calls_under_lock:
            name = dotted(call.func)
            if not name:
                continue
            target = self.graph._resolve(fn.module, fn, name)
            if target is None:
                continue
            for key in self.closure.get(target.qname, ()):
                if key != held:
                    self.edges.append(LockEdge(held, key, call, fn,
                                               via=target.qname))

    # -- queries -----------------------------------------------------------

    def order_graph(self) -> dict[str, set]:
        g: dict[str, set] = {}
        for e in self.edges:
            g.setdefault(e.held, set()).add(e.acquired)
        return g

    def find_cycles(self) -> list[tuple]:
        """Unordered (a, b, edge_ab, edge_ba) pairs where both orders
        exist — the minimal inconsistent-order witness. Longer cycles
        reduce to at least one inverted pair under the pairwise check
        run over the transitive order graph."""
        g = self.order_graph()
        # transitive reachability per node
        reach: dict[str, set] = {}
        for a in g:
            seen, frontier = set(), [a]
            while frontier:
                cur = frontier.pop()
                for nxt in g.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            reach[a] = seen
        out, seen_pairs = [], set()
        for e in self.edges:
            a, b = e.held, e.acquired
            if a == b or (b, a) in seen_pairs or (a, b) in seen_pairs:
                continue
            if a in reach.get(b, ()):  # b can (transitively) reach a
                back = next((x for x in self.edges
                             if x.held == b and x.acquired == a), None)
                out.append((a, b, e, back))
                seen_pairs.add((a, b))
        return out


def _walk_skip_lambdas(expr: ast.expr):
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def build_lock_model(graph: CallGraph) -> LockModel:
    model = LockModel(graph)
    model.collect_declarations()
    model.collect_acquisitions()
    model.compute_closure()
    model.interprocedural_edges()
    return model
