"""Concurrency rule family: the threaded `serve/` + `utils/` hazards.

Three project rules over the :class:`~.locks.LockModel` and the call
graph's thread entry points:

* ``lock-order`` — two locks acquired in both orders somewhere in the
  project (directly or through calls made while holding a lock). The
  static half of the deadlock story; `utils/sanitize.py` is the runtime
  half (per-instance, catches what instance-collapsing hides).
* ``blocking-under-lock`` — file/socket I/O, sleeps, ``.compile()`` /
  ``.lower()``, thread joins or event waits executed while a lock is
  held. One slow call under a hot lock serializes every thread behind
  it (the flight-recorder dump-I/O-outside-the-queue-lock rule from
  PR 5, promoted from review comment to gate).
* ``unlocked-shared-state`` — a module-level mutable (list/dict/set)
  that is MUTATED somewhere and reached from more than one thread entry
  point with at least one access outside any lock. Read-only constant
  tables (never mutated project-wide) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .callgraph import CallGraph
from .project import ProjectIndex, ProjectRule, register_project
from .rules import dotted

# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------


@register_project
class LockOrderRule(ProjectRule):
    """Inconsistent lock-acquisition order across the project lock graph.

    Heuristic: declared locks (``self.X = threading.Lock()`` /
    module-level), ``with``-statement acquisitions only, instance-
    collapsed, call edges followed transitively. An inverted pair
    (A held while B taken somewhere, B held while A taken elsewhere) is
    a potential deadlock the moment two threads hit both paths. Blind
    spots: ``.acquire()`` call pairs, per-instance ordering (see
    `utils/sanitize.py`), locks passed as arguments."""

    name = "lock-order"
    description = ("two locks acquired in inconsistent order somewhere "
                   "in the project (potential deadlock)")

    def check_project(self, index: ProjectIndex) -> Iterator:
        model = index.locks
        for a, b, edge_ab, edge_ba in model.find_cycles():
            site = edge_ab.node
            other = ""
            if edge_ba is not None:
                other = (f"; the opposite order is taken at "
                         f"{edge_ba.fn.module.rel_path}:"
                         f"{edge_ba.node.lineno}")
            via = f" (via {edge_ab.via})" if edge_ab.via else ""
            v = self.report(
                index, edge_ab.fn.module.rel_path, site,
                f"lock {b} is acquired{via} while holding "
                f"{a}, but the project also acquires them in "
                f"the opposite order{other} — two threads taking the two "
                "paths concurrently deadlock; pick one global order "
                "(see docs/JAXLINT.md)")
            if v:
                yield v


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


_BLOCKING_BARE = {"open", "sleep", "urlopen"}
_BLOCKING_DOTTED_TAILS = {
    "sleep", "urlopen", "makedirs", "compile", "lower",
    "write_text", "read_text", "read_bytes", "write_bytes",
    "recv", "accept", "connect", "sendall",
    "run", "check_call", "check_output", "Popen",
}
_BLOCKING_DOTTED_HEADS = {"time", "subprocess", "socket", "os", "shutil",
                          "urllib"}
# Dotted-call tails that block regardless of the object (the flight-
# recorder journal write, thread joins on *thread-like* attributes).
_WAIT_TAIL = "wait"
_DUMP_TAILS = {"dump", "export", "export_perfetto"}


@register_project
class BlockingUnderLockRule(ProjectRule):
    """A blocking call while holding a lock serializes every contender.

    Flags, lexically inside a ``with <lock>:`` body: ``open()``/
    ``time.sleep``/``urllib``/``socket``/``subprocess``/``os.makedirs``-
    class calls; ``.compile()``/``.lower()`` (an XLA compile is seconds);
    ``.wait(...)`` on anything OTHER than the condition being held
    (waiting on an event while holding an unrelated lock is a classic
    ordering bug); and flight-recorder/tracer ``.dump()``/``.export*()``
    journal writes. Calls made *by callees* are not followed (the
    lock-order rule follows calls; this one is about the lexically
    obvious cases where the fix is local: move the I/O out of the
    critical section)."""

    name = "blocking-under-lock"
    description = ("blocking call (I/O, sleep, compile, wait, journal "
                   "dump) while holding a lock")

    def check_project(self, index: ProjectIndex) -> Iterator:
        model = index.locks
        seen: set[tuple] = set()
        for held_key, call, fn, held_stack in model.calls_under_lock:
            msg = self._classify(call, fn, model, held_stack)
            if msg is None:
                continue
            site = (fn.module.rel_path, call.lineno, call.col_offset)
            if site in seen:
                continue
            seen.add(site)
            v = self.report(
                index, fn.module.rel_path, call,
                f"{msg} while holding lock {held_key} — every "
                "thread contending for it stalls behind this call; move "
                "it outside the critical section")
            if v:
                yield v

    def _classify(self, call: ast.Call, fn, model, held_stack):
        f = call.func
        name = dotted(f) or ""
        if isinstance(f, ast.Name) and f.id in _BLOCKING_BARE:
            return f"{f.id}() blocks"
        if isinstance(f, ast.Attribute):
            tail = f.attr
            head = name.split(".")[0] if name else ""
            if tail in _BLOCKING_DOTTED_TAILS and \
                    head in _BLOCKING_DOTTED_HEADS:
                return f"{name}() blocks"
            if tail in ("compile", "lower") and head != "re":
                return f".{tail}() compiles an XLA program (seconds)"
            if tail in _DUMP_TAILS and not name.startswith("json."):
                return f".{tail}() writes a journal/trace to disk"
            if tail == _WAIT_TAIL:
                # Waiting on the held condition itself is the Condition
                # protocol (it releases the lock); waiting on anything
                # else keeps the lock held for the wait's duration.
                waited = model._resolve_expr(fn, f.value)
                if waited is not None and waited in held_stack:
                    return None
                return f"{dotted(f.value) or '<expr>'}.wait() blocks"
        return None


# ---------------------------------------------------------------------------
# unlocked-shared-state
# ---------------------------------------------------------------------------


_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popleft",
             "clear", "update", "setdefault", "add", "discard",
             "appendleft"}


def _module_mutables(mod) -> dict[str, int]:
    """Module-level list/dict/set assignments: name → lineno."""
    out: dict[str, int] = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)) or (
                isinstance(value, ast.Call)
                and (dotted(value.func) or "").split(".")[-1] in
                ("list", "dict", "set", "defaultdict", "OrderedDict",
                 "deque", "bytearray")):
            for t in targets:
                out[t.id] = stmt.lineno
    return out


def _is_mutated(mod, name: str) -> bool:
    """Is the module global ever written/mutated (vs a constant table)?
    Assignment targets beyond the initializer, subscript/del stores,
    ``global`` rebinding, or a mutating method call."""
    initializer_seen = False
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name) and node.id == name and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            if not initializer_seen:
                initializer_seen = True
            else:
                return True
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == name and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == name and \
                node.func.attr in _MUTATORS:
            return True
        elif isinstance(node, ast.Global) and name in node.names:
            return True
    return False


@register_project
class UnlockedSharedStateRule(ProjectRule):
    """Module-level mutable state reached from >1 thread entry point with
    some access outside any lock.

    Thread entry points are pass 1's roots (``Thread(target=…)``,
    ``Thread.run``, HTTP ``do_*`` handlers); functions reachable from no
    root collapse into one implicit "main thread" entry. A global that
    is never mutated project-wide is a constant table, not state.
    Guardedness is lexical per access (inside some ``with <lock>:``) —
    the rule does not prove the SAME lock guards every access; it only
    accepts state whose every access is under some lock (the lock-order
    rule polices lock identity confusion)."""

    name = "unlocked-shared-state"
    description = ("module-level mutable reached from >1 thread entry "
                   "point with at least one unguarded access")
    path_filter = ()

    def check_project(self, index: ProjectIndex) -> Iterator:
        graph: CallGraph = index.graph
        model = index.locks
        reach = {root: graph.reachable(root)
                 for root in graph.thread_roots}
        for mod in graph.modules.values():
            mutables = _module_mutables(mod)
            if not mutables:
                continue
            hot = {n for n in mutables if _is_mutated(mod, n)}
            if not hot:
                continue
            # name → (entries, unguarded access site or None)
            uses: dict[str, tuple[set, ast.AST | None]] = {}
            for info in graph.functions.values():
                if info.module is not mod:
                    continue
                local = _local_bindings(info.node)
                regions = model.lock_regions.get(info.qname, ())
                entries = {r for r, seen in reach.items()
                           if info.qname in seen} or {"<main>"}
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Name) and node.id in hot \
                            and node.id not in local:
                        ents, site = uses.get(node.id, (set(), None))
                        ents = ents | entries
                        # Guardedness is per ACCESS: lexically inside
                        # some with-lock region of this function.
                        guarded = any(s <= node.lineno <= e
                                      for s, e in regions)
                        if not guarded and site is None:
                            site = node
                        uses[node.id] = (ents, site)
            for name, (entries, site) in sorted(uses.items()):
                if len(entries) < 2 or site is None:
                    continue
                roots = ", ".join(sorted(e.split(":")[-1]
                                         for e in entries))
                v = self.report(
                    index, mod.rel_path, site,
                    f"module-level mutable {name!r} (defined at line "
                    f"{mutables[name]}) is reached from {len(entries)} "
                    f"thread entry points ({roots}) and this access is "
                    "outside any lock — guard every access with one "
                    "lock or make the structure immutable")
                if v:
                    yield v


def _local_bindings(fn) -> set:
    names = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                             + fn.args.kwonlyargs)}
    if fn.args.vararg:
        names.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        names.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
    return names
