"""jaxlint built-in rules — the six hazard classes this repo has hit.

Every rule is lexical (pure AST, no type inference), so each one states
its exact heuristic and the known blind spots.  False positives are the
suppression comment's job (`# jaxlint: disable=RULE` with a
justification); systemic exceptions belong in the rule's path scoping,
not in per-line noise.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import FileContext, Rule, Violation, register

# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """'jax.random.uniform' for a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def jit_decorator_call(dec: ast.AST) -> ast.Call | None:
    """The ast.Call carrying jit kwargs for ``@jax.jit(...)`` or
    ``@functools.partial(jax.jit, ...)`` decorators, else None."""
    if isinstance(dec, ast.Call):
        f = dotted(dec.func)
        if f in _JIT_NAMES:
            return dec
        if f in ("functools.partial", "partial") and dec.args \
                and dotted(dec.args[0]) in _JIT_NAMES:
            return dec
    return None


def is_jitted(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        if dotted(dec) in _JIT_NAMES or jit_decorator_call(dec) is not None:
            return True
    return False


def walk_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------------------
# Rule 1: unguarded pallas imports
# ---------------------------------------------------------------------------

_PALLAS_PREFIX = "jax.experimental.pallas"


@register
class PallasImportRule(Rule):
    """Pallas must stay an optional dependency of every dispatch path.

    Round-5 regression: ``ops/poisson_sparse.py`` imported
    ``poisson_pallas`` (→ ``jax.experimental.pallas.tpu``) inside the CG
    hot path even when ``use_pallas`` resolved False, making CPU-only
    deployments depend on pallas importability.  The repo convention:
    ``*_pallas.py`` kernel modules are the only files that import pallas
    at module scope; every other file imports a kernel module lazily,
    inside an ``if``-gated (backend check) or ``try``-guarded branch.
    Tests are exempt (they pin kernel parity in interpret mode and may
    import kernels directly), as are ``scripts/`` (operator-run TPU
    probes/benches that only ever execute on TPU hosts).
    """

    name = "pallas-import"
    description = ("unguarded import of jax.experimental.pallas or a "
                   "*_pallas kernel module outside a gated branch")
    exempt_parts = ("tests", "scripts")
    exempt_suffixes = ("_pallas.py",)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        yield from self._visit(ctx, ctx.tree, guarded=False)

    def _visit(self, ctx, node, guarded):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                for target in self._pallas_targets(child):
                    if not guarded:
                        v = self.report(
                            ctx, child,
                            f"unguarded import of {target!r}: import pallas"
                            " kernel modules lazily inside a TPU-gated `if`"
                            " (e.g. `if tpu_backend(): from . import"
                            " x_pallas`) or a try/except so non-TPU"
                            " deployments never touch pallas"
                            " (*_pallas.py kernel modules are exempt)")
                        if v:
                            yield v
                continue
            # An `if`/`try` anywhere up the chain counts as the gate; a
            # function body RESETS the flag (its statements execute at
            # call time, not under the enclosing branch).
            if isinstance(child, (ast.If, ast.Try)):
                child_guarded = True
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                child_guarded = False
            else:
                child_guarded = guarded
            yield from self._visit(ctx, child, child_guarded)

    @staticmethod
    def _pallas_targets(node):
        def is_pallas_name(modname: str) -> bool:
            return (modname == _PALLAS_PREFIX
                    or modname.startswith(_PALLAS_PREFIX + ".")
                    or modname.split(".")[-1].endswith("_pallas"))

        hits = []
        if isinstance(node, ast.Import):
            for alias in node.names:
                if is_pallas_name(alias.name):
                    hits.append(alias.name)
        else:
            mod = node.module or ""
            if mod and is_pallas_name(mod):
                hits.append("." * node.level + mod)
            else:
                for alias in node.names:
                    if alias.name == "pallas" and mod == "jax.experimental":
                        hits.append(_PALLAS_PREFIX)
                    elif alias.name.endswith("_pallas"):
                        prefix = "." * node.level + (mod + "." if mod else "")
                        hits.append(prefix + alias.name)
        return hits


# ---------------------------------------------------------------------------
# Rule 2: host syncs inside jitted functions
# ---------------------------------------------------------------------------


@register
class HostSyncInJitRule(Rule):
    """Host-sync calls inside ``@jax.jit`` bodies either crash at trace
    time (``.item()`` / ``float()`` on a tracer raise ConcretizationError)
    or, when they slip through on a concrete leaf, silently serialize
    dispatch — the one-stray-host-sync stall class from the Gaussian-SDF
    SLAM pipelining analysis.  Heuristics: ``float()``/``int()`` are only
    flagged on computed arguments (calls / subscripts / attributes) —
    bare names are usually static python scalars, which are legal; numpy
    conversions are only flagged on non-literal arguments (converting a
    literal list builds a trace-time constant, which is fine).
    """

    name = "host-sync-in-jit"
    description = ("host-sync call (.item(), float()/int() on arrays, "
                   "np.asarray, block_until_ready) inside a jitted "
                   "function")

    _NP_MODS = ("np", "numpy", "onp")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        seen: set[tuple[int, int]] = set()
        for fn in walk_functions(ctx.tree):
            if not is_jitted(fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                msg = self._classify(node)
                if msg:
                    seen.add(key)
                    v = self.report(ctx, node, msg + f" inside jitted "
                                    f"function {fn.name}()")
                    if v:
                        yield v

    def _classify(self, node: ast.Call) -> str | None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                return (".item() forces a device→host transfer (and raises"
                        " on tracers)")
            if f.attr == "block_until_ready":
                return "block_until_ready() stalls dispatch"
            base = dotted(f.value)
            if base in self._NP_MODS and f.attr in ("asarray", "array"):
                arg = node.args[0] if node.args else None
                if arg is not None and not isinstance(
                        arg, (ast.Constant, ast.List, ast.Tuple,
                              ast.ListComp)):
                    return (f"{base}.{f.attr}() of a (possibly traced)"
                            " array pulls it to host — use jnp, or hoist"
                            " the conversion out of the jitted body")
        name = dotted(f)
        if name in ("jax.block_until_ready", "jax.device_get"):
            return f"{name}() stalls dispatch"
        if isinstance(f, ast.Name) and f.id in ("float", "int") \
                and len(node.args) == 1 and not node.keywords:
            if isinstance(node.args[0], (ast.Call, ast.Subscript,
                                         ast.Attribute)):
                return (f"{f.id}() on a computed value concretizes it"
                        " (raises on tracers; host-syncs on device"
                        " leaves)")
        return None


# ---------------------------------------------------------------------------
# Rule 3: implicit dtype in ops/
# ---------------------------------------------------------------------------


@register
class ImplicitDtypeRule(Rule):
    """``jnp.asarray``/``jnp.array`` without an explicit dtype takes the
    weak-type / x64-flag dependent default, and dtype drift across the
    ops layer is how mixed-precision bugs enter kernels (the fpfh_brick
    ring regression).  Scoped to ``ops/`` — the numerical kernel layer
    where every array's dtype is part of the contract."""

    name = "implicit-dtype"
    description = ("jnp.asarray/jnp.array without an explicit dtype in "
                   "ops/ (weak-type / x64 drift)")
    path_filter = ("ops/",)

    _FUNCS = {"jnp.asarray", "jnp.array",
              "jax.numpy.asarray", "jax.numpy.array"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name not in self._FUNCS:
                continue
            has_dtype = (len(node.args) >= 2
                         or any(k.arg == "dtype" for k in node.keywords))
            if not has_dtype:
                v = self.report(
                    ctx, node,
                    f"{name}() without an explicit dtype in ops/ — the "
                    "result dtype then depends on weak-type promotion and "
                    "the x64 flag; pass the intended dtype")
                if v:
                    yield v


# ---------------------------------------------------------------------------
# Rule 4: static_argnames hygiene
# ---------------------------------------------------------------------------


@register
class StaticArgnamesRule(Rule):
    """``static_argnames`` entries that don't name a parameter are
    silently ignored by jax (the argument traces instead — recompile per
    call or tracer leak); static parameters with unhashable defaults
    raise only on the first defaulted call."""

    name = "static-argnames"
    description = ("static_argnames naming a missing parameter, or a "
                   "static parameter with an unhashable default")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for fn in walk_functions(ctx.tree):
            for dec in fn.decorator_list:
                call = jit_decorator_call(dec)
                if call is None:
                    continue
                kw = next((k for k in call.keywords
                           if k.arg == "static_argnames"), None)
                if kw is None:
                    continue
                names = self._literal_names(kw.value)
                if names is None:
                    continue        # dynamic expression — cannot check
                params = [a.arg for a in (fn.args.posonlyargs + fn.args.args
                                          + fn.args.kwonlyargs)]
                defaults = self._default_map(fn)
                for name in names:
                    if name not in params:
                        v = self.report(
                            ctx, dec,
                            f"static_argnames entry {name!r} is not a "
                            f"parameter of {fn.name}() — jax ignores it "
                            "and the argument traces (recompile/tracer "
                            "hazard)")
                        if v:
                            yield v
                        continue
                    default = defaults.get(name)
                    if default is not None \
                            and self._unhashable(default):
                        v = self.report(
                            ctx, dec,
                            f"static parameter {name!r} of {fn.name}() has "
                            "an unhashable default (static args are dict "
                            "keys in the jit cache) — use a hashable "
                            "default (tuple/None) instead")
                        if v:
                            yield v

    @staticmethod
    def _literal_names(node) -> list[str] | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for elt in node.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    out.append(elt.value)
                else:
                    return None
            return out
        return None

    @staticmethod
    def _default_map(fn) -> dict[str, ast.expr]:
        pos = fn.args.posonlyargs + fn.args.args
        out: dict[str, ast.expr] = {}
        for arg, default in zip(pos[len(pos) - len(fn.args.defaults):],
                                fn.args.defaults):
            out[arg.arg] = default
        for arg, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if default is not None:
                out[arg.arg] = default
        return out

    @staticmethod
    def _unhashable(node: ast.expr) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            return name in ("list", "dict", "set", "bytearray",
                            "jnp.array", "jnp.asarray", "np.array",
                            "np.asarray", "jnp.zeros", "jnp.ones",
                            "np.zeros", "np.ones")
        return False


# ---------------------------------------------------------------------------
# Rule 5: jitted functions closing over module-level mutables
# ---------------------------------------------------------------------------


@register
class MutableGlobalRule(Rule):
    """A jitted function reading a module-level list/dict/set bakes the
    traced value into the compiled program: later mutations are silently
    invisible, and writing traced values INTO the global leaks tracers
    across traces.  Tuples and scalars are fine (immutable); so is
    reading mutable globals from untraced helpers."""

    name = "mutable-global"
    description = ("jitted function reads a module-level mutable "
                   "(list/dict/set) global")

    _MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "defaultdict",
                      "collections.defaultdict", "OrderedDict",
                      "collections.OrderedDict"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        mutable: dict[str, int] = {}
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            if self._is_mutable(value):
                for t in targets:
                    mutable[t.id] = stmt.lineno
        if not mutable:
            return
        for fn in walk_functions(ctx.tree):
            if not is_jitted(fn):
                continue
            local = self._local_bindings(fn)
            reported: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in mutable \
                        and node.id not in local \
                        and node.id not in reported:
                    reported.add(node.id)
                    v = self.report(
                        ctx, node,
                        f"jitted function {fn.name}() reads module-level "
                        f"mutable global {node.id!r} (defined at line "
                        f"{mutable[node.id]}) — its value is baked in at "
                        "trace time and later mutations are invisible "
                        "(tracer-leak risk if written); pass it as an "
                        "argument or freeze it to a tuple")
                    if v:
                        yield v

    def _is_mutable(self, node: ast.expr) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            return dotted(node.func) in self._MUTABLE_CALLS
        return False

    @staticmethod
    def _local_bindings(fn) -> set[str]:
        names = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                 + fn.args.kwonlyargs)}
        if fn.args.vararg:
            names.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            names.add(fn.args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                names.add(node.id)
        return names


# ---------------------------------------------------------------------------
# Rule 6: silent broad excepts
# ---------------------------------------------------------------------------


@register
class SilentExceptRule(Rule):
    """``except Exception: pass`` swallows EVERYTHING — including the
    tracer leaks, dtype errors and transport failures the rest of this
    linter exists to surface — and leaves no log line to debug from.  The
    hazard class behind the turntable serial-probe fix (PR 3): a broad
    handler whose body does literally nothing.  Heuristic: the handler
    catches a broad type (bare ``except``, ``Exception``/``BaseException``,
    alone or in a tuple) AND its body is only ``pass``/``continue``
    (docstring-style constants ignored).  Handlers that log, return a
    fallback, re-raise or set state are fine — the rule targets silence,
    not breadth."""

    name = "silent-except"
    description = ("except Exception/bare except whose body only "
                   "pass/continues — failures vanish with no log or "
                   "fallback")

    _BROAD = {"Exception", "BaseException"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if not self._is_silent(node.body):
                continue
            v = self.report(
                ctx, node,
                "broad except with a pass/continue-only body silently "
                "swallows every failure — log it, narrow the exception "
                "type, or return an explicit fallback")
            if v:
                yield v

    def _is_broad(self, type_node) -> bool:
        if type_node is None:           # bare except:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        name = dotted(type_node)
        return name is not None and name.split(".")[-1] in self._BROAD

    @staticmethod
    def _is_silent(body) -> bool:
        real = [s for s in body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))]
        return bool(real) and all(
            isinstance(s, (ast.Pass, ast.Continue)) for s in real)


# ---------------------------------------------------------------------------
# Rule 7: PRNG key reuse
# ---------------------------------------------------------------------------


@register
class KeyReuseRule(Rule):
    """A PRNG key consumed by two ``jax.random`` sampling calls in the
    same scope without an intervening ``split`` yields IDENTICAL random
    streams — RANSAC hypothesis batches that silently sample the same
    triplets.  Lexical scope walk: reassignment (including from
    ``split``) resets a key; passing a key to a non-``jax.random`` call
    does not count (the callee may split).  Blind spots: reuse across
    exclusive ``if`` branches false-positives, loop-carried reuse
    false-negatives."""

    name = "key-reuse"
    description = ("jax.random key consumed by two sampling calls with "
                   "no split in between")

    _SAFE = {"split", "fold_in", "PRNGKey", "key", "key_data",
             "wrap_key_data", "clone"}
    _RANDOM_MODS = ("jax.random", "random", "jrandom", "jr")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        out: list[Violation] = []
        self._run_body(ctx, ctx.tree.body, {}, out)
        for fn in walk_functions(ctx.tree):
            self._run_body(ctx, fn.body, {}, out)
        yield from out

    # -- scope interpreter --------------------------------------------------

    def _run_body(self, ctx, stmts, counts, out):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue    # separate scope, visited on its own
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                if stmt.value is not None:
                    self._consume(ctx, stmt.value, counts, out)
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                self._bind(targets, stmt.value, counts)
                continue
            # Generic statement: consume its immediate expressions, reset
            # any Name stores (for-targets, with-aliases), then recurse
            # into nested statement bodies.
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._consume(ctx, child, counts, out)
                elif isinstance(child, ast.withitem):
                    self._consume(ctx, child.context_expr, counts, out)
                    if child.optional_vars is not None:
                        self._bind([child.optional_vars], None, counts)
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._bind([stmt.target], None, counts)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    self._run_body(ctx, sub, counts, out)
            for handler in getattr(stmt, "handlers", []):
                self._run_body(ctx, handler.body, counts, out)

    def _bind(self, targets, value, counts):
        names = []
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(e.id for e in t.elts
                             if isinstance(e, ast.Name))
        for name in names:
            counts.pop(name, None)      # any rebind resets the key state
        if value is not None and self._makes_key(value):
            for name in names:
                counts[name] = 0

    def _makes_key(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Subscript):
            return self._makes_key(node.value)
        if not isinstance(node, ast.Call):
            return False
        name = dotted(node.func)
        if not name or "." not in name:
            return False
        mod, _, fn = name.rpartition(".")
        return mod in self._RANDOM_MODS and fn in self._SAFE

    def _consume(self, ctx, expr, counts, out):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if not name or "." not in name:
                continue
            mod, _, fn = name.rpartition(".")
            if mod not in self._RANDOM_MODS or fn in self._SAFE:
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            for arg in args:
                if isinstance(arg, ast.Name) and arg.id in counts:
                    counts[arg.id] += 1
                    if counts[arg.id] >= 2:
                        v = self.report(
                            ctx, node,
                            f"PRNG key {arg.id!r} is consumed by "
                            f"jax.random.{fn}() after an earlier sampling "
                            "call in the same scope with no split in "
                            "between — both calls draw IDENTICAL "
                            "randomness; jax.random.split() the key first")
                        if v:
                            out.append(v)
