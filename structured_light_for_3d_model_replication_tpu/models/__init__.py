"""Pipelines composing the ops: scan pipeline, oracle backend, synthetic scanner."""
