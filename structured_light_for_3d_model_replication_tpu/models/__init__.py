"""Pipelines composing the ops: scan pipeline, oracle backend, synthetic scanner.

`pipeline` is exposed lazily: the numpy_cv2 oracle backend must stay importable
without pulling in jax (which can block at interpreter TPU-claim time on this
image — see .claude/skills/verify/SKILL.md).
"""

import importlib

from . import oracle, synthetic  # noqa: F401


def __getattr__(name):
    if name in ("pipeline", "meshing", "merge", "scan360"):
        # import_module (not `from . import`) so an in-progress circular
        # import resolves from sys.modules instead of recursing into this
        # __getattr__ via the package attribute lookup.
        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
