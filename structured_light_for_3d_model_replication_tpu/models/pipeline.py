"""The flagship single-scan pipeline: stack → decode → triangulate → colors.

This is the compute core of the reference's `SLSystem.generate_cloud`
(`server/sl_system.py:483-653`) as ONE jittable function: a 46×H×W uint8
capture stack in, dense (H·W, 3) points + colors + validity out. The reference
runs it as ~30 sequential NumPy/imread passes; here the whole thing is a single
XLA program, so it fuses, stays in HBM, and vmaps over batches of scans.

Static-shape contract: outputs are dense over all H·W pixels with a `valid`
mask, never gathered — required for jit, vmap and sharding.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import DecodeConfig, TriangulationConfig
from ..ops import decode as decode_ops
from ..ops import triangulate as tri_ops


class CloudResult(NamedTuple):
    points: jnp.ndarray   # (H*W, 3) float32, zeros where invalid
    colors: jnp.ndarray   # (H*W, 3) uint8 from the white reference frame
    valid: jnp.ndarray    # (H*W,) bool
    col_map: jnp.ndarray  # (H, W) int32 decoded projector column
    row_map: jnp.ndarray  # (H, W) int32 decoded projector row


@functools.partial(
    jax.jit,
    static_argnums=(2, 3),
    static_argnames=("decode_cfg", "tri_cfg", "downsample"),
)
def reconstruct(
    stack: jnp.ndarray,
    calib: tri_ops.Calibration,
    col_bits: int,
    row_bits: int,
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    downsample: int = 1,
) -> CloudResult:
    """Full scan→cloud forward step (the reference's decode+triangulate core,
    `server/sl_system.py:508-653`, as one fused XLA program)."""
    col_map, row_map, mask = decode_ops.decode_stack(
        stack, col_bits, row_bits, cfg=decode_cfg, downsample=downsample
    )
    points, valid = tri_ops.triangulate(col_map, row_map, mask, calib, cfg=tri_cfg)
    colors = tri_ops.colors_from_white(stack[0])
    return CloudResult(points, colors, valid, col_map, row_map)


def to_point_cloud(res: CloudResult):
    """Compact a (single-scan) CloudResult to a host PointCloud — the
    file-boundary step the reference does inline in its PLY writer
    (`server/sl_system.py:671-691`)."""
    import numpy as np

    from ..io.ply import PointCloud

    keep = np.asarray(res.valid)
    return PointCloud(points=np.asarray(res.points)[keep],
                      colors=np.asarray(res.colors)[keep])


@functools.lru_cache(maxsize=None)
def reconstruct_batch_fn(col_bits: int, row_bits: int,
                         decode_cfg: DecodeConfig = DecodeConfig(),
                         tri_cfg: TriangulationConfig = TriangulationConfig(),
                         downsample: int = 1):
    """Jitted vmapped batch variant: (B, F, H, W) stacks + shared calib →
    CloudResult batched on the leading axis. Memoized on the (hashable,
    frozen) config args so repeat calls hit jit's compile cache instead of
    re-tracing a fresh closure.

    The stack argument is DONATED: at 1080p a B=8 batch is ~760 MB of
    uint8 that nothing reads after decode, and releasing it during
    execution is the per-chip memory headroom the multi-chip plan needs
    (sharding-readiness, docs/JAXLINT.md). Callers must stage a fresh
    device buffer per call — every in-repo caller already does (serve
    workers re-stage each batch, scan360 stages per chunk, the sharded
    path device_puts per call). The uint8 input cannot alias the float32
    outputs, so XLA notes the donation as "not usable" for aliasing at
    compile time; the early release still stands. ``in_shardings=None``
    leaves placement to propagation (committed shardings pass through —
    the `parallel/` path relies on that) while making the annotation
    explicit for the multi-chip flip."""

    def single(stack, calib):
        return reconstruct(stack, calib, col_bits, row_bits,
                           decode_cfg=decode_cfg, tri_cfg=tri_cfg,
                           downsample=downsample)

    return jax.jit(jax.vmap(single, in_axes=(0, None)),
                   donate_argnums=(0,),
                   in_shardings=None, out_shardings=None)
