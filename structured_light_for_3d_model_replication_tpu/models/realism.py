"""Sensor/optics degradation — photoreal capture simulation.

The reference repo bundles no sample captures, and this build environment
has no camera, so end-to-end validation against *bit-for-bit real*
photographs is impossible here (ROADMAP/VERDICT r1). What CAN be tested is
everything that separates a rendered pattern stack from a phone
photograph of one: this module applies the physically-motivated chain a
real capture goes through, in camera order —

1. **defocus / lens blur** — Gaussian PSF;
2. **radial + tangential lens distortion** (Brown–Conrady k1, k2, p1,
   p2) — inverse-map warp, the same model ``cv2.undistortPoints``
   inverts;
3. **vignetting** — cos⁴ illumination falloff about the principal point;
4. **exposure drift** — per-frame gain jitter (phone AE locked but the
   projector lamp and ambient light breathe);
5. **sensor noise** — signal-dependent shot noise + Gaussian read noise
   on the linear signal;
6. **gamma** — sRGB-style transfer (the phone writes display-referred
   JPEGs);
7. **JPEG round trip** — 8×8 DCT quantization artifacts at a configurable
   quality (the reference client uploads JPEG, `frotend/App.tsx:246`).

The degraded stacks feed the decode/mask/triangulate chain in
tests/test_realistic_capture.py: adaptive AND fixed thresholds
(`server/sl_system.py:526-535` vs `multi_point_cloud_process.py:36-38`)
must both survive this chain with quantified masks and reconstruction
error — the closest available stand-in for a captured stack, and exactly
the degradations that broke naive decoders on real rigs.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class SensorParams:
    """Defaults model a mid-range phone camera at ISO ~400."""

    defocus_sigma_px: float = 0.8
    k1: float = 0.06           # radial distortion (barrel)
    k2: float = -0.015
    p1: float = 0.0008         # tangential
    p2: float = -0.0005
    vignette_strength: float = 0.35   # 0 = none, 1 = full cos⁴
    exposure_jitter: float = 0.02     # per-frame gain stddev
    shot_noise: float = 0.02          # stddev at full scale, scales √signal
    read_noise: float = 2.0           # DN at 8 bit
    gamma: float = 2.2
    jpeg_quality: int = 85


def _gaussian_blur(img: np.ndarray, sigma: float) -> np.ndarray:
    if sigma <= 0:
        return img
    r = max(1, int(3 * sigma))
    x = np.arange(-r, r + 1, dtype=np.float64)
    kern = np.exp(-0.5 * (x / sigma) ** 2)
    kern /= kern.sum()
    pad = np.pad(img, ((r, r), (0, 0)), mode="edge")
    img = np.apply_along_axis(
        lambda c: np.convolve(c, kern, mode="valid"), 0, pad)
    pad = np.pad(img, ((0, 0), (r, r)), mode="edge")
    return np.apply_along_axis(
        lambda c: np.convolve(c, kern, mode="valid"), 1, pad)


def _distort_warp(h: int, w: int, cam_K: np.ndarray, p: SensorParams):
    """Sampling map: for each DISTORTED output pixel, where to sample the
    ideal image (forward Brown–Conrady applied to the sample position)."""
    fx, fy = cam_K[0, 0], cam_K[1, 1]
    cx, cy = cam_K[0, 2], cam_K[1, 2]
    v, u = np.mgrid[0:h, 0:w].astype(np.float64)
    x = (u - cx) / fx
    y = (v - cy) / fy
    r2 = x * x + y * y
    radial = 1 + p.k1 * r2 + p.k2 * r2 * r2
    xd = x * radial + 2 * p.p1 * x * y + p.p2 * (r2 + 2 * x * x)
    yd = y * radial + p.p1 * (r2 + 2 * y * y) + 2 * p.p2 * x * y
    return (xd * fx + cx).astype(np.float32), (yd * fy + cy).astype(
        np.float32)


def _bilinear(img: np.ndarray, mu: np.ndarray, mv: np.ndarray) -> np.ndarray:
    h, w = img.shape
    u0 = np.clip(np.floor(mu).astype(np.int64), 0, w - 2)
    v0 = np.clip(np.floor(mv).astype(np.int64), 0, h - 2)
    fu = np.clip(mu - u0, 0.0, 1.0)
    fv = np.clip(mv - v0, 0.0, 1.0)
    a = img[v0, u0] * (1 - fu) + img[v0, u0 + 1] * fu
    b = img[v0 + 1, u0] * (1 - fu) + img[v0 + 1, u0 + 1] * fu
    return a * (1 - fv) + b * fv


def _jpeg_roundtrip(img_u8: np.ndarray, quality: int) -> np.ndarray:
    try:
        import cv2

        ok, buf = cv2.imencode(".jpg", img_u8,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        if ok:
            return cv2.imdecode(buf, cv2.IMREAD_GRAYSCALE)
    except Exception as exc:
        # cv2-free images skip the JPEG stage; the rest of the degradation
        # chain still applies.
        log.debug("jpeg roundtrip unavailable (%s); frame passed through",
                  exc)
    return img_u8


def degrade_frame(frame: np.ndarray, cam_K: np.ndarray,
                  params: SensorParams = SensorParams(),
                  rng: np.random.Generator | None = None) -> np.ndarray:
    """One ideal (H, W) uint8 render → photoreal capture (H, W) uint8."""
    if rng is None:
        rng = np.random.default_rng(0)
    p = params
    h, w = frame.shape
    img = frame.astype(np.float64) / 255.0

    img = _gaussian_blur(img, p.defocus_sigma_px)
    mu, mv = _distort_warp(h, w, cam_K, p)
    img = _bilinear(img, mu, mv)

    fx = cam_K[0, 0]
    v, u = np.mgrid[0:h, 0:w]
    r2 = ((u - cam_K[0, 2]) ** 2 + (v - cam_K[1, 2]) ** 2) / (fx * fx)
    cos4 = 1.0 / (1.0 + r2) ** 2
    img = img * (1 - p.vignette_strength + p.vignette_strength * cos4)

    img = img * (1.0 + rng.normal(0.0, p.exposure_jitter))
    noise = rng.normal(0.0, 1.0, img.shape) * (
        p.shot_noise * np.sqrt(np.clip(img, 0.0, 1.0))) \
        + rng.normal(0.0, p.read_noise / 255.0, img.shape)
    img = np.clip(img + noise, 0.0, 1.0)

    img = img ** (1.0 / p.gamma)
    img_u8 = np.round(img * 255.0).astype(np.uint8)
    return _jpeg_roundtrip(img_u8, p.jpeg_quality)


def degrade_stack(stack: np.ndarray, cam_K: np.ndarray,
                  params: SensorParams = SensorParams(),
                  seed: int = 0) -> np.ndarray:
    """(F, H, W) uint8 ideal stack → photoreal stack, per-frame noise."""
    rng = np.random.default_rng(seed)
    return np.stack([degrade_frame(f, cam_K, params, rng) for f in stack])
