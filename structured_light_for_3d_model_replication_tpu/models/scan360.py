"""The flagship end-to-end workflow: 360° capture stacks → merged cloud.

This is the whole compute path of the reference's auto-scan post-processing
run as one device-resident program chain: the GUI's per-stop
`SLSystem.generate_cloud` (`server/sl_system.py:483-653`) followed by
`ProcessingLogic.merge_pro_360` (`server/processing.py:115-181`) — but where
the reference round-trips every stage through image files and ASCII PLYs, this
pipeline keeps everything in HBM from the raw uint8 stacks to the final merged
cloud:

1. batched decode+triangulate of all N stops (one vmapped XLA program);
2. per-stop fixed-size random subsample (static-shape stand-in for the
   reference's pre-ICP voxel downsample, `server/processing.py:83`);
3. ring registration — FPFH + feature RANSAC + point-to-plane ICP per edge
   (`server/processing.py:146-156`), optionally with the loop-closure edge and
   pose-graph LM of the legacy merge (`Old/360Merge.py:43-84`);
4. every FULL-resolution cloud transformed by its pose and merged through the
   final voxel → SOR → normals cleanup (`server/processing.py:171-181`).

The only host↔device traffic is the input stacks in and the final compacted
cloud out. This file is the north-star benchmark target (BASELINE.md: 24
stops × 46 frames @1080p in < 2 s).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..config import DecodeConfig, TriangulationConfig
from ..io import ply as ply_io
from ..ops import pointcloud, posegraph, registration
from ..ops.triangulate import Calibration
from ..utils.log import get_logger
from . import merge as merge_mod
from . import pipeline as pipeline_mod

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Scan360Params:
    """End-to-end knobs. ``merge`` carries the registration/cleanup settings
    (reference GUI defaults); ``view_cap`` bounds each stop's contribution to
    the final full-resolution merge (slots, post voxel-downsample)."""

    merge: merge_mod.MergeParams = merge_mod.MergeParams()
    method: str = "sequential"  # or "posegraph"
    view_cap: int = 131_072


def scan_stacks_to_cloud(
    stacks: jnp.ndarray,
    calib: Calibration,
    col_bits: int,
    row_bits: int,
    params: Scan360Params = Scan360Params(),
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    key=None,
):
    """(N, F, H, W) uint8 capture stacks → (merged PointCloud, poses (N,4,4)).

    Stops are assumed in turntable order (stop i+1 photographed after one
    rotation step), which is what the ring registration chain relies on —
    same assumption as the reference's numeric filename sort
    (`Old/new360Merge.py:7-20`).
    """
    if params.method not in ("sequential", "posegraph"):
        raise ValueError(f"method must be 'sequential' or 'posegraph', "
                         f"got {params.method!r}")
    if key is None:
        key = jax.random.PRNGKey(0)
    n = stacks.shape[0]
    mp = params.merge

    # 1. Decode + triangulate every stop in one vmapped program.
    recon = pipeline_mod.reconstruct_batch_fn(col_bits, row_bits, decode_cfg,
                                              tri_cfg)
    res = recon(stacks, calib)

    # 2. Fixed-size registration view of each stop (device-side). Clamped to
    # the slot count: a small camera may have fewer pixels than the cap
    # (top_k needs m ≤ n).
    m_reg = min(merge_mod._round_up(mp.max_points), res.points.shape[1])
    k_sub, k_reg = jax.random.split(key)
    sub_keys = jax.random.split(k_sub, n)
    reg_pts, _, reg_val = jax.vmap(
        lambda p, v, k: pointcloud.random_subsample(p, m_reg, valid=v, key=k)
    )(res.points, res.valid, sub_keys)

    # 3. Ring registration → per-stop poses.
    loop = params.method == "posegraph" and mp.loop_closure
    seq_T, seq_info, loop_T, loop_info, _ = merge_mod.register_sequence(
        reg_pts, reg_val, mp, loop_closure=loop, key=k_reg)
    if params.method == "posegraph":
        graph = posegraph.build_360_graph(seq_T, seq_info, loop_T, loop_info)
        poses = posegraph.optimize(graph, iterations=mp.posegraph_iterations)
    else:
        poses = posegraph.chain_poses(seq_T)

    # 4. Merge the FULL-resolution clouds under the poses. Each stop is first
    # reduced per-view (voxel downsample, then a uniform random compaction
    # into view_cap static slots — unbiased even when more than view_cap
    # cells survive; a prefix slice would chop off one spatial side, since
    # cells come out in lexicographic order), then the final global cleanup
    # chain runs on the concatenation.
    view_cap = merge_mod._round_up(min(params.view_cap, res.points.shape[1]))

    def reduce_view(pose, pts, colors, valid, k):
        moved = registration.transform_points(pose, pts)
        dpts, dcol, dvalid, _ = pointcloud.voxel_downsample(
            moved, mp.voxel_size, valid=valid,
            attrs=colors.astype(jnp.float32), with_attrs=True)
        return pointcloud.random_subsample(dpts, view_cap, valid=dvalid,
                                           attrs=dcol, key=k)

    view_keys = jax.random.split(jax.random.fold_in(key, 1), n)
    vpts, vcol, vval = jax.vmap(reduce_view)(
        jnp.asarray(poses, jnp.float32), res.points, res.colors, res.valid,
        view_keys)
    merged = merge_mod._finalize(
        vpts.reshape(-1, 3), vcol.reshape(-1, 3), vval.reshape(-1), mp,
        has_colors=True)
    log.info("scan_stacks_to_cloud: %d stops → %d points (%s)", n,
             len(merged), params.method)
    return merged, np.asarray(poses)


def scan_folders_to_cloud(
    stop_dirs,
    calib_path: str,
    output_path: str | None = None,
    col_bits: int | None = None,
    row_bits: int | None = None,
    params: Scan360Params = Scan360Params(),
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    key=None,
):
    """File-level wrapper: a list of per-stop frame folders + a `.mat`
    calibration → merged cloud (optionally written to ``output_path``).

    Mirrors the reference's batch flow (`multi_point_cloud_process.py`
    followed by the merge tab) with the file round-trips removed.
    """
    import math

    from ..io import images as img_io
    from ..io import matcal

    stacks = np.stack([img_io.load_stack(d) for d in stop_dirs])
    _, _, h, w = stacks.shape
    cal = matcal.load_calibration_mat(calib_path, h, w)
    # Bit counts follow the projector extent, `ceil(log2(dim))` — exactly how
    # the reference sizes its Gray sequences (`server/sl_system.py:52-54`).
    if col_bits is None:
        col_bits = math.ceil(math.log2(cal.plane_cols.shape[0]))
    if row_bits is None:
        row_bits = math.ceil(math.log2(cal.plane_rows.shape[0]))
    expect = 2 + 2 * (col_bits + row_bits)
    if stacks.shape[1] != expect:
        raise ValueError(
            f"stack has {stacks.shape[1]} frames but {col_bits}+{row_bits} "
            f"bits imply {expect} (white, black, then pattern/inverse pairs)")
    merged, poses = scan_stacks_to_cloud(
        jnp.asarray(stacks), cal, col_bits, row_bits,
        params=params, decode_cfg=decode_cfg, tri_cfg=tri_cfg, key=key)
    if output_path is not None:
        ply_io.write_ply(output_path, merged)
    return merged, poses
