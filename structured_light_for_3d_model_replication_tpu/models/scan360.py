"""The flagship end-to-end workflow: 360° capture stacks → merged cloud.

This is the whole compute path of the reference's auto-scan post-processing
run as one device-resident program chain: the GUI's per-stop
`SLSystem.generate_cloud` (`server/sl_system.py:483-653`) followed by
`ProcessingLogic.merge_pro_360` (`server/processing.py:115-181`) — but where
the reference round-trips every stage through image files and ASCII PLYs, this
pipeline keeps everything in HBM from the raw uint8 stacks to the final merged
cloud:

1. batched decode+triangulate of all N stops (chunked vmapped XLA programs);
2. per-stop fixed-size stratified subsample (static-shape stand-in for the
   reference's pre-ICP voxel downsample, `server/processing.py:83`);
3. ring registration — FPFH + feature RANSAC + point-to-plane ICP per edge
   (`server/processing.py:146-156`), optionally with the loop-closure edge and
   pose-graph LM of the legacy merge (`Old/360Merge.py:43-84`);
4. every FULL-resolution cloud transformed by its pose and merged through the
   final voxel → SOR → normals cleanup (`server/processing.py:171-181`).

The only host↔device traffic is the input stacks in and the final compacted
cloud out. This file is the north-star benchmark target (BASELINE.md: 24
stops × 46 frames @1080p in < 2 s).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import DecodeConfig, TriangulationConfig
from .. import health as health_mod
from ..io import ply as ply_io
from ..ops import pointcloud, posegraph, registration
from ..ops.triangulate import Calibration
from ..utils import events, trace
from ..utils.log import get_logger
from . import merge as merge_mod
from . import pipeline as pipeline_mod

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Scan360Params:
    """End-to-end knobs. ``merge`` carries the registration/cleanup settings
    (reference GUI defaults); ``view_cap`` bounds each stop's contribution to
    the final full-resolution merge (slots, post voxel-downsample)."""

    merge: merge_mod.MergeParams = merge_mod.MergeParams()
    method: str = "sequential"  # or "posegraph"
    # Ring dispatch strategy: "loop" (default; two small compiled programs)
    # or "scan" (whole ring in one launch — lowest latency on remote TPUs,
    # but a much heavier cold compile; see merge.register_sequence).
    ring_strategy: str = "loop"
    # Decode dispatch: "loop" launches one program per chunk; "scan" runs
    # ONE lax.scan over the chunks (single launch; requires device-resident
    # stacks — host arrays fall back to the loop so per-chunk staging still
    # overlaps compute).
    decode_strategy: str = "loop"
    view_cap: int = 131_072
    # Fuse the ENTIRE pipeline — decode scan, registration subsample, ring,
    # pose chain/pose-graph LM, per-view reduce, final cleanup — into ONE
    # XLA program (one launch, zero mid-path host syncs). Requires
    # device-resident stacks (host arrays fall back to the strategies
    # below). This is the lowest-latency path on remote/tunneled TPUs,
    # where every separate launch or host readback costs a network round
    # trip; the cold compile is heavy (minutes) but rides the persistent
    # compilation cache.
    fused: bool = False
    # Stops decoded/triangulated per device dispatch. The dense per-pixel
    # intermediates of ONE 1080p stop already saturate the chip; vmapping
    # every stop at once would multiply peak HBM by N (24×1080p ≈ 25 GB of
    # fusion temporaries — more than a v5e has). Chunking bounds memory at
    # chunk × per-stop while keeping dispatch overhead amortized.
    stop_chunk: int = 6
    # Retained for config compatibility; the merge reduction no longer
    # chunks (it transforms the pre-gathered per-stop subsample directly —
    # see `_subsample_views_body`).
    reduce_chunk: int = 6
    # Device-side compaction of the FUSED path's outputs to this many
    # static slots before readback (surviving points pack to the front, so
    # the host pulls ~the real cloud instead of `final_max_points` padded
    # slots — on a remote/tunneled TPU the readback rides a slow link).
    # None = no compaction. If survivors exceed the cap the result is a
    # stratified subset (a warning logs the truncation); size it above the
    # expected post-voxel/SOR count.
    output_cap: int | None = None
    # Host-side quality gates over the pipeline's existing health signals
    # (per-stop decode coverage from the `valid` masks, per-edge ICP
    # fitness/RMSE). None = gates off, behavior identical to before. With
    # gates on, a failing stop is DROPPED from the ring (its merge
    # contribution masked out — all static shapes preserved — and its ring
    # neighbors re-registered directly with the already-compiled edge
    # program, so no recompile) and a failing edge is repaired by the
    # ring-consensus step / down-weighted in the pose graph. See
    # `health.QualityGates` and docs/ROBUSTNESS.md. Gates require the
    # multi-launch path (fused=True falls back with a warning).
    gates: health_mod.QualityGates | None = None


@functools.lru_cache(maxsize=None)
def _decode_scan_fn(col_bits: int, row_bits: int, decode_cfg, tri_cfg,
                    chunk: int):
    """All decode chunks as ONE lax.scan launch (the chunk program is the
    scan body, compiled once). Memory contract is unchanged: one chunk of
    dense fusion temporaries lives at a time."""

    def body(carry, chunk_stacks):
        r = pipeline_mod.reconstruct_batch_fn(
            col_bits, row_bits, decode_cfg, tri_cfg)(chunk_stacks, carry)
        return carry, (r.points, r.colors, r.valid)

    @jax.jit
    def run(chunked_stacks, calib):
        _, ys = jax.lax.scan(body, calib, chunked_stacks)
        return ys

    return run


def _subsample_views_body(view_cap: int, m_reg: int):
    """ONE stratified pass per stop feeding BOTH downstream consumers:
    the merge view gathers ``view_cap`` slots, and the registration view
    resamples those uniformly down to ``m_reg``
    (stratified-of-stratified = stratified). Running the cumsum +
    binary-search machinery once instead of twice per stop was ~190 ms of
    the fused 360 program (XProf searchsorted gathers).

    Deliberately NO per-view voxel downsample: ``_finalize`` voxel-dedups
    the concatenation globally anyway, and a per-view pass would sort
    every view's full 2M-pixel cloud (3 sort passes each — it dominated
    the whole merge stage in round 1)."""

    def run(pts, cols, vals):
        sub_idx, sub_val = jax.vmap(
            lambda v: pointcloud.stratified_indices(v, view_cap))(vals)
        sub_pts = jnp.where(
            sub_val[..., None],
            jnp.take_along_axis(pts, sub_idx[..., None], axis=1), 0.0)
        sub_col = jnp.where(
            sub_val[..., None],
            jnp.take_along_axis(cols, sub_idx[..., None], axis=1),
            0.0).astype(jnp.float32)
        if view_cap >= m_reg:
            # Uniform resample of the VALID prefix of the gathered slots
            # (they pack at the front): stride by each stop's own valid
            # count, not by view_cap — a stop with fewer valid points than
            # view_cap would otherwise land most registration slots on
            # invalid padding. Float stride like stratified_indices (an
            # int product can overflow int32 at 4K sizes); ≤ m_reg valid
            # points keep identity slots (masked by sub_val).
            nv = jnp.sum(sub_val.astype(jnp.int32), axis=1)     # (N,)
            j = jnp.arange(m_reg, dtype=jnp.int32)
            stridef = nv.astype(jnp.float32)[:, None] / float(m_reg)
            rj = jnp.floor(j[None, :].astype(jnp.float32)
                           * stridef).astype(jnp.int32)
            rj = jnp.where(nv[:, None] > m_reg, rj,
                           jnp.minimum(j[None, :], view_cap - 1))
            rj = jnp.clip(rj, 0, view_cap - 1)
            reg_pts = jnp.take_along_axis(sub_pts, rj[..., None], axis=1)
            reg_val = jnp.take_along_axis(sub_val, rj, axis=1)
        else:  # unusual config: merge view smaller than registration view
            reg_pts, _, reg_val = jax.vmap(
                lambda p, v: pointcloud.stratified_subsample(
                    p, m_reg, valid=v))(pts, vals)
        return sub_pts, sub_col, sub_val, reg_pts, reg_val

    return run


@functools.lru_cache(maxsize=None)
def _subsample_views_fn(view_cap: int, m_reg: int):
    # The dense per-stop decode buffers (N, ~2M, 3) are DONATED: nothing
    # reads them after the subsample gathers (both callers take coverage
    # and shapes beforehand), and at 24×1080p they are ~600 MB of HBM
    # released during the gather instead of held to the end of the stage
    # — the sharding-readiness contract (docs/JAXLINT.md). The gathered
    # outputs are smaller than the inputs, so XLA reports the donation as
    # un-aliasable at compile; the early release still stands. Callers
    # must treat the passed arrays as consumed (every in-repo caller's
    # buffers are dead after this call).
    return jax.jit(_subsample_views_body(view_cap, m_reg),
                   donate_argnums=(0, 1, 2),
                   in_shardings=None, out_shardings=None)


@functools.lru_cache(maxsize=None)
def _transform_views_fn():
    return jax.jit(jax.vmap(registration.transform_points))


def _tail_body(params: Scan360Params, n: int, m_reg: int, view_cap: int):
    """Everything AFTER decode — registration subsample → whole-ring
    registration → pose chain (or pose-graph LM) → per-view reduce →
    voxel/SOR/normals finalize → output compaction — as one traceable
    function of the per-stop dense clouds. Inlined by :func:`_fused_fn`
    (the one-launch full pipeline) and jitted standalone by
    :func:`_fused_tail_fn` for the capture-overlapped streaming path
    (:func:`scan_stream_to_cloud`), so the two cannot diverge."""
    mp = params.merge
    loop = params.method == "posegraph" and mp.loop_closure
    ring = merge_mod._ring_body(mp, n, loop)
    cap = merge_mod._round_up(mp.final_max_points)

    def run(pts, cols, vals, key):
        p_count = pts.shape[1]

        # Shared subsample structure (see `_subsample_views_body` — the
        # loop strategies use the SAME traced body, so the paths cannot
        # diverge).
        vc = min(view_cap, p_count)
        mr = min(m_reg, p_count)
        sub_pts, sub_col, sub_val, reg_pts, reg_val = _subsample_views_body(
            vc, mr)(pts, cols, vals)

        keys = jax.random.split(key, n)
        Ts, fit, rmse, infos = ring(reg_pts, reg_val, keys)
        if params.method == "posegraph":
            graph = posegraph.build_360_graph(
                Ts[: n - 1], infos[: n - 1],
                Ts[n - 1] if loop else None,
                infos[n - 1] if loop else None)
            poses = posegraph.optimize(graph,
                                       iterations=mp.posegraph_iterations)
        else:
            poses = posegraph.chain_poses(Ts[: n - 1])
        poses_f = poses.astype(jnp.float32)

        # Per-view reduce: the subsample is already gathered, so the merge
        # contribution is just the pose transform of (n, vc, 3) points
        # (transform commutes with the gather — no per-chunk scan, no
        # second stratified pass over the full 2M-pixel clouds).
        moved = jax.vmap(registration.transform_points)(poses_f, sub_pts)
        flat_pts = moved.reshape(-1, 3)
        flat_col = sub_col.reshape(-1, 3)
        flat_val = sub_val.reshape(-1)

        # Final cleanup chain (`server/processing.py:171-181`) — the SAME
        # traced body as merge._finalize_fn, so fused and standalone paths
        # cannot diverge.
        dpts, dcol, normals, out_valid = merge_mod._finalize_body(
            mp, cap)(flat_pts, flat_col, flat_val)
        n_out = jnp.sum(out_valid.astype(jnp.int32))
        if params.output_cap is not None:
            # Pack survivors to the front of output_cap slots (identity
            # order when they fit; stratified subset + warning when not)
            # — the readback then moves ~the real cloud, not the padded
            # final_max_points buffers. Colors travel as uint8 and
            # normals as f16 (unit vectors; ~5e-4 error) for the same
            # reason; points stay f32.
            cidx, cval = pointcloud.stratified_indices(out_valid,
                                                       params.output_cap)
            dpts = jnp.where(cval[:, None], dpts[cidx], 0.0)
            dcol = jnp.where(cval[:, None], dcol[cidx], 0.0)
            normals = jnp.where(cval[:, None], normals[cidx], 0.0)
            out_valid = cval
        dcol_u8 = jnp.clip(dcol, 0, 255).astype(jnp.uint8)
        return (dpts, dcol_u8, normals.astype(jnp.float16), out_valid,
                n_out, poses_f, fit, rmse)

    return run


@functools.lru_cache(maxsize=None)
def _fused_tail_fn(params: Scan360Params, n: int, m_reg: int,
                   view_cap: int):
    """The post-decode tail as its own single launch (streaming path).

    The accumulated dense clouds are donated (same rationale and caller
    contract as :func:`_subsample_views_fn` — the streaming path holds
    the whole session's decode output only until this launch)."""
    return jax.jit(_tail_body(params, n, m_reg, view_cap),
                   donate_argnums=(0, 1, 2),
                   in_shardings=None, out_shardings=None)


@functools.lru_cache(maxsize=None)
def _fused_fn(params: Scan360Params, decode_cfg, tri_cfg,
              col_bits: int, row_bits: int, n: int, m_reg: int,
              view_cap: int):
    """The ENTIRE 360° pipeline as ONE jitted program: chunked decode scan →
    registration subsample → whole-ring registration → pose chain (or
    pose-graph LM) → chunked per-view reduce → voxel/SOR/normals finalize.

    Zero host syncs between the raw stacks and the final compact cloud:
    on a remote/tunneled TPU the round-trip budget collapses from ~15
    launches + several readbacks (the "loop"/"scan" strategies) to ONE
    launch + one readback. Memory contract matches the chunked strategies:
    the decode and reduce stages run as ``lax.scan`` over the same chunk
    sizes, so only one chunk of dense per-pixel fusion temporaries is live
    at a time.
    """
    chunk = max(1, min(params.stop_chunk, n))
    n_pad = ((n + chunk - 1) // chunk) * chunk
    recon = pipeline_mod.reconstruct_batch_fn(col_bits, row_bits, decode_cfg,
                                              tri_cfg)
    tail = _tail_body(params, n, m_reg, view_cap)

    def run(stacks, calib, key):
        # stacks: (n_pad, F, H, W) uint8, already padded to the chunk
        # multiple (repeat-last padding, sliced away below).
        def dec_body(carry, chunk_stacks):
            r = recon(chunk_stacks, carry)
            return carry, (r.points, r.colors, r.valid)

        _, (pts, cols, vals) = jax.lax.scan(
            dec_body, calib,
            stacks.reshape((n_pad // chunk, chunk) + stacks.shape[1:]))
        pts = pts.reshape(n_pad, -1, 3)[:n]
        cols = cols.reshape(n_pad, -1, 3)[:n]
        vals = vals.reshape(n_pad, -1)[:n]
        return tail(pts, cols, vals, key)

    return jax.jit(run)


def scan_stacks_to_cloud(
    stacks: jnp.ndarray,
    calib: Calibration,
    col_bits: int,
    row_bits: int,
    params: Scan360Params = Scan360Params(),
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    key=None,
    with_stats: bool = False,
    health: health_mod.ScanHealthReport | None = None,
    stop_labels=None,
):
    """(N, F, H, W) uint8 capture stacks → (merged PointCloud, poses (N,4,4)).

    ``stacks`` may be a device array or a host ``np.ndarray`` — pass the
    host array for large scans: chunks are then staged to HBM one at a time
    (a 24-stop 1080p session is 2.3 GB of uint8 that never needs to be
    device-resident all at once).

    Stops are assumed in turntable order (stop i+1 photographed after one
    rotation step), which is what the ring registration chain relies on —
    same assumption as the reference's numeric filename sort
    (`Old/new360Merge.py:7-20`).

    ``with_stats`` appends a third return value: a dict with per-edge
    registration quality (``{"edges": [{src, dst, fitness, rmse}, ...]}``)
    so callers (bench telemetry, quality guards) can attribute ring
    regressions to specific edges.

    ``params.gates`` enables the failure-containment path: per-stop decode
    coverage and per-edge fitness/RMSE are gated host-side, failing stops
    are dropped from the ring (bridged, masked out of the merge — static
    shapes and compiled programs unchanged), failing edges repaired;
    ``health`` (a :class:`~..health.ScanHealthReport`) accumulates what
    happened. ``stop_labels`` (gated path only) maps stack position →
    physical stop index when the stacks already exclude capture-failed
    stops, so health records key by real stops and bridge gaps count
    real commanded steps.
    """
    if params.method not in ("sequential", "posegraph"):
        raise ValueError(f"method must be 'sequential' or 'posegraph', "
                         f"got {params.method!r}")
    if params.decode_strategy not in ("loop", "scan"):
        raise ValueError(f"decode_strategy must be 'loop' or 'scan', "
                         f"got {params.decode_strategy!r}")
    if key is None:
        key = jax.random.PRNGKey(0)
    n = stacks.shape[0]
    mp = params.merge

    if params.gates is not None and params.fused:
        log.warning("quality gates need the multi-launch path — "
                    "fused=True falls back to the loop strategies")
    if params.fused and params.gates is None \
            and not isinstance(stacks, np.ndarray):
        return _run_fused(stacks, calib, col_bits, row_bits, params,
                          decode_cfg, tri_cfg, key, with_stats=with_stats)

    # 1. Decode + triangulate every stop, chunked (see ``stop_chunk``). Only
    # the dense outputs actually needed downstream (points/colors/valid) are
    # retained across chunks — the heavy fusion temporaries die with each
    # dispatch, and the decoded col/row maps are dropped. Raw stacks may
    # arrive as host arrays: then each chunk is staged to HBM on its own and
    # the full uint8 stack never lives on device at once.
    recon = pipeline_mod.reconstruct_batch_fn(col_bits, row_bits, decode_cfg,
                                              tri_cfg)
    chunk = max(1, min(params.stop_chunk, n))
    # Pad the stop axis to a chunk multiple (repeating the last stop) so
    # every dispatch reuses ONE compiled batch shape — a ragged tail chunk
    # would force a second multi-minute compile of the heaviest programs.
    # Padded outputs are sliced away immediately after each loop.
    n_pad = ((n + chunk - 1) // chunk) * chunk
    if n_pad != n:
        pad = [stacks[-1:]] * (n_pad - n)
        cat = np.concatenate if isinstance(stacks, np.ndarray) \
            else jnp.concatenate
        stacks = cat([stacks] + pad)
    with trace.span("scan360.decode_triangulate", stops=n, chunk=chunk):
        use_scan = (params.decode_strategy == "scan"
                    and not isinstance(stacks, np.ndarray))
        if use_scan:
            dec = _decode_scan_fn(col_bits, row_bits, decode_cfg, tri_cfg,
                                  chunk)
            pts, cols, vals = dec(
                stacks.reshape((n_pad // chunk, chunk) + stacks.shape[1:]),
                calib)
            res = pipeline_mod.CloudResult(
                pts.reshape((n_pad, -1, 3))[:n],
                cols.reshape((n_pad, -1, 3))[:n],
                vals.reshape((n_pad, -1))[:n], None, None)
        else:
            pts_p, col_p, val_p = [], [], []
            for s in range(0, n_pad, chunk):
                part = stacks[s:s + chunk]
                if isinstance(part, np.ndarray):
                    part = jax.device_put(jnp.asarray(part))
                elif part is stacks:
                    # jnp full-range slicing short-circuits to the SAME
                    # array, and the decode program donates its stack
                    # argument — the caller's buffer must not be the one
                    # handed over (single-chunk device sessions).
                    part = jnp.array(part, copy=True)
                r = recon(part, calib)
                pts_p.append(r.points)
                col_p.append(r.colors)
                val_p.append(r.valid)
            res = pipeline_mod.CloudResult(
                jnp.concatenate(pts_p)[:n], jnp.concatenate(col_p)[:n],
                jnp.concatenate(val_p)[:n], None, None)
            del pts_p, col_p, val_p

    if params.gates is not None:
        return _gated_tail(res, params, key, with_stats=with_stats,
                           health=health, stop_labels=stop_labels)

    # 2. ONE stratified pass per stop feeds BOTH the registration view and
    # the merge reduce (same structure as the fused path, `_fused_fn`, so
    # the two cannot diverge): view_cap slots gathered once, registration
    # view strided down to m_reg.
    m_reg = min(merge_mod._round_up(mp.max_points), res.points.shape[1])
    view_cap = merge_mod._round_up(min(params.view_cap, res.points.shape[1]))
    with trace.span("scan360.subsample", m=m_reg):
        sub_pts, sub_col, sub_val, reg_pts, reg_val = _subsample_views_fn(
            view_cap, m_reg)(res.points, res.colors, res.valid)

    # 3. Ring registration → per-stop poses.
    loop = params.method == "posegraph" and mp.loop_closure
    with trace.span("scan360.register", edges=n - 1 + int(loop)):
        (seq_T, seq_info, loop_T, loop_info, edge_fit,
         edge_rmse) = merge_mod.register_sequence(
            reg_pts, reg_val, mp, loop_closure=loop, key=key,
            strategy=params.ring_strategy)
        if params.method == "posegraph":
            graph = posegraph.build_360_graph(seq_T, seq_info, loop_T,
                                              loop_info)
            poses = posegraph.optimize(graph,
                                       iterations=mp.posegraph_iterations)
        else:
            poses = posegraph.chain_poses(seq_T)

    # 4. Merge under the poses: the per-stop subsample is already gathered
    # (stage 2), so the merge contribution is just the pose transform of
    # (N, view_cap, 3) points; the global voxel dedup happens in
    # _finalize.
    poses_f = jnp.asarray(poses, jnp.float32)
    with trace.span("scan360.merge", view_cap=view_cap):
        moved = _transform_views_fn()(poses_f, sub_pts)
        merged = merge_mod._finalize(
            moved.reshape(-1, 3), sub_col.reshape(-1, 3),
            sub_val.reshape(-1), mp, has_colors=True)
    log.info("scan_stacks_to_cloud: %d stops → %d points (%s)", n,
             len(merged), params.method)
    if with_stats:
        return merged, np.asarray(poses), _edge_stats(
            n, np.asarray(edge_fit), np.asarray(edge_rmse))
    return merged, np.asarray(poses)


def _edge_stats(n: int, fit: np.ndarray, rmse: np.ndarray) -> dict:
    """Per-edge registration-quality telemetry (edge i maps stop src→dst,
    the ring ordering of `merge._ring_edge_indices`)."""
    edges = []
    for i in range(fit.shape[0]):
        src, dst = (i + 1, i) if i < n - 1 else (0, n - 1)  # loop edge last
        edges.append({"src": src, "dst": dst,
                      "fitness": round(float(fit[i]), 4),
                      "rmse": round(float(rmse[i]), 4)})
    fits = [e["fitness"] for e in edges]
    return {"edges": edges,
            "min_fitness": min(fits) if fits else None,
            "mean_fitness": round(float(np.mean(fits)), 4) if fits else None}


# ---------------------------------------------------------------------------
# Quality-gated path (failure containment; see health.QualityGates)
# ---------------------------------------------------------------------------


def _ring_span(labels: list[int], step_deg: float | None) -> int:
    """Total commanded steps of the full ring, for the loop edge's
    wrap-around gap. The commanded step pins it exactly (360/step);
    without it, max(labels)+1 is the best available estimate — it cannot
    see holes AFTER the last surviving stop, so prefer setting
    ``MergeParams.step_deg`` whenever the ring may be degraded."""
    if step_deg:
        return max(int(round(360.0 / abs(step_deg))), max(labels) + 1)
    return max(labels) + 1


def _register_ring_gated(reg_pts, reg_val, mp: merge_mod.MergeParams,
                         surv: list[int], labels: list[int], loop: bool,
                         key):
    """Ring registration over the SURVIVING stops only, reusing the two
    already-compiled loop-strategy programs (`merge._preprocess_fn`,
    `merge._edge_fn`) — per-stop/per-edge shapes are independent of the
    stop count, so dropping a stop changes the number of invocations, not
    the programs (the no-recompile contract the chaos suite asserts).

    An edge between non-adjacent survivors is a BRIDGE registered
    directly (src onto dst, spanning the dropped stops); its ``gap``
    records how many commanded turntable steps it covers. The axis-prior
    re-pass is vmapped over a static edge count and is skipped here —
    the edge gates + consensus repair in :func:`health.gate_edges` cover
    its failure mode on the degraded ring.

    Returns ``(edges, Ts, fit, rmse, infos)`` with host arrays; ``edges``
    is a list of ``(src, dst, gap)``.
    """
    prep = merge_mod._preprocess_fn(mp.voxel_size, mp.normals_k,
                                    mp.fpfh_max_nn, mp.fpfh_engine,
                                    mp.fpfh_slots, mp.fpfh_max_cells)
    edge = merge_mod._edge_fn(mp)
    keys = jax.random.split(key, len(surv))
    pre = {i: prep(reg_pts[i], reg_val[i])[:4] for i in surv}
    pairs = [(surv[j + 1], surv[j]) for j in range(len(surv) - 1)]
    if loop:
        pairs.append((surv[0], surv[-1]))
    # Edge metadata in PHYSICAL labels (same order as `pairs`): gaps count
    # commanded steps, spanning capture-failed stops too.
    edges = health_mod.ring_edges([labels[i] for i in surv], loop,
                                  span=_ring_span(labels, mp.step_deg))
    hint = jnp.eye(4, dtype=jnp.float32)
    outs = []
    for k_i, (s, d) in enumerate(pairs):
        s_pts, s_val, _, s_feat = pre[s]
        d_pts, d_val, d_nrm, d_feat = pre[d]
        out = edge(s_pts, s_val, s_feat, d_pts, d_val, d_nrm, d_feat,
                   keys[k_i], hint)
        outs.append(out)
        hint = out[0]
    Ts = np.stack([np.asarray(o[0]) for o in outs])
    fit = np.array([float(o[1]) for o in outs])
    rmse = np.array([float(o[2]) for o in outs])
    infos = np.stack([np.asarray(o[3]) for o in outs])
    return edges, Ts, fit, rmse, infos


def _terminal_guard_cloud(merged: ply_io.PointCloud, sub_pts, sub_val,
                          coverage: np.ndarray,
                          health: health_mod.ScanHealthReport):
    """Last line of defence: a NaN-poisoned or empty merge degrades to the
    best available artifact (non-finite points stripped; if nothing is
    left, the highest-coverage stop's raw subsample) instead of handing
    the caller a crash in the mesher/writer."""
    pts = np.asarray(merged.points)
    if pts.shape[0]:
        finite = np.isfinite(pts).all(axis=1)
        if not finite.all():
            health.note("terminal guard: stripped %d non-finite points "
                        "from the merged cloud", int((~finite).sum()))
            merged = ply_io.PointCloud(
                points=pts[finite],
                colors=None if merged.colors is None
                else np.asarray(merged.colors)[finite],
                normals=None if merged.normals is None
                else np.asarray(merged.normals)[finite])
    if len(merged) == 0:
        best = int(np.argmax(coverage))
        p = np.asarray(sub_pts[best])
        v = np.asarray(sub_val[best])
        health.note("terminal guard: merged cloud empty — degraded to the "
                    "raw subsample of best-coverage stop %d (%d points)",
                    best, int(v.sum()))
        merged = ply_io.PointCloud(points=p[v].astype(np.float32))
    return merged


def _gated_tail(res, params: Scan360Params, key, with_stats: bool,
                health: health_mod.ScanHealthReport | None,
                stop_labels=None):
    """Stages 2-4 under the quality gates: coverage gate → (possibly
    degraded) ring registration → edge gates/repair → masked merge →
    terminal guard. Static shapes everywhere: dropping a stop only masks
    its merge contribution and re-routes ring edges.

    ``stop_labels`` maps stack position → PHYSICAL stop index (strictly
    increasing; default identity). Callers whose stacks already exclude
    capture-failed stops pass the surviving physical indices so (a) one
    ``ScanHealthReport`` can span capture and compute without the records
    colliding, and (b) edge gaps count real commanded steps across the
    holes (the consensus repair raises the step transform to that power).
    """
    gates = params.gates
    health = health if health is not None else health_mod.ScanHealthReport()
    mp = params.merge
    n = res.points.shape[0]
    labels = list(range(n)) if stop_labels is None \
        else [int(x) for x in stop_labels]
    if len(labels) != n:
        raise ValueError(f"stop_labels has {len(labels)} entries for "
                         f"{n} stops")

    # -- per-stop decode-coverage gate (N scalars read back) ---------------
    coverage = np.asarray(jnp.mean(res.valid.astype(jnp.float32), axis=1))
    for i in range(n):
        health.stop(labels[i]).coverage = float(coverage[i])
    keep = coverage >= gates.min_coverage
    if int(keep.sum()) < 2:
        order = np.argsort(-coverage)
        keep = np.zeros(n, bool)
        keep[order[:2]] = True
        health.note("coverage gate relaxed: fewer than 2 stops ≥ %.3f — "
                    "keeping best stops %s", gates.min_coverage,
                    sorted(labels[int(i)] for i in order[:2]))
    dropped = [i for i in range(n) if not keep[i]]
    for i in dropped:
        health.stop(labels[i]).status = "dropped"
        events.record("stop_dropped", severity="warning",
                      message="decode coverage below gate",
                      scan_id=health.scan_id, stop=labels[i],
                      coverage=round(float(coverage[i]), 4),
                      min_coverage=gates.min_coverage)
    if dropped:
        health.note("coverage gate dropped stops %s (coverage %s < %.3f)",
                    [labels[i] for i in dropped],
                    [round(float(coverage[i]), 4) for i in dropped],
                    gates.min_coverage)
    surv = [i for i in range(n) if keep[i]]

    # -- stage 2: shared subsample (same compiled program as ungated) ------
    m_reg = min(merge_mod._round_up(mp.max_points), res.points.shape[1])
    view_cap = merge_mod._round_up(min(params.view_cap, res.points.shape[1]))
    with trace.span("scan360.subsample", m=m_reg):
        sub_pts, sub_col, sub_val, reg_pts, reg_val = _subsample_views_fn(
            view_cap, m_reg)(res.points, res.colors, res.valid)

    # -- stage 3: ring registration + edge gates ---------------------------
    loop = params.method == "posegraph" and mp.loop_closure
    with trace.span("scan360.register", edges=len(surv) - 1 + int(loop),
                    dropped=len(dropped)):
        if not dropped:
            # Full ring: identical heavy path to the ungated pipeline
            # (including the axis-prior pass); gates apply post-hoc.
            (seq_T, seq_info, loop_T, loop_info, fit,
             rmse) = merge_mod.register_sequence(
                reg_pts, reg_val, mp, loop_closure=loop, key=key,
                strategy=params.ring_strategy)
            edges = health_mod.ring_edges(labels, loop,
                                          span=_ring_span(labels,
                                                          mp.step_deg))
            Ts = np.asarray(seq_T)
            infos = np.asarray(seq_info)
            if loop:
                Ts = np.concatenate([Ts, np.asarray(loop_T)[None]])
                infos = np.concatenate([infos, np.asarray(loop_info)[None]])
        else:
            edges, Ts, fit, rmse, infos = _register_ring_gated(
                reg_pts, reg_val, mp, surv, labels, loop, key)
    Ts2, infos2, _ = health_mod.gate_edges(
        edges, Ts, np.asarray(fit), np.asarray(rmse), infos, gates,
        step_deg=mp.step_deg, report=health)

    # -- poses: chain (or pose-graph) over the surviving ring --------------
    n_seq = len(surv) - 1
    if params.method == "posegraph":
        graph = posegraph.build_360_graph(
            jnp.asarray(Ts2[:n_seq], jnp.float32),
            jnp.asarray(infos2[:n_seq], jnp.float32),
            jnp.asarray(Ts2[n_seq], jnp.float32) if loop else None,
            jnp.asarray(infos2[n_seq], jnp.float32) if loop else None)
        surv_poses = np.asarray(posegraph.optimize(
            graph, iterations=mp.posegraph_iterations))
    else:
        surv_poses = np.empty((len(surv), 4, 4), np.float64)
        surv_poses[0] = np.eye(4)
        for j in range(n_seq):
            surv_poses[j + 1] = surv_poses[j] @ np.asarray(Ts2[j],
                                                          np.float64)
    poses = np.tile(np.eye(4, dtype=np.float32), (n, 1, 1))
    for j, i in enumerate(surv):
        poses[i] = surv_poses[j].astype(np.float32)

    # -- stage 4: merge with dropped stops masked out ----------------------
    poses_f = jnp.asarray(poses, jnp.float32)
    keep_dev = jnp.asarray(keep)
    with trace.span("scan360.merge", view_cap=view_cap,
                    dropped=len(dropped)):
        moved = _transform_views_fn()(poses_f, sub_pts)
        merged = merge_mod._finalize(
            moved.reshape(-1, 3), sub_col.reshape(-1, 3),
            (sub_val & keep_dev[:, None]).reshape(-1), mp, has_colors=True)
    merged = _terminal_guard_cloud(merged, sub_pts, sub_val, coverage,
                                   health)
    log.info("scan_stacks_to_cloud[gated]: %d stops (%d dropped) → %d "
             "points (%s)", n, len(dropped), len(merged), params.method)
    if with_stats:
        stats_edges = [
            {"src": s, "dst": d, "gap": g,
             "fitness": round(float(fit[i]), 4),
             "rmse": round(float(rmse[i]), 4)}
            for i, (s, d, g) in enumerate(edges)]
        fits = [e["fitness"] for e in stats_edges]
        stats = {"edges": stats_edges,
                 "min_fitness": min(fits) if fits else None,
                 "mean_fitness": round(float(np.mean(fits)), 4)
                 if fits else None,
                 "dropped_stops": [labels[i] for i in dropped]}
        return merged, poses, stats
    return merged, poses


def _run_fused(stacks, calib, col_bits, row_bits, params, decode_cfg,
               tri_cfg, key, with_stats: bool = False):
    """Dispatch the one-launch fused program and compact the result on host
    (the single sync of the whole pipeline)."""
    n = stacks.shape[0]
    mp = params.merge
    chunk = max(1, min(params.stop_chunk, n))
    n_pad = ((n + chunk - 1) // chunk) * chunk
    if n_pad != n:  # repeat-last padding, one shape for the decode scan
        stacks = jnp.concatenate([stacks] + [stacks[-1:]] * (n_pad - n))
    m_reg = merge_mod._round_up(mp.max_points)
    view_cap = merge_mod._round_up(params.view_cap)
    fn = _fused_fn(params, decode_cfg, tri_cfg, col_bits, row_bits, n,
                   m_reg, view_cap)
    with trace.span("scan360.fused", stops=n, chunk=chunk):
        outs = fn(stacks, calib, key)
        return _compact_result(outs, params, n, with_stats, tag="fused")


def _compact_result(outs, params: Scan360Params, n: int, with_stats: bool,
                    tag: str):
    """Host side of the fused/streamed paths: ONE batched readback (per-
    array np.asarray pulls would each pay a full round trip on a remote/
    tunneled TPU, ~0.1 s apiece), edge telemetry, PointCloud assembly."""
    (dpts, dcol, normals, keep, n_out, poses, fit,
     rmse) = jax.device_get(outs)
    if params.output_cap is not None and int(n_out) > params.output_cap:
        log.warning("fused output compaction truncated %d survivors to "
                    "output_cap=%d (stratified subset)", int(n_out),
                    params.output_cap)
    for i in range(1, n):
        log.info("edge %d→%d fitness=%.3f rmse=%.4f", i, i - 1,
                 fit[i - 1], rmse[i - 1])
    if fit.shape[0] > n - 1:
        log.info("loop edge 0→%d fitness=%.3f", n - 1, fit[n - 1])
    merged = ply_io.PointCloud(
        points=dpts[keep],
        colors=dcol[keep],
        normals=normals[keep].astype(np.float32))
    log.info("scan_stacks_to_cloud[%s]: %d stops → %d points (%s)", tag, n,
             len(merged), params.method)
    if with_stats:
        return merged, np.asarray(poses), _edge_stats(n, fit, rmse)
    return merged, np.asarray(poses)


def scan_stream_to_cloud(
    stop_stacks,
    calib: Calibration,
    col_bits: int,
    row_bits: int,
    params: Scan360Params = Scan360Params(),
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    key=None,
    with_stats: bool = False,
    timing: dict | None = None,
):
    """Capture-overlapped 360° processing: consume per-stop host frame
    stacks AS THEY ARRIVE and return the merged cloud one tail-launch
    after the last stop lands.

    The reference captures then processes strictly in sequence; here each
    ``stop_chunk`` of stops is staged to HBM and decoded WHILE the
    (hardware-bound, ~46 × 200 ms per stop — `server/sl_system.py:465`)
    capture of the next stops is still running. Only the dense per-stop
    clouds are retained, so the raw 2.3 GB session never needs to be
    host- or device-resident at once. After the final stop, ONE jitted
    tail launch (`_tail_body` — the same traced body as the fused path)
    registers and merges the ring.

    ``stop_stacks``: iterable of per-stop (F, H, W) uint8 host arrays in
    turntable order (e.g. a generator draining the capture queue).
    ``timing``: optional dict that receives per-chunk
    ``stage_decode_s`` wall times and the ``tail_s`` — the
    capture-overlap evidence the bench reports.
    """
    import time as _time

    if key is None:
        key = jax.random.PRNGKey(0)
    if params.gates is not None:
        log.warning("quality gates are not applied on the streaming path "
                    "(single fused tail launch) — run scan_stacks_to_cloud "
                    "with gates for the contained pipeline")
    chunk = max(1, params.stop_chunk)
    recon = pipeline_mod.reconstruct_batch_fn(col_bits, row_bits,
                                              decode_cfg, tri_cfg)
    per_chunk_s = []
    pts_p, col_p, val_p = [], [], []
    buf = []
    n = 0

    def flush(buf):
        t0 = _time.perf_counter()
        part = np.stack(buf)
        if part.shape[0] < chunk:  # ragged tail: repeat-last padding
            part = np.concatenate(
                [part] + [part[-1:]] * (chunk - part.shape[0]))
        r = recon(jax.device_put(jnp.asarray(part)), calib)
        jax.block_until_ready(r.points)
        pts_p.append(r.points)
        col_p.append(r.colors)
        val_p.append(r.valid)
        per_chunk_s.append(_time.perf_counter() - t0)

    for stack in stop_stacks:
        buf.append(np.asarray(stack))
        n += 1
        if len(buf) == chunk:
            flush(buf)
            buf = []
    if buf:
        flush(buf)
    if n < 2:
        raise ValueError(f"need at least 2 stops, got {n}")

    t0 = _time.perf_counter()
    pts = jnp.concatenate(pts_p)[:n]
    cols = jnp.concatenate(col_p)[:n]
    vals = jnp.concatenate(val_p)[:n]
    m_reg = merge_mod._round_up(params.merge.max_points)
    view_cap = merge_mod._round_up(params.view_cap)
    tail = _fused_tail_fn(params, n, m_reg, view_cap)
    with trace.span("scan360.stream_tail", stops=n):
        outs = tail(pts, cols, vals, key)
        result = _compact_result(outs, params, n, with_stats, tag="stream")
    if timing is not None:
        timing["stage_decode_s"] = [round(t, 3) for t in per_chunk_s]
        timing["tail_s"] = round(_time.perf_counter() - t0, 3)
        timing["stops"] = n
        timing["chunk"] = chunk
    return result


# ---------------------------------------------------------------------------
# Incremental (per-stop) entry points — the building blocks of stream/
# ---------------------------------------------------------------------------


def decode_stop(stack, calib, col_bits: int, row_bits: int,
                decode_cfg: DecodeConfig = DecodeConfig(),
                tri_cfg: TriangulationConfig = TriangulationConfig()):
    """ONE stop decoded+triangulated through the SAME compiled batch
    program (B=1 lane) every other path uses — the per-stop half of an
    incremental session (`stream/`). ``stack`` is (F, H, W) uint8, host
    or device. Returns ``(points (P, 3) f32, colors (P, 3), valid (P,))``
    device arrays; the staged batch copy is donated to the program, the
    caller's ``stack`` is untouched."""
    if stack.ndim != 3:
        raise ValueError(f"stack must be (frames, H, W), got shape "
                         f"{stack.shape}")
    recon = pipeline_mod.reconstruct_batch_fn(col_bits, row_bits,
                                              decode_cfg, tri_cfg)
    if isinstance(stack, np.ndarray):
        part = jax.device_put(jnp.asarray(stack[None]))
    else:
        part = stack[None]  # expand_dims executes → a fresh donated buffer
    r = recon(part, calib)
    return r.points[0], r.colors[0], r.valid[0]


def subsample_stop(points, colors, valid, view_cap: int, m_reg: int):
    """One stop's merge + registration views via the shared compiled
    subsample program (stop axis of 1 — compiled once, reused every
    stop). ``view_cap``/``m_reg`` must already be rounded the way the
    batch path rounds them (see :func:`stop_view_sizes`). The staged
    [None] copies are donated; the caller's arrays are untouched.
    Returns ``(sub_pts, sub_col, sub_val, reg_pts, reg_val)``."""
    out = _subsample_views_fn(view_cap, m_reg)(
        points[None], colors[None], valid[None])
    return tuple(a[0] for a in out)


def stop_view_sizes(params: Scan360Params, n_pixels: int):
    """The (view_cap, m_reg) the batch path derives for ``n_pixels``-pixel
    stops — one derivation, so incremental sessions subsample identically
    to :func:`scan_stacks_to_cloud`."""
    m_reg = min(merge_mod._round_up(params.merge.max_points), n_pixels)
    view_cap = merge_mod._round_up(min(params.view_cap, n_pixels))
    return view_cap, m_reg


def scan_folders_to_cloud(
    stop_dirs,
    calib_path: str,
    output_path: str | None = None,
    col_bits: int | None = None,
    row_bits: int | None = None,
    params: Scan360Params = Scan360Params(),
    decode_cfg: DecodeConfig = DecodeConfig(),
    tri_cfg: TriangulationConfig = TriangulationConfig(),
    key=None,
    health: health_mod.ScanHealthReport | None = None,
    stop_labels=None,
):
    """File-level wrapper: a list of per-stop frame folders + a `.mat`
    calibration → merged cloud (optionally written to ``output_path``).

    Mirrors the reference's batch flow (`multi_point_cloud_process.py`
    followed by the merge tab) with the file round-trips removed.
    """
    import math

    from ..io import images as img_io
    from ..io import matcal

    stacks = np.stack([img_io.load_stack(d) for d in stop_dirs])
    if params.fused:
        # The one-launch path needs device-resident stacks (host arrays
        # fall back to the chunk-staged loop strategies).
        stacks = jax.device_put(jnp.asarray(stacks))
    _, _, h, w = stacks.shape
    cal = matcal.load_calibration_mat(calib_path, h, w)
    # Bit counts follow the projector extent, `ceil(log2(dim))` — exactly how
    # the reference sizes its Gray sequences (`server/sl_system.py:52-54`).
    if col_bits is None:
        col_bits = math.ceil(math.log2(cal.plane_cols.shape[0]))
    if row_bits is None:
        row_bits = math.ceil(math.log2(cal.plane_rows.shape[0]))
    expect = 2 + 2 * (col_bits + row_bits)
    if stacks.shape[1] != expect:
        raise ValueError(
            f"stack has {stacks.shape[1]} frames but {col_bits}+{row_bits} "
            f"bits imply {expect} (white, black, then pattern/inverse pairs)")
    merged, poses = scan_stacks_to_cloud(
        stacks, cal, col_bits, row_bits,
        params=params, decode_cfg=decode_cfg, tri_cfg=tri_cfg, key=key,
        health=health, stop_labels=stop_labels)
    if output_path is not None:
        ply_io.write_ply(output_path, merged)
    return merged, poses
