"""360° merge workflows: sequential chain merge and pose-graph merge.

TPU-native equivalents of the reference's two multi-scan registration
pipelines:

* ``ProcessingLogic.merge_pro_360`` (`server/processing.py:115-181`) — load
  all scans, then for each consecutive pair: voxel downsample → normals →
  FPFH → global feature RANSAC → point-to-plane ICP, accumulate the chained
  transform, concatenate, and finish with voxel downsample + statistical
  outlier removal + normal re-estimation.
* the legacy pose-graph variant (`Old/360Merge.py:43-84`,
  `Old/new360Merge.py:77-137`) — same per-pair registration plus a
  loop-closure edge (first scan onto the last), 6×6 information matrices per
  edge, and Levenberg-Marquardt pose-graph optimization before merging.
  Strictly more robust than the shipped sequential chain; exposed here as a
  first-class sibling, not a buried script.

Design notes (TPU-first):

* Every scan is padded to one common static point count, so the per-pair
  registration function compiles ONCE and is reused for all N-1 (or N) edges
  — no shape-polymorphic recompiles across a 24-stop ring.
* All per-pair work (KNN, FPFH, vmapped RANSAC hypotheses, ICP iterations)
  runs on device; only the trivial 4×4 chain accumulation and file I/O stay
  on host.
* Cleanup workflows (`remove_background`, `remove_outliers`) mirror
  `server/processing.py:24-76` as mask-producing device ops plus host
  compaction at the file boundary.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import health as health_mod
from ..io import ply as ply_io
from ..io.layout import list_clouds
from ..ops import (
    features,
    features_brick,
    pointcloud,
    posegraph,
    registration,
    segmentation,
)
from ..ops.knn import knn
from ..ops.sor_normals import sor_normals as sor_normals_fused
from ..utils.log import get_logger

log = get_logger(__name__)

_PAD = 1024  # pad point counts to a multiple of this → few distinct shapes


def _round_up(n: int) -> int:
    return ((n + _PAD - 1) // _PAD) * _PAD


@dataclasses.dataclass(frozen=True)
class MergeParams:
    """Knobs mirroring the reference GUI defaults (`server/gui.py:27-83`,
    `server/processing.py:115`)."""

    voxel_size: float = 0.02
    ransac_iterations: int = 100_000
    icp_iterations: int = 30
    fpfh_max_nn: int = 100
    normals_k: int = 30
    # FPFH engine for the per-view preprocess: "gather" = neighbor-list
    # form over the shared KNN sweep (`ops/features.py`), "brick" =
    # sorted brick-layout form (`ops/features_brick.py`; with it the
    # shared KNN shrinks to ``normals_k`` wide and ``fpfh_max_nn`` is
    # unused — all in-radius pairs are histogrammed). "gather" stays the
    # default: the XLA brick form MEASURED 2169 ms vs 556 ms at the
    # 24×8192 ring shape on the tunneled v5e (round 5; stage breakdown
    # in ops/features_brick.py's docstring) — the layout only pays off
    # as a future Mosaic kernel.
    fpfh_engine: str = "gather"
    # Brick-engine ring shape (``fpfh_engine="brick"`` only): per-cell
    # candidate slots and the occupied-cell budget of
    # `ops/features_brick.fpfh_brick`. When the cloud outgrows them the
    # engine thins candidates instead of failing — the overflow count is
    # returned by fpfh_brick and logged by the eager preprocess path —
    # so these are the knobs to raise when that warning fires.
    fpfh_slots: int = 48
    fpfh_max_cells: int = 1024
    final_nb_neighbors: int = 20      # final SOR (`server/processing.py:174`)
    final_std_ratio: float = 2.0
    loop_closure: bool = True         # pose-graph variant only
    posegraph_iterations: int = 50
    # Turntable-axis pose prior: a ring's edges all measure the SAME rigid
    # step (one turntable advance seen in the fixed camera frame), so after
    # the per-edge pass a robust consensus of the edge screws estimates
    # that step. A second ICP pass seeded with it is kept whenever it is
    # not clearly worse — on feature-poor edges (smooth surfaces of
    # revolution) where RANSAC has no signal and a free ICP slides
    # tangentially with high fitness, the prior-seeded result wins and the
    # ring stays rigid.
    axis_prior: bool = True
    # fit2 ≥ fit − margin keeps the prior-seeded edge (slides on smooth
    # geometry score the SAME fitness as the true pose, so a strict ">"
    # would never adopt the prior exactly where it matters).
    axis_prior_margin: float = 0.02
    # Commanded turntable advance per stop in degrees, when known (the
    # auto-scan loop always knows it, `server/gui.py:79-80`). With it the
    # consensus TRUSTS only edges whose rotation magnitude lands near the
    # commanded step — crucial on smooth geometry, where the FAILED edges
    # (identity slides) can be the majority and would drag a plain median
    # to zero rotation. None → plain component-wise median (majority-
    # correct assumption).
    step_deg: float | None = None
    # Per-scan point cap for REGISTRATION (the KNN/FPFH/ICP stages are
    # O(M²) tiled matmuls, so M must stay bounded regardless of capture
    # resolution). Registration on a subsample is exactly what the reference
    # does too — its per-pair preprocess voxel-downsamples before ICP
    # (`server/processing.py:83,146-147`); poses from the subsample are
    # applied to the FULL clouds at merge time. 8192 is comparable to the
    # point counts the reference's voxel-downsampled clouds actually carry
    # into RANSAC/ICP, and the O(M²) stages scale 4× per halving.
    max_points: int = 8_192
    # Slot cap for the FINAL cleanup chain after the global voxel downsample
    # (the SOR KNN is O(M²) too). Voxel-downsampled cells land in a
    # contiguous valid prefix, so when the padded merge exceeds this cap a
    # uniform random compaction bounds the cleanup cost.
    final_max_points: int = 1_048_576


class _Padded:
    """N clouds stacked to one (N, M, 3) array + valid masks (+ colors).

    Holds BOTH the full-resolution stack (for the final merge) and a
    registration view capped at ``max_points`` per scan (for the O(M²)
    KNN/FPFH/ICP stages). When a cloud exceeds the cap, a deterministic
    uniform subsample stands in for registration only.
    """

    def __init__(self, clouds: Sequence[ply_io.PointCloud],
                 max_points: int | None = None):
        if len(clouds) < 2:
            raise ValueError("need at least two clouds to merge")
        m = _round_up(max(len(c.points) for c in clouds))
        n = len(clouds)
        pts = np.zeros((n, m, 3), np.float32)
        val = np.zeros((n, m), bool)
        col = np.zeros((n, m, 3), np.float32)
        self.has_colors = any(c.colors is not None for c in clouds)
        for i, c in enumerate(clouds):
            k = len(c.points)
            pts[i, :k] = c.points
            val[i, :k] = True
            if c.colors is not None:
                col[i, :k] = c.colors
        self.points = jnp.asarray(pts)
        self.valid = jnp.asarray(val)
        self.colors = jnp.asarray(col)
        self.counts = [len(c.points) for c in clouds]

        if max_points is not None and m > _round_up(max_points):
            mr = _round_up(max_points)
            rpts = np.zeros((n, mr, 3), np.float32)
            rval = np.zeros((n, mr), bool)
            rng = np.random.default_rng(0)
            for i, c in enumerate(clouds):
                k = len(c.points)
                if k > mr:
                    sel = rng.choice(k, mr, replace=False)
                    rpts[i] = c.points[sel]
                    rval[i] = True
                else:
                    rpts[i, :k] = c.points
                    rval[i, :k] = True
            self.reg_points = jnp.asarray(rpts)
            self.reg_valid = jnp.asarray(rval)
        else:
            self.reg_points = self.points
            self.reg_valid = self.valid


# ---------------------------------------------------------------------------
# Per-pair registration (compiled once per point-count shape)
# ---------------------------------------------------------------------------


def _preprocess(pts, valid, voxel, normals_k, fpfh_max_nn,
                fpfh_engine="gather", fpfh_slots=48, fpfh_max_cells=1024):
    """`preprocess_point_cloud` (`server/processing.py:78-96`): voxel
    downsample, normals (radius 2·voxel ≈ k-NN PCA), FPFH at 5·voxel.

    "gather" engine: ONE shared KNN sweep feeds both normals (first
    ``normals_k`` columns) and FPFH (all ``fpfh_max_nn``) — the two
    O(M²) sweeps were ~40 % of the measured ring preprocess time. FPFH
    re-masks its pairs against the normal-validity mask, so the only
    deviation from separate sweeps is that a (rare) <3-neighbor point's
    slot is dropped rather than replaced by a farther neighbor.

    "brick" engine: the KNN sweep shrinks to ``normals_k`` wide (normals
    only) and FPFH runs in the sorted brick layout
    (`ops/features_brick.py`) with no neighbor lists at all.

    The 5th output is the brick engine's candidate-overflow count
    (always 0 for "gather"): eager callers get a log.warning here, and
    jitted callers (`_preprocess_fn`) must surface the returned count
    themselves once it is concrete — under a trace no host warning can
    fire."""
    if fpfh_engine not in ("gather", "brick"):
        raise ValueError(f"unknown fpfh_engine {fpfh_engine!r}")
    dpts, _, dvalid, _ = pointcloud.voxel_downsample(pts, voxel, valid=valid)
    if fpfh_engine == "brick":
        nb = knn(dpts, normals_k, points_valid=dvalid)
        normals, nvalid = pointcloud.estimate_normals(
            dpts, valid=dvalid, k=normals_k, neighbors=nb)
        feat, fvalid, n_overflow = features_brick.fpfh_brick(
            dpts, normals, 5.0 * voxel, valid=nvalid,
            slots=fpfh_slots, max_cells=fpfh_max_cells)
        features_brick.emit_overflow_warning(n_overflow, jnp.sum(nvalid))
        return dpts, dvalid & nvalid & fvalid, normals, feat, n_overflow
    k_shared = max(normals_k, fpfh_max_nn)
    nb = knn(dpts, k_shared, points_valid=dvalid)
    normals, nvalid = pointcloud.estimate_normals(dpts, valid=dvalid,
                                                  k=normals_k, neighbors=nb)
    feat, fvalid = features.fpfh(dpts, normals, 5.0 * voxel, valid=nvalid,
                                 max_nn=fpfh_max_nn, neighbors=nb)
    return (dpts, dvalid & nvalid & fvalid, normals, feat,
            jnp.zeros((), jnp.int32))


def register_pair(
    src_pts, src_valid, dst_pts, dst_valid,
    params: MergeParams,
    key=None,
):
    """RANSAC-seeded point-to-plane ICP of src onto dst — the inner step of
    `merge_pro_360` (`server/processing.py:146-156`).

    Returns (RegistrationResult, 6×6 information matrix). Inputs are the
    FULL-resolution padded clouds; downsampling happens inside, exactly as
    the reference preprocesses per pair.
    """
    v = params.voxel_size
    src = _preprocess(src_pts, src_valid, v, params.normals_k,
                      params.fpfh_max_nn, params.fpfh_engine,
                      params.fpfh_slots, params.fpfh_max_cells)
    dst = _preprocess(dst_pts, dst_valid, v, params.normals_k,
                      params.fpfh_max_nn, params.fpfh_engine,
                      params.fpfh_slots, params.fpfh_max_cells)
    return _register_preprocessed(src[:4], dst[:4], params, key=key)


@functools.lru_cache(maxsize=None)
def _edge_fn(params: MergeParams):
    """ONE jitted program for a whole edge registration (RANSAC → ICP →
    information matrix). Fusing the edge matters beyond XLA fusion: each
    eager op or separate jit call is a device round trip, and on a remote
    (tunneled) TPU a 23-edge ring at ~10 launches/edge pays seconds of pure
    latency. params is a frozen dataclass → hashable cache key."""

    return jax.jit(_edge_body(params))


@functools.lru_cache(maxsize=None)
def _edge_body(params: MergeParams):
    """The edge registration math, unjitted — shared by the per-edge jit
    (:func:`_edge_fn`) and the whole-ring ``lax.scan`` (:func:`_ring_fn`),
    where it becomes the scan body compiled ONCE for all edges."""
    it = params.icp_iterations
    # Coarse-to-fine correspondence radius (geometric 4→1 over the ICP
    # iterations): converges from rough inits where a fixed tight radius
    # finds zero correspondences and stalls.
    anneal = tuple(float(4.0 ** (1.0 - i / max(it - 1, 1)))
                   for i in range(it))

    def run(s_pts, s_val, s_feat, d_pts, d_val, d_nrm, d_feat, key, hint):
        v = params.voxel_size
        coarse = registration.ransac_feature_registration(
            s_pts, s_feat, d_pts, d_feat,
            distance_threshold=1.5 * v,
            src_valid=s_val, dst_valid=d_val,
            num_iterations=params.ransac_iterations,
            key=key,
        )

        # Feature RANSAC can fail outright on feature-poor geometry (a
        # smooth surface of revolution gives FPFH almost no signal). Pick
        # the best of {RANSAC result, caller's hint (e.g. the previous ring
        # edge — a turntable rotates by a constant step), identity} by
        # correspondence count at a loose radius, then anneal ICP down.
        cands = jnp.stack([coarse.transformation, hint,
                           jnp.eye(4, dtype=jnp.float32)])

        def count_corr(T):
            moved = registration.transform_points(T, s_pts)
            idx, found, d2 = registration._nn1(moved, d_pts, d_val, s_val)
            return jnp.sum(found & (d2 <= (4.0 * v) ** 2))

        counts = jax.vmap(count_corr)(cands)
        init = cands[jnp.argmax(counts)]

        fine = registration.icp(
            s_pts, d_pts,
            max_correspondence_distance=v,
            init=init,
            dst_normals=d_nrm,
            src_valid=s_val, dst_valid=d_val,
            max_iterations=it,
            method="point_to_plane",
            schedule=anneal,
            # Early sweeps on every 4th point (see icp docstring): the
            # correspondence sweep is the edge's wall-clock floor.
            warmup_subsample=4,
        )
        info = registration.information_matrix(
            s_pts, d_pts, fine.transformation,
            max_correspondence_distance=v,
            src_valid=s_val, dst_valid=d_val,
        )
        return fine.transformation, fine.fitness, fine.inlier_rmse, info

    return run


@functools.lru_cache(maxsize=None)
def _ring_fn(params: MergeParams, n: int, loop_closure: bool):
    """Jitted wrapper around :func:`_ring_body` (whole ring, one launch)."""
    return jax.jit(_ring_body(params, n, loop_closure))


@functools.lru_cache(maxsize=None)
def _ring_body(params: MergeParams, n: int, loop_closure: bool):
    """The ENTIRE ring — N per-stop preprocesses + N-1 (+ loop) edge
    registrations — as ONE traceable function (un-jitted so larger fused
    programs, `models/scan360._fused_tail_fn`, can inline it).

    Edges run VMAPPED, not sequentially: each edge body is itself
    scan-heavy (≈200 RANSAC hypothesis batches + 30 annealed ICP steps of
    small kernels), and a sequential edge chain executes ~5000 tiny
    kernels back-to-back — measured 3.3 s of the round-1 north-star time.
    vmap turns every step into a 23×-wider kernel (vmap-of-scan = scan of
    the vmapped body: same step count, actual TPU utilization). The price
    is the hint chain: every edge starts from identity instead of its
    predecessor's transform; the turntable-axis consensus re-pass
    (:func:`_axis_prior_pass`, also vmapped) supersedes it as the
    feature-poor-edge mechanism. Why one program at all: on a
    remote/tunneled TPU every launch is a network round trip."""
    body = _edge_body(params)

    n_edges = n - 1 + int(loop_closure)

    def run(points, valid, keys):
        pre = jax.vmap(
            lambda p, v: _preprocess(p, v, params.voxel_size,
                                     params.normals_k, params.fpfh_max_nn,
                                     params.fpfh_engine, params.fpfh_slots,
                                     params.fpfh_max_cells)
        )(points, valid)
        # pre[4] (per-stop fpfh overflow counts) is dropped here: the
        # fused one-launch ring keeps the (T, fit, rmse, info) contract
        # that scan360's fused tail consumes, so it trades the overflow
        # channel for launch count — the default "loop" strategy and
        # eager register_pair surface it (same discipline as brick_knn's
        # drop count under a fused program).
        xs = _edge_xs(pre[:4], n, loop_closure, keys)
        eye = jnp.eye(4, dtype=jnp.float32)
        outs = jax.vmap(lambda s_p, s_v, s_f, d_p, d_v, d_n, d_f, k:
                        body(s_p, s_v, s_f, d_p, d_v, d_n, d_f, k, eye)
                        )(*xs)
        if params.axis_prior and n_edges >= 3:
            outs = _axis_prior_pass(params, xs, outs)
        return outs  # (T (E,4,4), fit (E,), rmse (E,), info (E,6,6))

    return run


def _ring_edge_indices(n: int, loop_closure: bool):
    """(src, dst) stop indices of the ring's edges: seq edges i+1→i plus
    the optional loop edge 0→N-1 — THE edge ordering every ring consumer
    (first pass, axis-prior re-pass, pose-graph build) shares."""
    src = tuple(range(1, n)) + ((0,) if loop_closure else ())
    dst = tuple(range(0, n - 1)) + ((n - 1,) if loop_closure else ())
    return src, dst


def _edge_xs(pre, n: int, loop_closure: bool, keys):
    """Per-edge registration inputs from stacked per-stop preprocess
    outputs ``pre = (pts, valid, normals, feat)``; the positional layout
    every edge body (`_edge_body`, `_axis_prior_pass.re_edge`) unpacks."""
    src_ix, dst_ix = _ring_edge_indices(n, loop_closure)
    si = jnp.asarray(src_ix)
    di = jnp.asarray(dst_ix)
    return (pre[0][si], pre[1][si], pre[3][si],
            pre[0][di], pre[1][di], pre[2][di], pre[3][di],
            keys[: len(src_ix)])


def _consensus_step(Ts: jnp.ndarray,
                    step_deg: float | None) -> jnp.ndarray:
    """Robust common per-edge transform of a turntable ring: median of the
    edge screws (every edge measures the same physical step, including the
    loop edge — 345°→360° is one more advance). When the commanded step is
    known, only edges whose rotation magnitude lands near it vote — failed
    edges on smooth geometry slide to identity and can outnumber the good
    ones, so an unfiltered median would vote for zero rotation."""
    from ..ops.posegraph import log_so3

    w = jax.vmap(log_so3)(Ts[:, :3, :3])                  # (E, 3)
    t = Ts[:, :3, 3]
    if step_deg is not None:
        step = abs(float(step_deg)) * jnp.pi / 180.0
        ang = jnp.linalg.norm(w, axis=1)
        trusted = jnp.abs(ang - step) <= 0.35 * step
        # No trusted edge (fully featureless ring): fall back to all.
        trusted = trusted | (~jnp.any(trusted))
        nan = jnp.float32(jnp.nan)
        w_bar = jnp.nanmedian(jnp.where(trusted[:, None], w, nan), axis=0)
        t_bar = jnp.nanmedian(jnp.where(trusted[:, None], t, nan), axis=0)
    else:
        w_bar = jnp.median(w, axis=0)
        t_bar = jnp.median(t, axis=0)
    R_bar = registration.exp_so3(w_bar)
    Tp = jnp.eye(4, dtype=jnp.float32)
    Tp = Tp.at[:3, :3].set(R_bar)
    return Tp.at[:3, 3].set(t_bar)


@functools.lru_cache(maxsize=None)
def _axis_pass_fn(params: MergeParams):
    """Jitted axis-prior sweep for the python-loop ring strategy."""
    return jax.jit(lambda xs, outs: _axis_prior_pass(params, xs, outs))


def _axis_prior_pass(params: MergeParams, xs, outs):
    """Second ICP sweep seeded with the ring-consensus step; each edge
    keeps the seeded result unless it is clearly worse (see
    ``MergeParams.axis_prior``)."""
    Ts, fit, rmse, infos = outs
    Tp = _consensus_step(Ts, params.step_deg)
    it = params.icp_iterations
    v = params.voxel_size

    def re_edge(s_pts, s_val, _sf, d_pts, d_val, d_nrm, _df, _k):
        # TIGHT constant radius, no annealing, FEW iterations: the prior is
        # already near the answer. A wide-radius phase recruits cross-
        # surface correspondences that slide the edge right back to the
        # failure the prior exists to fix, and on smooth geometry extra
        # iterations random-walk along the unobservable (tangential)
        # direction — a handful polishes the observable directions and
        # leaves the prior's rotation intact.
        fine = registration.icp(
            s_pts, d_pts, max_correspondence_distance=v, init=Tp,
            dst_normals=d_nrm, src_valid=s_val, dst_valid=d_val,
            max_iterations=min(it, 6), method="point_to_plane")
        info2 = registration.information_matrix(
            s_pts, d_pts, fine.transformation,
            max_correspondence_distance=v,
            src_valid=s_val, dst_valid=d_val)
        return (fine.transformation, fine.fitness, fine.inlier_rmse, info2)

    T2, fit2, rmse2, info2 = jax.vmap(re_edge)(*xs)
    # Adoption: edges whose FREE result already agrees with the consensus
    # keep it unless the seeded one is at least as fit; edges that
    # DISAGREE are exactly the suspected slides, and on smooth geometry a
    # slide scores fitness as high as the truth — so for them the seeded
    # result wins under a much wider fitness margin.
    from ..ops.posegraph import log_so3

    w_free = jax.vmap(log_so3)(Ts[:, :3, :3])
    w_p = log_so3(Tp[:3, :3])
    disagree = jnp.linalg.norm(w_free - w_p[None], axis=1) \
        > 0.5 * jnp.maximum(jnp.linalg.norm(w_p), 1e-3)
    # The widened margin for disagreeing edges is only safe when the
    # consensus is anchored by the COMMANDED step: on an irregular ring
    # (skipped/resumed stop) with no step_deg, a genuinely different edge
    # must not be dragged onto the majority vote.
    wide = 10.0 if params.step_deg is not None else 1.0
    margin = jnp.where(disagree, wide * params.axis_prior_margin,
                       params.axis_prior_margin)
    use2 = fit2 >= fit - margin
    return (jnp.where(use2[:, None, None], T2, Ts),
            jnp.where(use2, fit2, fit),
            jnp.where(use2, rmse2, rmse),
            jnp.where(use2[:, None, None], info2, infos))


@functools.lru_cache(maxsize=None)
def _preprocess_fn(voxel: float, normals_k: int, fpfh_max_nn: int,
                   fpfh_engine: str = "gather", fpfh_slots: int = 48,
                   fpfh_max_cells: int = 1024):
    """Whole per-scan preprocess as one jitted program (same launch-count
    rationale as :func:`_edge_fn`)."""

    def run(pts, valid):
        return _preprocess(pts, valid, voxel, normals_k, fpfh_max_nn,
                           fpfh_engine, fpfh_slots, fpfh_max_cells)

    return jax.jit(run)




def preprocess_registration_view(points, valid, params: MergeParams):
    """One scan's registration preprocess (voxel → normals → FPFH)
    through the SAME compiled program the ring strategies use — the
    per-stop half of an incremental (streaming) ring, where stops arrive
    one at a time but must hit the already-warm programs. Returns the
    ``(pts, valid, normals, feat)`` tuple the edge program consumes."""
    prep = _preprocess_fn(params.voxel_size, params.normals_k,
                          params.fpfh_max_nn, params.fpfh_engine,
                          params.fpfh_slots, params.fpfh_max_cells)
    out = prep(points, valid)
    # prep is jitted, so the eager overflow warning inside _preprocess was
    # silenced at trace time — surface the now-concrete count (same
    # discipline as register_sequence's loop strategy).
    features_brick.emit_overflow_warning(out[4], jnp.sum(out[1]))
    return out[:4]


def register_edge(src_prep, dst_prep, params: MergeParams, key=None,
                  hint=None):
    """One ring edge — src registered onto dst — through the compiled
    edge program (`_edge_fn`): the per-edge half of an incremental ring.
    ``src_prep``/``dst_prep`` are :func:`preprocess_registration_view`
    outputs; ``hint`` seeds the RANSAC/ICP candidate set (pass the
    previous edge's transform — a turntable advances by a constant step).
    Returns ``(T, fitness, rmse, info)`` device values."""
    if key is None:
        key = jax.random.PRNGKey(0)
    if hint is None:
        hint = jnp.eye(4, dtype=jnp.float32)
    s_pts, s_val, _, s_feat = src_prep
    d_pts, d_val, d_nrm, d_feat = dst_prep
    return _edge_fn(params)(s_pts, s_val, s_feat, d_pts, d_val, d_nrm,
                            d_feat, key, hint)


def _register_preprocessed(src, dst, params: MergeParams, key=None):
    """Pair registration on already-preprocessed (pts, valid, normals, feat)
    tuples — lets ring workflows preprocess each scan ONCE even though every
    scan serves as src of one edge and dst of another."""
    if key is None:
        key = jax.random.PRNGKey(0)
    s_pts, s_val, _, s_feat = src
    d_pts, d_val, d_nrm, d_feat = dst
    T, fitness, rmse, info = _edge_fn(params)(
        s_pts, s_val, s_feat, d_pts, d_val, d_nrm, d_feat, key,
        jnp.eye(4, dtype=jnp.float32))
    return registration.RegistrationResult(T, fitness, rmse), info


def register_sequence(points: jnp.ndarray, valid: jnp.ndarray,
                      params: MergeParams,
                      loop_closure: bool = False, key=None,
                      strategy: str = "loop"):
    """Edge transforms for the ring: seq edge i maps scan i+1 into scan i's
    frame; the optional loop edge maps scan 0 into scan N-1's frame
    (`Old/360Merge.py:53-56`). ``points`` is the padded (N, M, 3) stack with
    its (N, M) valid mask — M should already be capped (see ``_Padded``).

    Python loop over a once-compiled pair step — identical static shapes per
    edge mean a single XLA program, executed N-1 (+1) times back-to-back on
    device.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    n = points.shape[0]
    keys = jax.random.split(key, n)

    if strategy == "scan":
        # One launch for the whole ring (lax.scan over stops and edges,
        # see _ring_fn) — lowest dispatch latency, but the scan-of-scans
        # program takes MUCH longer to compile cold; opt in when the
        # persistent compilation cache is warm.
        Ts, fit, rmse, infos = _ring_fn(params, n, loop_closure)(
            points, valid, keys)
    elif strategy == "loop":
        # Python loop over two once-compiled programs (per-stop preprocess,
        # per-edge registration). Dispatch stays fully async — the previous
        # edge's transform chains into the next edge's init hint as a
        # device array, and the single host sync happens at the
        # diagnostics below.
        prep = _preprocess_fn(params.voxel_size, params.normals_k,
                              params.fpfh_max_nn, params.fpfh_engine,
                              params.fpfh_slots, params.fpfh_max_cells)
        edge = _edge_fn(params)
        pre_full = [prep(points[i], valid[i]) for i in range(n)]
        pre = [p[:4] for p in pre_full]
        hint = jnp.eye(4, dtype=jnp.float32)
        outs = []
        for i in range(1, n):
            s_pts, s_val, _, s_feat = pre[i]
            d_pts, d_val, d_nrm, d_feat = pre[i - 1]
            out = edge(s_pts, s_val, s_feat, d_pts, d_val, d_nrm, d_feat,
                       keys[i - 1], hint)
            outs.append(out)
            hint = out[0]
        if loop_closure:
            s_pts, s_val, _, s_feat = pre[0]
            d_pts, d_val, d_nrm, d_feat = pre[n - 1]
            outs.append(edge(s_pts, s_val, s_feat, d_pts, d_val, d_nrm,
                             d_feat, keys[n - 1], hint))
        Ts = jnp.stack([o[0] for o in outs])
        fit = jnp.stack([o[1] for o in outs])
        rmse = jnp.stack([o[2] for o in outs])
        infos = jnp.stack([o[3] for o in outs])
        if params.axis_prior and len(outs) >= 3:
            pre_stacked = tuple(jnp.stack([pre[i][j] for i in range(n)])
                                for j in range(4))
            xs = _edge_xs(pre_stacked, n, loop_closure, keys)
            Ts, fit, rmse, infos = _axis_pass_fn(params)(
                xs, (Ts, fit, rmse, infos))
        # prep is jitted, so _preprocess's own eager overflow warning was
        # silenced at trace time — surface the now-concrete per-stop
        # counts. Deferred until after edge dispatch so the async chain
        # stays intact; the host pull lands with the diagnostics sync
        # just below.
        for p in pre_full:
            features_brick.emit_overflow_warning(p[4], jnp.sum(p[1]))
    else:
        raise ValueError(f"unknown ring strategy {strategy!r}")
    fit_np = np.asarray(fit)
    rmse_np = np.asarray(rmse)
    for i in range(1, n):
        log.info("edge %d→%d fitness=%.3f rmse=%.4f", i, i - 1,
                 fit_np[i - 1], rmse_np[i - 1])
    seq_T, seq_info = Ts[: n - 1], infos[: n - 1]
    loop_T = loop_info = None
    if loop_closure:
        loop_T, loop_info = Ts[n - 1], infos[n - 1]
        log.info("loop edge 0→%d fitness=%.3f", n - 1, fit_np[n - 1])
    # Fitness/rmse lists cover EVERY edge (the loop edge last, when
    # present) so telemetry consumers see the same edges on the loop and
    # fused paths.
    return (seq_T, seq_info, loop_T, loop_info, list(fit_np),
            list(rmse_np))


# ---------------------------------------------------------------------------
# Merge workflows
# ---------------------------------------------------------------------------


def _finalize_body(params: MergeParams, cap: int):
    """The final-cleanup math, un-jitted — shared by the standalone
    :func:`_finalize_fn` program and the one-launch fused pipeline
    (`models/scan360._fused_fn`), so the two paths cannot silently
    diverge (same pattern as :func:`_ring_body`)."""

    def run(points, colors, valid):
        dpts, dcol, dvalid, _ = pointcloud.voxel_downsample(
            points, params.voxel_size, valid=valid, attrs=colors,
            with_attrs=True)
        if dpts.shape[0] > cap:
            # Bound the O(M²) SOR below: stratified decimation of the voxel
            # cells into `cap` static slots (cells are in lexicographic
            # order so the stride stays spatially spread).
            dpts, dcol, dvalid = pointcloud.stratified_subsample(
                dpts, cap, valid=dvalid, attrs=dcol)
        if dpts.shape[0] >= pointcloud.APPROX_KNN_THRESHOLD:
            # Large clouds: one fused Morton pass for SOR + normals-on-
            # survivors (ops/sor_normals.py) — one sort, no (N,k,3) gather.
            keep, normals, nvalid = sor_normals_fused(
                dpts, valid=dvalid,
                nb_neighbors=params.final_nb_neighbors,
                std_ratio=params.final_std_ratio,
                k_normals=params.normals_k)
            return dpts, dcol, normals, nvalid
        keep = pointcloud.statistical_outlier_removal(
            dpts, valid=dvalid,
            nb_neighbors=params.final_nb_neighbors,
            std_ratio=params.final_std_ratio)
        normals, nvalid = pointcloud.estimate_normals(dpts, valid=keep,
                                                      k=params.normals_k)
        return dpts, dcol, normals, keep & nvalid

    return run


@functools.lru_cache(maxsize=None)
def _finalize_fn(params: MergeParams, cap: int):
    """Device half of the final cleanup as ONE program (launch-count
    discipline, see `_edge_fn`)."""
    return jax.jit(_finalize_body(params, cap))


def _finalize(points, colors, valid, params: MergeParams,
              has_colors: bool = True):
    """Final cleanup chain (`server/processing.py:171-181`): voxel downsample
    → statistical outlier removal → normals. Returns a compact host cloud."""
    cap = _round_up(params.final_max_points)
    dpts, dcol, normals, keep = _finalize_fn(params, cap)(
        points, colors, valid)
    keep_np = np.asarray(keep)
    colors_u8 = None
    if has_colors:
        colors_u8 = np.clip(np.asarray(dcol)[keep_np], 0,
                            255).astype(np.uint8)
    return ply_io.PointCloud(
        points=np.asarray(dpts)[keep_np],
        colors=colors_u8,
        normals=np.asarray(normals)[keep_np],
    )


def _apply_poses_and_merge(padded: _Padded, poses, params: MergeParams):
    """Transform every scan by its pose and concatenate (still padded —
    invalid slots carry through to the final masked cleanup)."""
    moved = jax.vmap(registration.transform_points)(
        jnp.asarray(poses, jnp.float32), padded.points)
    flat_pts = moved.reshape(-1, 3)
    flat_col = padded.colors.reshape(-1, 3)
    flat_val = padded.valid.reshape(-1)
    return _finalize(flat_pts, flat_col, flat_val, params,
                     has_colors=padded.has_colors)


def _gate_ring_edges(n: int, Ts: np.ndarray, infos: np.ndarray,
                     fit, rmse, loop: bool,
                     gates: health_mod.QualityGates,
                     params: MergeParams,
                     health: health_mod.ScanHealthReport | None):
    """Post-registration edge gate shared by both merge workflows: the
    ring's (seq [+ loop]) edges verdicted against ``gates``, rejects
    replaced by the ring-consensus step and down-weighted for the pose
    graph (see `health.gate_edges`)."""
    edges = health_mod.ring_edges(range(n), loop)
    Ts2, infos2, _ = health_mod.gate_edges(
        edges, Ts, np.asarray(fit), np.asarray(rmse), infos, gates,
        step_deg=params.step_deg, report=health)
    return Ts2, infos2


def merge_pro_360(
    clouds: Sequence[ply_io.PointCloud],
    params: MergeParams | None = None,
    key=None,
    gates: health_mod.QualityGates | None = None,
    health: health_mod.ScanHealthReport | None = None,
):
    """Sequential chain merge — `ProcessingLogic.merge_pro_360`
    (`server/processing.py:115-181`): scan i registers onto scan i-1, poses
    accumulate down the chain (`accum_T = accum_T @ T_local`, `:162`), no
    loop closure. With ``gates``, edges failing the fitness/RMSE gate are
    replaced by the ring-consensus step before chaining (a slid edge no
    longer corrupts every pose downstream of it). Returns
    (merged PointCloud, poses (N,4,4) np.ndarray).
    """
    params = params or MergeParams()
    padded = _Padded(clouds, max_points=params.max_points)
    seq_T, seq_info, _, _, fit, rmse = register_sequence(
        padded.reg_points, padded.reg_valid,
        params, loop_closure=False, key=key)
    if gates is not None:
        Ts2, _ = _gate_ring_edges(len(clouds), np.asarray(seq_T),
                                  np.asarray(seq_info), fit, rmse, False,
                                  gates, params, health)
        seq_T = jnp.asarray(Ts2, jnp.float32)
    poses = posegraph.chain_poses(seq_T)
    merged = _apply_poses_and_merge(padded, poses, params)
    log.info("merge_pro_360: %d scans → %d points", len(clouds), len(merged))
    return merged, np.asarray(poses)


def merge_posegraph_360(
    clouds: Sequence[ply_io.PointCloud],
    params: MergeParams | None = None,
    key=None,
    gates: health_mod.QualityGates | None = None,
    health: health_mod.ScanHealthReport | None = None,
):
    """Pose-graph merge with loop closure (`Old/360Merge.py:43-84`,
    `Old/new360Merge.py:96-137`): per-edge ICP transforms + information
    matrices → Levenberg-Marquardt global optimization → merge under the
    optimized poses. With ``gates``, edges failing the fitness/RMSE gate
    keep the graph connected but barely vote (information matrices scaled
    by ``gates.posegraph_down_weight``) and their measurements are
    replaced by the ring-consensus step. Returns
    (merged PointCloud, poses (N,4,4) np.ndarray).
    """
    params = params or MergeParams()
    padded = _Padded(clouds, max_points=params.max_points)
    seq_T, seq_info, loop_T, loop_info, fit, rmse = register_sequence(
        padded.reg_points, padded.reg_valid, params,
        loop_closure=params.loop_closure, key=key)
    if gates is not None:
        n = len(clouds)
        Ts = np.asarray(seq_T)
        infos = np.asarray(seq_info)
        if params.loop_closure:
            Ts = np.concatenate([Ts, np.asarray(loop_T)[None]])
            infos = np.concatenate([infos, np.asarray(loop_info)[None]])
        Ts2, infos2 = _gate_ring_edges(n, Ts, infos, fit, rmse,
                                       params.loop_closure, gates, params,
                                       health)
        seq_T = jnp.asarray(Ts2[: n - 1], jnp.float32)
        seq_info = jnp.asarray(infos2[: n - 1], jnp.float32)
        if params.loop_closure:
            loop_T = jnp.asarray(Ts2[n - 1], jnp.float32)
            loop_info = jnp.asarray(infos2[n - 1], jnp.float32)
    graph = posegraph.build_360_graph(seq_T, seq_info, loop_T, loop_info)
    poses = posegraph.optimize(graph, iterations=params.posegraph_iterations)
    merged = _apply_poses_and_merge(padded, poses, params)
    log.info("merge_posegraph_360: %d scans → %d points", len(clouds),
             len(merged))
    return merged, np.asarray(poses)


def merge_360_files(
    folder: str,
    output_path: str,
    params: MergeParams | None = None,
    method: str = "posegraph",
    key=None,
    gates: health_mod.QualityGates | None = None,
    health: health_mod.ScanHealthReport | None = None,
):
    """File-level entry mirroring the GUI action (`server/gui.py:622-641`):
    read every ``*.ply`` in ``folder`` (numeric sort, `Old/new360Merge.py:
    7-20`), merge, write the result. Returns the merged cloud."""
    if method not in ("posegraph", "sequential"):
        raise ValueError(f"method must be 'posegraph' or 'sequential', "
                         f"got {method!r}")
    paths = list_clouds(folder)
    if len(paths) < 2:
        raise ValueError(f"need ≥2 .ply files in {folder}, found {len(paths)}")
    clouds = [ply_io.read_ply(p) for p in paths]
    fn = merge_posegraph_360 if method == "posegraph" else merge_pro_360
    merged, _ = fn(clouds, params, key=key, gates=gates, health=health)
    ply_io.write_ply(output_path, merged)
    return merged


# ---------------------------------------------------------------------------
# Cleanup workflows (`server/processing.py:24-76`)
# ---------------------------------------------------------------------------


def remove_background(
    cloud: ply_io.PointCloud,
    distance_threshold: float = 10.0,
    num_iterations: int = 1000,
    key=None,
) -> ply_io.PointCloud:
    """Drop the dominant RANSAC plane (the wall/table behind the object) —
    `ProcessingLogic.remove_background` (`server/processing.py:24-52`)."""
    pts = jnp.asarray(cloud.points, jnp.float32)
    pts_p, val_p = _pad_cloud(pts)
    _, inliers = segmentation.segment_plane(
        pts_p, distance_threshold=distance_threshold,
        num_iterations=num_iterations, valid=val_p, key=key)
    keep = np.asarray(val_p & ~inliers)[: len(cloud.points)]
    log.info("remove_background: %d → %d points", len(cloud.points),
             int(keep.sum()))
    return _select(cloud, keep)


def remove_outliers(
    cloud: ply_io.PointCloud,
    nb_neighbors: int = 20,
    std_ratio: float = 2.0,
) -> ply_io.PointCloud:
    """Statistical outlier removal — `ProcessingLogic.remove_outliers`
    (`server/processing.py:54-76`)."""
    pts = jnp.asarray(cloud.points, jnp.float32)
    pts_p, val_p = _pad_cloud(pts)
    keep = pointcloud.statistical_outlier_removal(
        pts_p, valid=val_p, nb_neighbors=nb_neighbors, std_ratio=std_ratio)
    keep = np.asarray(keep)[: len(cloud.points)]
    log.info("remove_outliers: %d → %d points", len(cloud.points),
             int(keep.sum()))
    return _select(cloud, keep)


def register_pair_clouds(
    src: ply_io.PointCloud,
    dst: ply_io.PointCloud,
    params: MergeParams | None = None,
    key=None,
):
    """Two-cloud RANSAC+ICP alignment — the reference's pairwise
    registration demo (`Old/New360.py:37-79`) on :class:`PointCloud`
    inputs. Returns (RegistrationResult, 6×6 information matrix)."""
    if params is None:
        params = MergeParams(voxel_size=_auto_voxel(src.points))
    s_pts, s_val = _pad_cloud(jnp.asarray(src.points, jnp.float32))
    d_pts, d_val = _pad_cloud(jnp.asarray(dst.points, jnp.float32))
    return register_pair(s_pts, s_val, d_pts, d_val, params, key=key)


def _auto_voxel(points: np.ndarray) -> float:
    """A serviceable voxel size for parameterless entry points: ~1/60 of
    the bounding-box diagonal (the reference hand-picks 0.02 for its
    meter-scale clouds — same ratio for a ~1.7-unit object)."""
    pts = np.asarray(points, np.float64)
    if pts.shape[0] == 0:
        return 1.0
    diag = float(np.linalg.norm(pts.max(0) - pts.min(0)))
    return max(diag / 60.0, 1e-6)


def _pad_cloud(pts: jnp.ndarray):
    n = pts.shape[0]
    m = _round_up(n)
    val = jnp.arange(m) < n
    pts_p = jnp.zeros((m, 3), jnp.float32).at[:n].set(pts)
    return pts_p, val


def _select(cloud: ply_io.PointCloud, keep: np.ndarray) -> ply_io.PointCloud:
    return ply_io.PointCloud(
        points=cloud.points[keep],
        colors=None if cloud.colors is None else cloud.colors[keep],
        normals=None if cloud.normals is None else cloud.normals[keep],
    )
