"""Cloud → printable mesh workflows.

The framework's analogue of the reference's meshing entry points
(`ProcessingLogic.reconstruct_stl`, `server/processing.py:184-249`, and
`ProcessingLogic.mesh_360`, `server/processing.py:251-310`): estimate and
orient normals, run the (TPU) Poisson solve, extract + trim, write STL.

Orientation modes mirror `server/processing.py:267-289`:
* ``"radial"``  — orient toward the cloud center, then negate (outward);
* ``"tangent"`` — Hoppe MST propagation (`orient_normals_consistent_tangent_
  plane(100)`), falling back to radial on failure, like the reference's
  try/except at `:284-289`;
* ``"camera"``  — toward an explicit camera location.

"Surface" (non-watertight) mode: the reference ball-pivots with radii =
avg-NN-dist × multipliers (`server/processing.py:222-235`). Ball pivoting is
sequential front propagation — a poor fit for a vector machine — so it runs
in the native C++ layer (`native/src/ball_pivot.cpp`) with the same
radii-from-average-NN-distance recipe; if the native library is unavailable
the fallback is the Poisson solve with an aggressive density trim (open
surface where there was no data).
"""

from __future__ import annotations

import numpy as np

from ..io.ply import PointCloud
from ..io.stl import TriangleMesh, write_stl
from ..ops import marching, orientation, poisson, poisson_sparse
from ..ops import pointcloud as pc_ops
from ..utils.log import get_logger

log = get_logger(__name__)


def ensure_oriented_normals(
    cloud: PointCloud,
    mode: str = "radial",
    k: int = 30,
    camera: np.ndarray | None = None,
) -> np.ndarray:
    """Estimate (if absent) and globally orient normals; returns (N,3)."""
    pts = np.asarray(cloud.points, np.float32)
    if cloud.normals is not None and len(cloud.normals) == len(pts):
        normals = np.asarray(cloud.normals, np.float32)
    else:
        normals, _ = (np.asarray(a) for a in
                      pc_ops.estimate_normals(pts, k=k))

    center = pts.mean(axis=0)
    if mode == "radial":
        # Toward center then negate → outward (`server/processing.py:270-277`).
        normals = np.asarray(pc_ops.orient_normals(pts, normals, center,
                                                   outward=True))
    elif mode == "tangent":
        try:
            normals = orientation.orient_normals_consistent_tangent_plane(
                pts, normals, k=100)
        except Exception as exc:  # reference falls back to radial (:284-289)
            log.warning("tangent orientation failed (%s); radial fallback",
                        exc)
            normals = np.asarray(pc_ops.orient_normals(pts, normals, center,
                                                       outward=True))
    elif mode == "camera":
        if camera is None:
            raise ValueError("orientation='camera' needs a camera location")
        normals = np.asarray(pc_ops.orient_normals(
            pts, normals, np.asarray(camera, np.float32), outward=False))
    else:
        raise ValueError(f"unknown orientation mode {mode!r}")
    cloud.normals = normals
    return normals


def mesh_from_cloud(
    cloud: PointCloud,
    mode: str = "watertight",
    depth: int = 8,
    quantile_trim: float = 0.02,
    orientation_mode: str = "radial",
    camera: np.ndarray | None = None,
    radii_multipliers: str = "1,2,4",
    cg_iters: int = 300,
    preconditioner: str = "additive",
    extraction: str = "auto",
    max_blocks: int | None = None,
    representation: str = "poisson",
    tsdf_max_bricks: int = 8192,
    cg_x0=None,
    device_mesh=None,
    solve_stats: dict | None = None,
) -> TriangleMesh:
    """Poisson-mesh a cloud (the body of `reconstruct_stl` / `mesh_360`).

    ``mode="watertight"`` trims the given density quantile (reference default
    2%, `server/processing.py:217`; pass 0.0 for fully watertight — the
    `mesh_360` GUI default, `server/gui.py:65`). ``mode="surface"`` trims
    hard (25%) as the ball-pivot substitute. ``depth`` ≤ 8 solves on a
    2^depth dense grid; depth 9-16 routes to the band-sparse solver
    (`ops/poisson_sparse.py`), covering the reference octree's full
    acceptance envelope (default depth 10, `server/processing.py:293`;
    ≤ 16 accepted, > 16 rejected, `server/processing.py:207-208`).

    ``preconditioner`` forwards to the sparse solver's fine-band CG
    (`"additive"` two-level multigrid default; `"vcycle"`,
    `"chebyshev"`, `"jacobi"` — see ``ops.poisson_sparse.PoissonParams``)
    and ``extraction`` picks the iso-surface extractor (`"auto"` =
    device marching on TPU backends, host NumPy oracle elsewhere — see
    ``ops.marching.extract_sparse``); ``max_blocks`` overrides the
    solver's band budget (None = its default, with its own
    overflow-retry). All three only apply to the deep (sparse) path;
    the dense ≤ 8 path is untouched.

    ``representation`` dispatches the scene representation
    (docs/MESHING.md): ``"poisson"`` (default) is the watertight print
    path above; ``"tsdf"`` fuses the oriented cloud into a sparse
    brick-grid TSDF (`fusion/`) and extracts a VERTEX-COLORED mesh —
    open where the data is open, colors carried from ``cloud.colors``.
    ``depth`` maps onto the TSDF grid depth (clamped to 5–9; the volume
    is ``2^depth`` voxels per axis) and ``quantile_trim`` trims the
    lowest-weight triangle fraction; ``tsdf_max_bricks`` bounds the
    brick pool (overflow degrades to holes, logged). ``cg_x0``
    warm-starts the Poisson solve: on the dense (≤ 8) path a χ ARRAY at
    the solve resolution seeds the CG directly; on the sparse (> 8)
    path a dense ``poisson.PoissonGrid`` (the streaming previewer's
    last grid) warm-starts the internal coarse solve and a
    ``SparsePoissonGrid`` reseeds the band — see
    ``poisson_sparse.reconstruct_sparse``. The TSDF path ignores it.
    ``solve_stats`` (a caller-supplied dict) is filled with the sparse
    solver's ``with_stats`` output (``cg_iters_used``,
    ``coarse_iters_used``, ``warm_start_blocks``) — the streaming
    finalize's warm-start assertion reads it.

    ``device_mesh`` (a ``parallel/mesh.py`` Mesh, docs/MESHING.md §
    sharded solve) stages the cloud sharded over the mesh's space axis
    before the DENSE (depth ≤ 8) Poisson solve: the solver jits leave
    placement to propagation, so the committed input sharding is what
    flips the splat/CG phases from replicated to sharded — one huge
    solve spans chips (the serve tier's big-bucket dispatch) instead of
    serializing on one. The band-sparse (depth > 8) solver keeps
    single placement: its block-discovery scatters partition into
    all-gather storms under GSPMD (measured: the depth-9 compile never
    finishes on an 8-way host mesh), so sharding it needs explicit
    per-phase specs — the ROADMAP's follow-on, not a free flip.
    Host-side stages (normals, ball pivot, extraction readback) and
    the TSDF path are unaffected.
    """
    if mode not in ("watertight", "surface"):
        raise ValueError(f"unknown mesh mode {mode!r}")
    if extraction not in ("auto", "host", "device"):
        # Fail BEFORE the multi-second solve, not in the extractor after.
        raise ValueError(f"unknown extraction engine {extraction!r}")
    if representation == "archival":
        # The streaming tier's opt-in watertight format (docs/STREAMING.md):
        # TSDF previews during the scan, Poisson for the final artifact.
        # By the time a cloud reaches this function the preview story is
        # over — archival IS the Poisson print path.
        representation = "poisson"
    if representation not in ("poisson", "tsdf"):
        raise ValueError(f"unknown representation {representation!r} "
                         "(expected 'poisson', 'tsdf' or 'archival')")
    pts = np.asarray(cloud.points, np.float32)
    if pts.shape[0] < 16:
        raise ValueError(f"too few points to mesh ({pts.shape[0]})")
    normals = ensure_oriented_normals(cloud, orientation_mode,
                                      camera=camera)

    def _sharded_cloud():
        """Stage (points, normals, valid) over the device mesh. Point
        counts are data-dependent (a valid-mask compaction), so the
        cloud is padded up to a shard multiple with valid=False rows —
        an uneven device_put is a hard error, and real scans are almost
        never evenly divisible."""
        import jax

        from ..parallel import mesh as pmesh

        n = pts.shape[0]
        n_shards = int(device_mesh.devices.size)
        pad = (-n) % n_shards
        sp = pts
        sn = np.ascontiguousarray(normals, np.float32)
        sv = None
        if pad:
            sp = np.concatenate(
                [sp, np.zeros((pad, 3), np.float32)])
            sn = np.concatenate(
                [sn, np.tile(np.asarray([[0.0, 0.0, 1.0]], np.float32),
                             (pad, 1))])
            sv = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
        sharded = pmesh.points_sharding(device_mesh)
        sp = jax.device_put(sp, sharded)
        sn = jax.device_put(sn, sharded)
        if sv is not None:
            sv = jax.device_put(sv, pmesh.samples_sharding(device_mesh))
        return sp, sn, sv

    if representation == "tsdf":
        trim = quantile_trim if mode == "watertight" \
            else max(quantile_trim, 0.25)
        mesh = _tsdf_mesh(cloud, pts, normals, depth, trim,
                          tsdf_max_bricks)
        log.info("TSDF-meshed %d points -> %d verts / %d faces "
                 "(depth=%d, colored=%s)", pts.shape[0],
                 len(mesh.vertices), len(mesh.faces), depth,
                 mesh.vertex_colors is not None)
        return mesh

    if mode == "surface":
        mesh = _ball_pivot_mesh(pts, normals, radii_multipliers)
        if mesh is not None:
            log.info("ball-pivoted %d points -> %d verts / %d faces",
                     pts.shape[0], len(mesh.vertices), len(mesh.faces))
            return mesh
        log.warning("native ball pivoting unavailable; Poisson surface "
                    "fallback")

    trim = quantile_trim if mode == "watertight" else max(quantile_trim, 0.25)
    if int(depth) > 8:
        # Block-budget overflow (→ dropped blocks → holes) is detected and
        # handled INSIDE reconstruct_sparse before the solve runs.
        kw = {} if max_blocks is None else {"max_blocks": int(max_blocks)}
        if cg_x0 is not None and isinstance(
                cg_x0, (poisson.PoissonGrid,
                        poisson_sparse.SparsePoissonGrid)):
            kw["x0"] = cg_x0
        # NOT solve_pts: the sparse solver keeps single placement (see
        # the device_mesh docstring note).
        grid, n_blocks, stats = poisson_sparse.reconstruct_sparse(
            pts, normals, depth=int(depth), cg_iters=cg_iters,
            preconditioner=preconditioner, with_stats=True, **kw)
        if solve_stats is not None:
            solve_stats.update(stats)
        log.info("sparse Poisson depth=%d: %d active blocks", int(depth),
                 int(n_blocks))
        mesh = marching.extract_sparse(grid, quantile_trim=trim,
                                       engine=extraction)
    else:
        if device_mesh is not None:
            solve_pts, solve_normals, solve_valid = _sharded_cloud()
        else:
            solve_pts, solve_normals, solve_valid = pts, normals, None
        grid = poisson.reconstruct(solve_pts, solve_normals,
                                   valid=solve_valid, depth=int(depth),
                                   cg_iters=cg_iters, x0=cg_x0)
        mesh = marching.extract(grid, quantile_trim=trim)
    log.info("meshed %d points -> %d verts / %d faces (mode=%s depth=%d)",
             pts.shape[0], len(mesh.vertices), len(mesh.faces), mode, depth)
    return mesh


def mesh_from_cloud_async(cloud: PointCloud, *, task_name: str = "mesh",
                          **kw):
    """Launch :func:`mesh_from_cloud` on a pipelined worker and return
    the :class:`~..utils.overlap.PipelinedTask` handle.

    The overlapped-finalize seam (docs/MESHING.md): once a cloud's
    geometry is final, its Poisson/extraction solve shares no data with
    the caller's remaining registration/merge tail (pose assembly,
    health gating, artifact serialization) — so the solve can run while
    the caller finishes that tail, and ``task.result()`` joins
    deterministically. Determinism contract: the worker runs the SAME
    function with the SAME arguments the sequential call would, so the
    joined mesh is bit-identical to ``mesh_from_cloud(...)`` —
    tests/test_overlap.py pins it. The caller must not mutate ``cloud``
    (or ``kw`` arrays) until the join; worker exceptions re-raise at
    ``result()``, exactly where the sequential path would have thrown.
    """
    from ..utils.overlap import PipelinedTask

    return PipelinedTask(mesh_from_cloud, cloud, name=task_name, **kw)


def _tsdf_mesh(cloud: PointCloud, pts: np.ndarray, normals: np.ndarray,
               depth: int, quantile_trim: float,
               max_bricks: int) -> TriangleMesh:
    """Oriented cloud → fused TSDF → vertex-colored mesh (fusion/).

    Sign comes from the oriented normals (inward = −n̂). The point count
    is bucketed to powers of two so arbitrary clouds reuse a handful of
    compiled integrate programs (the marching capacity rule)."""
    from ..fusion import TSDFParams, TSDFVolume
    from ..ops.marching_jax import _bucket

    grid_depth = min(max(int(depth), 5), 9)
    params = TSDFParams(grid_depth=grid_depth,
                        max_bricks=int(max_bricks))
    n = pts.shape[0]
    cap = _bucket(n)
    pad = cap - n
    has_colors = cloud.colors is not None \
        and len(cloud.colors) == n
    cols = np.asarray(cloud.colors, np.float32) if has_colors \
        else np.zeros((n, 3), np.float32)
    pts_p = np.concatenate([pts, np.zeros((pad, 3), np.float32)])
    cols_p = np.concatenate([cols, np.zeros((pad, 3), np.float32)])
    nrm_p = np.concatenate([normals.astype(np.float32),
                            np.tile(np.asarray([[0.0, 0.0, 1.0]],
                                               np.float32), (pad, 1))])
    val_p = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
    vol = TSDFVolume.from_bounds(params, pts.min(axis=0),
                                 pts.max(axis=0))
    vol.integrate_oriented(pts_p, cols_p, val_p, nrm_p)
    if vol.n_dropped:
        log.warning("TSDF mesh dropped %d brick(s) past "
                    "max_bricks=%d — raise tsdf_max_bricks or lower "
                    "depth if the surface shows holes", vol.n_dropped,
                    int(max_bricks))
    return vol.extract(quantile_trim=quantile_trim,
                       with_colors=has_colors)


def _ball_pivot_mesh(pts: np.ndarray, normals: np.ndarray,
                     radii_multipliers: str) -> TriangleMesh | None:
    """Ball-pivoting via the native layer; None when unavailable.

    Radii recipe mirrors `server/processing.py:222-235`: average NN distance
    scaled by the parsed multiplier list (default "1,2,4")."""
    from .. import native
    from ..ops.knn import knn

    if not native.available():
        return None
    multipliers = [float(x) for x in str(radii_multipliers).split(",") if x]
    if not multipliers:
        multipliers = [1.0, 2.0, 4.0]
    d2, _, nbv = knn(pts, 1, exclude_self=True)
    d = np.sqrt(np.asarray(d2)[:, 0])
    avg = float(d[np.asarray(nbv)[:, 0]].mean()) if np.asarray(
        nbv).any() else 1.0
    radii = [avg * m for m in multipliers]
    tris = native.ball_pivot(pts, normals, radii)
    if len(tris) == 0:
        return None
    return TriangleMesh(vertices=pts.copy(), faces=tris)


def reconstruct_stl(
    cloud: PointCloud,
    out_path: str,
    mode: str = "watertight",
    depth: int = 8,
    quantile_trim: float = 0.02,
    orientation_mode: str = "radial",
    **kw,
) -> TriangleMesh:
    """Cloud → STL file (drop-in for `ProcessingLogic.reconstruct_stl`,
    `server/processing.py:184-249`)."""
    mesh = mesh_from_cloud(cloud, mode=mode, depth=depth,
                           quantile_trim=quantile_trim,
                           orientation_mode=orientation_mode, **kw)
    write_stl(out_path, mesh)
    return mesh


def mesh_360(
    cloud: PointCloud,
    out_path: str,
    depth: int = 8,
    quantile_trim: float = 0.0,
    orientation_mode: str = "radial",
    **kw,
) -> TriangleMesh:
    """Merged-360° cloud → watertight STL (drop-in for
    `ProcessingLogic.mesh_360`, `server/processing.py:251-310`; watertight
    trim default 0.0 per `server/gui.py:65`)."""
    mesh = mesh_from_cloud(cloud, mode="watertight", depth=depth,
                           quantile_trim=quantile_trim,
                           orientation_mode=orientation_mode, **kw)
    write_stl(out_path, mesh)
    return mesh
