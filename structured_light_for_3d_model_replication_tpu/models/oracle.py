"""NumPy oracle backend (`PROCESSING_BACKEND = "numpy_cv2"`).

An independent, plainly-written NumPy implementation of decode + triangulation
with the reference's exact semantics (`server/sl_system.py:508-653`,
`multi_point_cloud_process.py:23-119`). It exists for two reasons:

1. BASELINE.json requires the numpy_cv2 backend to remain selectable.
2. It is the correctness oracle the JAX kernels are tested against
   (per-pixel equality for decode maps/masks, float tolerance for points).

Everything here favors clarity over speed — speed is the JAX backend's job.
"""

from __future__ import annotations

import numpy as np

from ..config import DecodeConfig, TriangulationConfig


def gray_to_binary_np(g: np.ndarray, n_bits: int) -> np.ndarray:
    b = g.copy()
    shift = 1
    while shift < n_bits:
        b ^= b >> shift
        shift *= 2
    return b


def decode_bits_np(pairs: np.ndarray) -> np.ndarray:
    """(n_bits, 2, H, W) -> (H, W) int32 binary code. bit = pattern > inverse."""
    n_bits = pairs.shape[0]
    gray = np.zeros(pairs.shape[2:], dtype=np.int32)
    for b in range(n_bits):
        bit = (pairs[b, 0] > pairs[b, 1]).astype(np.int32)
        gray |= bit << (n_bits - 1 - b)
    return gray_to_binary_np(gray, n_bits)


def masks_np(white: np.ndarray, black: np.ndarray, cfg: DecodeConfig) -> np.ndarray:
    w = white.astype(np.float32)
    b = black.astype(np.float32)
    if cfg.mode == "adaptive":
        thresh_w = cfg.white_factor * np.percentile(b, cfg.black_percentile)
        contrast = w - b
        return (w > thresh_w) & (contrast > cfg.contrast_frac * contrast.max())
    if cfg.mode == "fixed":
        return (w > cfg.white_thresh) & ((w - b) > cfg.contrast_thresh)
    raise ValueError(cfg.mode)


def decode_stack_np(stack: np.ndarray, col_bits: int, row_bits: int,
                    cfg: DecodeConfig = DecodeConfig(), downsample: int = 1):
    """(n_frames, H, W) -> (col_map, row_map, mask); protocol frame order."""
    n = 2 + 2 * col_bits + 2 * row_bits
    assert stack.shape[0] == n, (stack.shape, n)
    white, black = stack[0], stack[1]
    col = stack[2:2 + 2 * col_bits].reshape(col_bits, 2, *stack.shape[1:])
    row = stack[2 + 2 * col_bits:].reshape(row_bits, 2, *stack.shape[1:])
    off = (downsample - 1) // 2
    return (
        decode_bits_np(col) * downsample + off,
        decode_bits_np(row) * downsample + off,
        masks_np(white, black, cfg),
    )


def camera_rays_np(cam_K: np.ndarray, height: int, width: int) -> np.ndarray:
    uu, vv = np.meshgrid(np.arange(width, dtype=np.float64),
                         np.arange(height, dtype=np.float64))
    pix = np.stack([uu, vv, np.ones_like(uu)], axis=-1)
    rays = pix @ np.linalg.inv(cam_K).T
    return rays / np.linalg.norm(rays, axis=-1, keepdims=True)


def projector_planes_np(proj_K, R, T, n: int, axis: str) -> np.ndarray:
    """Per-column/row light planes (n, 4) in camera coords; see ops.triangulate."""
    Kinv = np.linalg.inv(np.asarray(proj_K, np.float64))
    R = np.asarray(R, np.float64)
    T = np.asarray(T, np.float64).reshape(3)
    center = -(R.T @ T)
    idx = np.arange(n, dtype=np.float64)
    one = np.ones_like(idx)
    zero = np.zeros_like(idx)
    if axis == "col":
        p0 = np.stack([idx, zero, one], -1)
        edge = Kinv[:, 1]
    else:
        p0 = np.stack([zero, idx, one], -1)
        edge = Kinv[:, 0]
    d0 = (p0 @ Kinv.T) @ R
    normal = np.cross(d0, (R.T @ edge)[None, :])
    normal /= np.linalg.norm(normal, axis=-1, keepdims=True)
    d = -(normal @ center)
    return np.concatenate([normal, d[:, None]], axis=-1).astype(np.float64)


def _plane_t_np(planes, rays, eps):
    """t per ray for origin + t*ray on plane n·X + d = 0 (origin = 0)."""
    n, d = planes[:, :3], planes[:, 3]
    denom = np.sum(n * rays, axis=-1)
    ok = np.abs(denom) > eps
    t = np.where(ok, -d / np.where(ok, denom, 1.0), 0.0)
    return t, ok


def _est_np(planes_all, idx, rays, eps):
    """(t, ok, inverse-variance weight) — same fusion scheme as the JAX path:
    variance = depth sensitivity to a one-index plane step (forward diff,
    backward at the last plane)."""
    n_planes = len(planes_all)
    idx = np.clip(idx, 0, n_planes - 1)
    nbr = np.where(idx + 1 < n_planes, idx + 1, idx - 1)
    t0, ok0 = _plane_t_np(planes_all[idx], rays, eps)
    t1, _ = _plane_t_np(planes_all[nbr], rays, eps)
    sens = np.abs(t1 - t0) + 1e-12
    return t0, ok0, 1.0 / (sens * sens)


def triangulate_np(col_map, row_map, mask, cam_K, proj_K, R, T,
                   proj_width=1920, proj_height=1080,
                   cfg: TriangulationConfig = TriangulationConfig()):
    """Gathered (ragged) triangulation like the reference: only valid pixels.

    Returns (points (N,3) float64, valid_flat_indices (N,)).
    """
    H, W = col_map.shape
    rays = camera_rays_np(cam_K, H, W).reshape(-1, 3)
    valid = np.flatnonzero(mask.reshape(-1))
    r = rays[valid]
    if cfg.plane_axis == "col":
        planes_all = projector_planes_np(proj_K, R, T, proj_width, "col")
        idx = np.clip(col_map.reshape(-1)[valid], 0, proj_width - 1)
        t, ok = _plane_t_np(planes_all[idx], r, cfg.denom_eps)
    elif cfg.plane_axis == "row":
        planes_all = projector_planes_np(proj_K, R, T, proj_height, "row")
        idx = np.clip(row_map.reshape(-1)[valid], 0, proj_height - 1)
        t, ok = _plane_t_np(planes_all[idx], r, cfg.denom_eps)
    elif cfg.plane_axis == "both":
        pc = projector_planes_np(proj_K, R, T, proj_width, "col")
        pr = projector_planes_np(proj_K, R, T, proj_height, "row")
        tc, sc, wc = _est_np(pc, col_map.reshape(-1)[valid], r, cfg.denom_eps)
        tr, sr, wr = _est_np(pr, row_map.reshape(-1)[valid], r, cfg.denom_eps)
        wc = wc * sc
        wr = wr * sr
        wsum = wc + wr
        ok = (sc | sr) & (wsum > 0.0)
        t = np.where(ok, (wc * tc + wr * tr) / np.where(ok, wsum, 1.0), 0.0)
    else:
        raise ValueError(f"unknown plane_axis {cfg.plane_axis!r}")
    ok &= (t > cfg.min_t) & (t < cfg.max_t)
    points = t[:, None] * r
    return points[ok], valid[ok]
