"""Synthetic structured-light scanner — the hardware simulator.

The reference has no headless test path at all (SURVEY.md §4: "There are no
tests"; its only mock is a `time.sleep(2)` turntable stub, `server/gui.py:
690-693`). This module is the new build's answer: a ray-traced simulator that
renders exactly the frame stack a phone camera would capture while the
projector plays the Gray-code sequence over a known scene. Every pipeline
stage can then be tested end-to-end against analytic ground truth — decode
maps against true projector coordinates, triangulated points against true
surface geometry, multi-view merges against the true rotated object.

Scenes are unions of spheres plus an optional background wall (so background
removal has something to remove). A turntable is simulated by rotating the
spheres about a vertical axis through a pivot point, like the real 28BYJ-48
turntable (`ESP_code.ino`).

Host-side NumPy: this is a test/data substrate, not a hot path.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import ProjectorConfig
from .oracle import camera_rays_np


@dataclasses.dataclass(frozen=True)
class Sphere:
    center: tuple  # (x, y, z) mm, camera frame at angle 0
    radius: float
    albedo: float = 0.9  # fraction of projector brightness reflected


@dataclasses.dataclass(frozen=True)
class Scene:
    spheres: tuple = (
        Sphere((0.0, 10.0, 500.0), 80.0, 0.9),
        Sphere((45.0, -55.0, 470.0), 35.0, 0.7),  # bump: breaks rotational symmetry
    )
    wall_z: float | None = 700.0
    wall_albedo: float = 0.35
    pivot: tuple = (0.0, 0.0, 500.0)  # turntable axis passes through this, along +y
    ambient: float = 4.0


def default_calibration(cam_height: int = 270, cam_width: int = 480,
                        proj: ProjectorConfig = ProjectorConfig()):
    """A plausible synthetic camera/projector stereo rig.

    Returns (cam_K, proj_K, R, T) with stereoCalibrate convention
    X_p = R X_c + T (units mm). Small camera resolutions keep tests fast;
    intrinsics scale with the requested size.
    """
    f_cam = 1.1 * cam_width
    cam_K = np.array(
        [[f_cam, 0, cam_width / 2 - 0.5],
         [0, f_cam, cam_height / 2 - 0.5],
         [0, 0, 1]], dtype=np.float64)
    f_proj = 1.2 * proj.width
    proj_K = np.array(
        [[f_proj, 0, proj.width / 2 - 0.5],
         [0, f_proj, proj.height / 2 - 0.5],
         [0, 0, 1]], dtype=np.float64)
    # Projector sits 150 mm to the camera's left, toed in ~8° about y.
    ang = np.deg2rad(8.0)
    R = np.array(
        [[np.cos(ang), 0, -np.sin(ang)],
         [0, 1, 0],
         [np.sin(ang), 0, np.cos(ang)]], dtype=np.float64)
    T = np.array([150.0, 0.0, 20.0], dtype=np.float64)
    return cam_K, proj_K, R, T


def rotated_scene(scene: Scene, angle_deg: float) -> Scene:
    """Scene after the turntable rotates by angle_deg about the pivot's y-axis."""
    th = np.deg2rad(angle_deg)
    Ry = np.array([[np.cos(th), 0, np.sin(th)],
                   [0, 1, 0],
                   [-np.sin(th), 0, np.cos(th)]], dtype=np.float64)
    pivot = np.asarray(scene.pivot)
    spheres = tuple(
        Sphere(tuple(pivot + Ry @ (np.asarray(s.center) - pivot)), s.radius, s.albedo)
        for s in scene.spheres
    )
    return dataclasses.replace(scene, spheres=spheres)


def raycast(scene: Scene, rays: np.ndarray):
    """Intersect unit rays from the origin with the scene.

    rays: (N, 3). Returns (t (N,), albedo (N,), hit_object (N,) bool,
    hit_any (N,) bool). Nearest positive hit wins; wall is a hit but not
    "object".
    """
    N = rays.shape[0]
    t_best = np.full(N, np.inf)
    albedo = np.zeros(N)
    is_object = np.zeros(N, dtype=bool)

    for s in scene.spheres:
        c = np.asarray(s.center, np.float64)
        b = rays @ c  # = t at closest approach (|ray|=1)
        disc = b * b - (c @ c - s.radius**2)
        ok = disc > 0
        sq = np.sqrt(np.where(ok, disc, 0.0))
        t0 = b - sq
        t1 = b + sq
        t = np.where(t0 > 1e-6, t0, t1)  # nearest positive root
        ok &= t > 1e-6
        closer = ok & (t < t_best)
        t_best = np.where(closer, t, t_best)
        albedo = np.where(closer, s.albedo, albedo)
        is_object = np.where(closer, True, is_object)

    if scene.wall_z is not None:
        rz = rays[:, 2]
        ok = rz > 1e-6
        t = np.where(ok, scene.wall_z / np.where(ok, rz, 1.0), np.inf)
        closer = ok & (t < t_best)
        t_best = np.where(closer, t, t_best)
        albedo = np.where(closer, scene.wall_albedo, albedo)
        is_object = np.where(closer, False, is_object)

    hit = np.isfinite(t_best)
    t_best = np.where(hit, t_best, 0.0)
    return t_best, albedo, is_object, hit


def render_scan(
    scene: Scene,
    cam_K: np.ndarray,
    proj_K: np.ndarray,
    R: np.ndarray,
    T: np.ndarray,
    cam_height: int,
    cam_width: int,
    proj: ProjectorConfig = ProjectorConfig(),
    pattern_frames: np.ndarray | None = None,
):
    """Render the full protocol-ordered capture stack for one turntable stop.

    Returns (stack (n_frames, H, W) uint8, ground_truth dict). Ground truth
    holds per-pixel true points, true projector (u, v), the object mask, and
    the hit mask — everything needed to verify decode and triangulation
    analytically.
    """
    from ..ops.patterns import pattern_stack  # lazy: pulls in jax

    if pattern_frames is None:
        pattern_frames = np.asarray(
            pattern_stack(proj.width, proj.height, proj.col_bits, proj.row_bits,
                          proj.brightness, proj.downsample))

    rays = camera_rays_np(cam_K, cam_height, cam_width).reshape(-1, 3)
    t, albedo, is_object, hit = raycast(scene, rays)
    points = t[:, None] * rays  # (N, 3), camera frame

    # Project every hit point into the projector.
    P_p = points @ R.T + T[None, :]
    z = P_p[:, 2]
    ok_z = z > 1e-6
    u = np.where(ok_z, (proj_K[0, 0] * P_p[:, 0] + proj_K[0, 2] * z)
                 / np.where(ok_z, z, 1.0), -1.0)
    v = np.where(ok_z, (proj_K[1, 1] * P_p[:, 1] + proj_K[1, 2] * z)
                 / np.where(ok_z, z, 1.0), -1.0)
    ui = np.round(u).astype(np.int64)
    vi = np.round(v).astype(np.int64)
    lit = hit & ok_z & (ui >= 0) & (ui < proj.width) & (vi >= 0) & (vi < proj.height)
    ui_c = np.clip(ui, 0, proj.width - 1)
    vi_c = np.clip(vi, 0, proj.height - 1)

    n_frames = pattern_frames.shape[0]
    stack = np.empty((n_frames, cam_height * cam_width), dtype=np.uint8)
    amb = scene.ambient
    for f in range(n_frames):
        frame = pattern_frames[f]
        proj_val = frame[vi_c, ui_c].astype(np.float64)
        val = np.where(lit, albedo * proj_val + amb, np.where(hit, amb, 0.0))
        stack[f] = np.clip(val, 0, 255).astype(np.uint8)
    stack = stack.reshape(n_frames, cam_height, cam_width)

    gt = {
        "points": points.reshape(cam_height, cam_width, 3),
        "proj_u": u.reshape(cam_height, cam_width),
        "proj_v": v.reshape(cam_height, cam_width),
        "object_mask": is_object.reshape(cam_height, cam_width),
        "hit_mask": hit.reshape(cam_height, cam_width),
        "lit_mask": lit.reshape(cam_height, cam_width),
    }
    return stack, gt


def render_turntable_scans(
    scene: Scene,
    n_stops: int,
    degrees_per_stop: float,
    cam_K, proj_K, R, T,
    cam_height: int, cam_width: int,
    proj: ProjectorConfig = ProjectorConfig(),
):
    """Render stacks for a full 360° schedule. Returns list of (stack, gt)."""
    from ..ops.patterns import pattern_stack

    frames = np.asarray(
        pattern_stack(proj.width, proj.height, proj.col_bits, proj.row_bits,
                      proj.brightness, proj.downsample))
    out = []
    for k in range(n_stops):
        sc = rotated_scene(scene, k * degrees_per_stop)
        out.append(render_scan(sc, cam_K, proj_K, R, T, cam_height, cam_width,
                               proj, pattern_frames=frames))
    return out
