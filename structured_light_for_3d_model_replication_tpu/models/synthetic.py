"""Synthetic structured-light scanner — the hardware simulator.

The reference has no headless test path at all (SURVEY.md §4: "There are no
tests"; its only mock is a `time.sleep(2)` turntable stub, `server/gui.py:
690-693`). This module is the new build's answer: a ray-traced simulator that
renders exactly the frame stack a phone camera would capture while the
projector plays the Gray-code sequence over a known scene. Every pipeline
stage can then be tested end-to-end against analytic ground truth — decode
maps against true projector coordinates, triangulated points against true
surface geometry, multi-view merges against the true rotated object.

Scenes are unions of spheres plus an optional background wall (so background
removal has something to remove). A turntable is simulated by rotating the
spheres about a vertical axis through a pivot point, like the real 28BYJ-48
turntable (`ESP_code.ino`).

Host-side NumPy: this is a test/data substrate, not a hot path.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import ProjectorConfig
from .oracle import camera_rays_np


@dataclasses.dataclass(frozen=True)
class Sphere:
    center: tuple  # (x, y, z) mm, camera frame at angle 0
    radius: float
    albedo: float = 0.9  # fraction of projector brightness reflected


@dataclasses.dataclass(frozen=True)
class Scene:
    spheres: tuple = (
        Sphere((0.0, 10.0, 500.0), 80.0, 0.9),
        Sphere((45.0, -55.0, 470.0), 35.0, 0.7),  # bump: breaks rotational symmetry
    )
    wall_z: float | None = 700.0
    wall_albedo: float = 0.35
    pivot: tuple = (0.0, 0.0, 500.0)  # turntable axis passes through this, along +y
    ambient: float = 4.0


def default_calibration(cam_height: int = 270, cam_width: int = 480,
                        proj: ProjectorConfig = ProjectorConfig()):
    """A plausible synthetic camera/projector stereo rig.

    Returns (cam_K, proj_K, R, T) with stereoCalibrate convention
    X_p = R X_c + T (units mm). Small camera resolutions keep tests fast;
    intrinsics scale with the requested size.
    """
    f_cam = 1.1 * cam_width
    cam_K = np.array(
        [[f_cam, 0, cam_width / 2 - 0.5],
         [0, f_cam, cam_height / 2 - 0.5],
         [0, 0, 1]], dtype=np.float64)
    f_proj = 1.2 * proj.width
    proj_K = np.array(
        [[f_proj, 0, proj.width / 2 - 0.5],
         [0, f_proj, proj.height / 2 - 0.5],
         [0, 0, 1]], dtype=np.float64)
    # Projector sits 150 mm to the camera's left, toed in ~8° about y.
    ang = np.deg2rad(8.0)
    R = np.array(
        [[np.cos(ang), 0, -np.sin(ang)],
         [0, 1, 0],
         [np.sin(ang), 0, np.cos(ang)]], dtype=np.float64)
    T = np.array([150.0, 0.0, 20.0], dtype=np.float64)
    return cam_K, proj_K, R, T


def rotated_scene(scene: Scene, angle_deg: float) -> Scene:
    """Scene after the turntable rotates by angle_deg about the pivot's y-axis."""
    th = np.deg2rad(angle_deg)
    Ry = np.array([[np.cos(th), 0, np.sin(th)],
                   [0, 1, 0],
                   [-np.sin(th), 0, np.cos(th)]], dtype=np.float64)
    pivot = np.asarray(scene.pivot)
    spheres = tuple(
        Sphere(tuple(pivot + Ry @ (np.asarray(s.center) - pivot)), s.radius, s.albedo)
        for s in scene.spheres
    )
    return dataclasses.replace(scene, spheres=spheres)


def raycast(scene: Scene, rays: np.ndarray):
    """Intersect unit rays from the origin with the scene.

    rays: (N, 3). Returns (t (N,), albedo (N,), hit_object (N,) bool,
    hit_any (N,) bool). Nearest positive hit wins; wall is a hit but not
    "object".
    """
    N = rays.shape[0]
    t_best = np.full(N, np.inf)
    albedo = np.zeros(N)
    is_object = np.zeros(N, dtype=bool)

    for s in scene.spheres:
        c = np.asarray(s.center, np.float64)
        b = rays @ c  # = t at closest approach (|ray|=1)
        disc = b * b - (c @ c - s.radius**2)
        ok = disc > 0
        sq = np.sqrt(np.where(ok, disc, 0.0))
        t0 = b - sq
        t1 = b + sq
        t = np.where(t0 > 1e-6, t0, t1)  # nearest positive root
        ok &= t > 1e-6
        closer = ok & (t < t_best)
        t_best = np.where(closer, t, t_best)
        albedo = np.where(closer, s.albedo, albedo)
        is_object = np.where(closer, True, is_object)

    if scene.wall_z is not None:
        rz = rays[:, 2]
        ok = rz > 1e-6
        t = np.where(ok, scene.wall_z / np.where(ok, rz, 1.0), np.inf)
        closer = ok & (t < t_best)
        t_best = np.where(closer, t, t_best)
        albedo = np.where(closer, scene.wall_albedo, albedo)
        is_object = np.where(closer, False, is_object)

    hit = np.isfinite(t_best)
    t_best = np.where(hit, t_best, 0.0)
    return t_best, albedo, is_object, hit


class FrameShader:
    """Geometry of one scene pose, precomputed once; shades ANY projector
    frame into the camera image. ``render_scan`` uses it per stop; the
    virtual hardware rig (`hw/`) uses it to answer captures of whatever the
    virtual projector currently displays — the headless phone simulator the
    reference lacks (SURVEY §4: "capture paths cannot run headless")."""

    def __init__(self, scene: Scene, cam_K, proj_K, R, T,
                 cam_height: int, cam_width: int,
                 proj: ProjectorConfig = ProjectorConfig()):
        self.cam_height, self.cam_width = cam_height, cam_width
        self.proj = proj
        rays = camera_rays_np(cam_K, cam_height, cam_width).reshape(-1, 3)
        t, albedo, is_object, hit = raycast(scene, rays)
        points = t[:, None] * rays  # (N, 3), camera frame

        # Project every hit point into the projector.
        P_p = points @ R.T + T[None, :]
        z = P_p[:, 2]
        ok_z = z > 1e-6
        u = np.where(ok_z, (proj_K[0, 0] * P_p[:, 0] + proj_K[0, 2] * z)
                     / np.where(ok_z, z, 1.0), -1.0)
        v = np.where(ok_z, (proj_K[1, 1] * P_p[:, 1] + proj_K[1, 2] * z)
                     / np.where(ok_z, z, 1.0), -1.0)
        ui = np.round(u).astype(np.int64)
        vi = np.round(v).astype(np.int64)
        lit = (hit & ok_z & (ui >= 0) & (ui < proj.width)
               & (vi >= 0) & (vi < proj.height))
        self._ui = np.clip(ui, 0, proj.width - 1)
        self._vi = np.clip(vi, 0, proj.height - 1)
        self._lit = lit
        self._hit = hit
        self._albedo = albedo
        self._ambient = scene.ambient
        self.ground_truth = {
            "points": points.reshape(cam_height, cam_width, 3),
            "proj_u": u.reshape(cam_height, cam_width),
            "proj_v": v.reshape(cam_height, cam_width),
            "object_mask": is_object.reshape(cam_height, cam_width),
            "hit_mask": hit.reshape(cam_height, cam_width),
            "lit_mask": lit.reshape(cam_height, cam_width),
        }

    def shade(self, frame: np.ndarray) -> np.ndarray:
        """(proj_h, proj_w[, 3]) frame -> (cam_h, cam_w) uint8 camera image
        (color frames shade by luminance — the synthetic camera is mono)."""
        frame = np.asarray(frame)
        if frame.ndim == 3:
            frame = frame.mean(axis=-1)
        proj_val = frame[self._vi, self._ui].astype(np.float64)
        val = np.where(self._lit, self._albedo * proj_val + self._ambient,
                       np.where(self._hit, self._ambient, 0.0))
        return np.clip(val, 0, 255).astype(np.uint8).reshape(
            self.cam_height, self.cam_width)


def render_scan(
    scene: Scene,
    cam_K: np.ndarray,
    proj_K: np.ndarray,
    R: np.ndarray,
    T: np.ndarray,
    cam_height: int,
    cam_width: int,
    proj: ProjectorConfig = ProjectorConfig(),
    pattern_frames: np.ndarray | None = None,
):
    """Render the full protocol-ordered capture stack for one turntable stop.

    Returns (stack (n_frames, H, W) uint8, ground_truth dict). Ground truth
    holds per-pixel true points, true projector (u, v), the object mask, and
    the hit mask — everything needed to verify decode and triangulation
    analytically.
    """
    from ..ops.patterns import pattern_stack_for  # lazy: pulls in jax

    if pattern_frames is None:
        pattern_frames = np.asarray(pattern_stack_for(proj))

    shader = FrameShader(scene, cam_K, proj_K, R, T, cam_height, cam_width,
                         proj)
    stack = np.stack([shader.shade(f) for f in pattern_frames])
    return stack, shader.ground_truth


def render_calibration_pose(
    board_R: np.ndarray,
    board_t: np.ndarray,
    cam_K: np.ndarray,
    proj_K: np.ndarray,
    R: np.ndarray,
    T: np.ndarray,
    cam_height: int,
    cam_width: int,
    proj: ProjectorConfig = ProjectorConfig(),
    checker_cols: int = 7,
    checker_rows: int = 7,
    square_mm: float = 35.0,
    pattern_frames: np.ndarray | None = None,
    supersample: int = 3,
):
    """Render one calibration pose: a checkerboard plane under the projector.

    The board plane carries a printed checkerboard (dark/light squares) so
    `findChessboardCorners` has real corners to detect, and reflects the
    Gray-code patterns so the projector coordinates can be decoded at those
    corners — the full substrate of the reference's calibration capture
    (`server/sl_system.py:114-182`).

    board_R/board_t map board coords (x, y, 0) into the camera frame. Inner
    corners sit at (i*square, j*square), i in [0, cols), j in [0, rows).
    Returns (stack uint8, gt dict with corner camera pixels + projector uv).
    """
    from ..ops.patterns import pattern_stack_for

    if pattern_frames is None:
        pattern_frames = np.asarray(pattern_stack_for(proj))

    sq = square_mm
    # Supersampled render: a real sensor pixel integrates over its footprint;
    # point-sampling a binary checker gives aliased edges that cap
    # cornerSubPix at ~0.5 px. Render at s x resolution and box-average.
    s = max(1, int(supersample))
    K_ss = cam_K.copy().astype(np.float64)
    K_ss[:2, :] *= s
    K_ss[0, 2] += (s - 1) / 2.0
    K_ss[1, 2] += (s - 1) / 2.0
    hs, ws = cam_height * s, cam_width * s
    rays = camera_rays_np(K_ss, hs, ws).reshape(-1, 3)
    n = board_R[:, 2]  # board plane normal in camera frame
    denom = rays @ n
    ok = np.abs(denom) > 1e-9
    t_hit = np.where(ok, (board_t @ n) / np.where(ok, denom, 1.0), np.inf)
    ok &= t_hit > 1e-6
    points = t_hit[:, None] * rays
    local = (points - board_t[None, :]) @ board_R  # board coords
    bx, by = local[:, 0], local[:, 1]

    # Checker field spans one square beyond the inner-corner grid on each
    # side; a 1.5-square white margin rings it (printed board on white card).
    in_checker = (ok & (bx >= -sq) & (bx <= checker_cols * sq)
                  & (by >= -sq) & (by <= checker_rows * sq))
    in_margin = (ok & ~in_checker
                 & (bx >= -2.5 * sq) & (bx <= (checker_cols + 1.5) * sq)
                 & (by >= -2.5 * sq) & (by <= (checker_rows + 1.5) * sq))
    parity = (np.floor(bx / sq).astype(np.int64)
              + np.floor(by / sq).astype(np.int64)) % 2
    albedo = np.where(in_checker, np.where(parity == 0, 0.08, 0.85),
                      np.where(in_margin, 0.92, 0.0))
    hit = in_checker | in_margin

    # Projector coordinates of every board point (same math as render_scan).
    P_p = points @ R.T + T[None, :]
    z = P_p[:, 2]
    ok_z = z > 1e-6
    u = np.where(ok_z, (proj_K[0, 0] * P_p[:, 0] + proj_K[0, 2] * z)
                 / np.where(ok_z, z, 1.0), -1.0)
    v = np.where(ok_z, (proj_K[1, 1] * P_p[:, 1] + proj_K[1, 2] * z)
                 / np.where(ok_z, z, 1.0), -1.0)
    ui = np.clip(np.round(u).astype(np.int64), 0, proj.width - 1)
    vi = np.clip(np.round(v).astype(np.int64), 0, proj.height - 1)
    lit = (hit & ok_z & (u >= 0) & (u < proj.width)
           & (v >= 0) & (v < proj.height))

    # Room light illuminates the printed board everywhere (so the checker
    # pattern is detectable even outside the projector frustum, as in a real
    # calibration room); the projector adds pattern light on top.
    room = 60.0
    sensor_floor = 4.0
    n_frames = pattern_frames.shape[0]
    stack = np.empty((n_frames, cam_height, cam_width), dtype=np.uint8)
    for f in range(n_frames):
        proj_val = np.where(lit, pattern_frames[f][vi, ui], 0.0)
        val = np.where(hit, albedo * (proj_val + room) + sensor_floor, 0.0)
        img = val.reshape(hs, ws)
        if s > 1:  # box-filter downsample = per-pixel integration
            img = img.reshape(cam_height, s, cam_width, s).mean(axis=(1, 3))
        stack[f] = np.clip(img, 0, 255).astype(np.uint8)

    # Ground truth for the inner corners.
    ii, jj = np.meshgrid(np.arange(checker_cols), np.arange(checker_rows),
                         indexing="ij")
    corners_board = np.stack(
        [ii.ravel() * sq, jj.ravel() * sq, np.zeros(ii.size)], axis=-1)
    corners_cam3 = corners_board @ board_R.T + board_t[None, :]
    cu = cam_K[0, 0] * corners_cam3[:, 0] / corners_cam3[:, 2] + cam_K[0, 2]
    cv_ = cam_K[1, 1] * corners_cam3[:, 1] / corners_cam3[:, 2] + cam_K[1, 2]
    corners_proj3 = corners_cam3 @ R.T + T[None, :]
    pu = proj_K[0, 0] * corners_proj3[:, 0] / corners_proj3[:, 2] + proj_K[0, 2]
    pv = proj_K[1, 1] * corners_proj3[:, 1] / corners_proj3[:, 2] + proj_K[1, 2]

    gt = {
        "corner_cam_px": np.stack([cu, cv_], axis=-1),
        "corner_proj_px": np.stack([pu, pv], axis=-1),
        "corner_points": corners_cam3,
    }
    return stack, gt


def calibration_pose_set(n_poses: int = 5, distance: float = 900.0):
    """(board_R, board_t) list: tilted/rotated board poses for calibration.

    Placement keeps every inner corner inside the (narrower) projector
    frustum of `default_calibration`'s rig so the corner decode is valid;
    the board's white margin only needs room light, not projector light.
    """
    poses = []
    rng = np.random.default_rng(7)
    for k in range(n_poses):
        tilt_x = np.deg2rad(rng.uniform(-22, 22))
        tilt_y = np.deg2rad(rng.uniform(-22, 22))
        roll = np.deg2rad(rng.uniform(-12, 12))
        Rx = np.array([[1, 0, 0],
                       [0, np.cos(tilt_x), -np.sin(tilt_x)],
                       [0, np.sin(tilt_x), np.cos(tilt_x)]])
        Ry = np.array([[np.cos(tilt_y), 0, np.sin(tilt_y)],
                       [0, 1, 0],
                       [-np.sin(tilt_y), 0, np.cos(tilt_y)]])
        Rz = np.array([[np.cos(roll), -np.sin(roll), 0],
                       [np.sin(roll), np.cos(roll), 0],
                       [0, 0, 1]])
        board_R = Rx @ Ry @ Rz
        board_t = np.array([
            rng.uniform(-120, -60), rng.uniform(-150, -100),
            distance + rng.uniform(-60, 60)])
        poses.append((board_R, board_t))
    return poses


def render_turntable_scans(
    scene: Scene,
    n_stops: int,
    degrees_per_stop: float,
    cam_K, proj_K, R, T,
    cam_height: int, cam_width: int,
    proj: ProjectorConfig = ProjectorConfig(),
):
    """Render stacks for a full 360° schedule. Returns list of (stack, gt)."""
    from ..ops.patterns import pattern_stack_for

    frames = np.asarray(pattern_stack_for(proj))
    out = []
    for k in range(n_stops):
        sc = rotated_scene(scene, k * degrees_per_stop)
        out.append(render_scan(sc, cam_K, proj_K, R, T, cam_height, cam_width,
                               proj, pattern_frames=frames))
    return out
