"""Tkinter GUI orchestrator — the interactive six-tab workstation.

Feature parity with the reference's `ScannerGUI` (`server/gui.py:15-774`,
6 tabs: connection, calibration, scanning, cloud generation, processing,
meshing) rebuilt over this framework's headless layers: the GUI owns a
:class:`~.scanner.Scanner`, a :class:`~.hw.command_server.CommandServer` and
a :class:`~.hw.turntable.SerialTurntable`/:class:`SimulatedTurntable`, and
every button dispatches onto a daemon worker thread with results marshalled
back via ``root.after`` — the reference's threading discipline
(`server/gui.py:475,541,620,641,684,773`, marshalling `:495-498`).

Differences by design:

* all compute buttons call the TPU pipeline entry points (the reference
  calls NumPy/Open3D inline);
* the auto-scan tab supports RESUME (skips complete stops) and a
  "virtual rig" toggle — the reference's only simulation is a sleep stub
  (`server/gui.py:690-693,764-765`);
* progress/elapsed/remaining timing mirrors `server/gui.py:727-731`.

Headless-safe: importing this module must not require a display; the Tk
root is only created inside :func:`main` / :class:`ScannerGUI`.
"""

from __future__ import annotations

import os
import queue
import threading
import traceback

from .config import ProjectorConfig, TurntableConfig
from .io.layout import SessionLayout
from .utils.log import get_logger

log = get_logger(__name__)


class WorkerMixin:
    """One daemon worker per action + an `after`-pumped result queue."""

    POLL_MS = 100

    def _init_worker(self, root):
        self._root = root
        self._q: queue.Queue = queue.Queue()
        self._pump()

    def _pump(self):
        try:
            while True:
                fn, args = self._q.get_nowait()
                fn(*args)
        except queue.Empty:
            pass
        self._root.after(self.POLL_MS, self._pump)

    def call_ui(self, fn, *args):
        """Queue a callable for the Tk thread (root.after marshalling,
        `server/gui.py:495-498`)."""
        self._q.put((fn, args))

    def run_bg(self, name: str, work, on_done=None, on_error=None):
        def runner():
            try:
                result = work()
            except Exception as e:  # surface, never kill the UI
                log.error("%s failed: %s\n%s", name, e,
                          traceback.format_exc())
                if on_error is not None:
                    self.call_ui(on_error, e)
                return
            if on_done is not None:
                self.call_ui(on_done, result)

        threading.Thread(target=runner, daemon=True, name=name).start()


def selected_pose_dirs(all_pose_dirs, selection: dict) -> list:
    """Pose-culling filter (the reference's pose-selection step,
    `server/gui.py:500-523`): keep a pose directory iff its basename is
    checked. With no analysis yet (empty selection) every pose is used —
    the reference's 'all' answer."""
    if not selection:
        return list(all_pose_dirs)
    return [d for d in all_pose_dirs
            if selection.get(os.path.basename(d), False)]


class ScannerGUI(WorkerMixin):
    """Six-tab Tk application. Instantiate with a ``tk.Tk()`` root."""

    def __init__(self, root, session_base: str = "."):
        import tkinter as tk
        from tkinter import ttk

        self.tk = tk
        self.ttk = ttk
        self.root = root
        root.title("Structured Light 3D Scanner (TPU)")
        self._init_worker(root)

        self.layout = SessionLayout.today(session_base).ensure()
        self.proj_cfg = ProjectorConfig()
        self.tt_cfg = TurntableConfig()

        self.server = None
        self.turntable = None
        self.scanner = None
        self._virtual_rig = None

        # -- runtime parameters (the reference's ~30 Tk vars,
        # `server/gui.py:27-83`) --
        self.var_port = tk.IntVar(value=5000)
        self.var_serial = tk.StringVar(value="/dev/ttyUSB0")
        self.var_virtual = tk.BooleanVar(value=False)
        self.var_scan_name = tk.StringVar(value="scan")
        self.var_turns = tk.IntVar(value=self.tt_cfg.turns)
        self.var_degrees = tk.DoubleVar(value=self.tt_cfg.degrees_per_turn)
        self.var_resume = tk.BooleanVar(value=True)
        self.var_pose = tk.IntVar(value=1)
        self.var_calib_file = tk.StringVar(
            value=self.layout.calib_mat())
        self.var_scan_dir = tk.StringVar(value="")
        self.var_cloud_out = tk.StringVar(value="cloud.ply")
        self.var_thresholds = tk.StringVar(value="adaptive")
        self.var_merge_dir = tk.StringVar(value="")
        self.var_merge_out = tk.StringVar(value="merged.ply")
        self.var_merge_method = tk.StringVar(value="posegraph")
        self.var_voxel = tk.DoubleVar(value=0.02)
        self.var_mesh_in = tk.StringVar(value="merged.ply")
        self.var_mesh_out = tk.StringVar(value="model.stl")
        self.var_mesh_depth = tk.IntVar(value=8)  # ≤8 dense; 9-16 sparse solver
        self.var_mesh_trim = tk.DoubleVar(value=0.0)
        self.var_mesh_orient = tk.StringVar(value="radial")
        self.var_status = tk.StringVar(value="disconnected")

        nb = ttk.Notebook(root)
        nb.pack(fill="both", expand=True)
        self._build_connection_tab(nb)
        self._build_calibration_tab(nb)
        self._build_scan_tab(nb)
        self._build_cloud_tab(nb)
        self._build_process_tab(nb)
        self._build_mesh_tab(nb)

        self.log_box = tk.Text(root, height=8, state="disabled")
        self.log_box.pack(fill="x")

    # ------------------------------------------------------------------
    # UI plumbing
    # ------------------------------------------------------------------

    def log_line(self, msg: str):
        log.info("%s", msg)
        self.log_box.configure(state="normal")
        self.log_box.insert("end", msg + "\n")
        self.log_box.see("end")
        self.log_box.configure(state="disabled")

    def _row(self, parent, label, widget_fn):
        f = self.ttk.Frame(parent)
        f.pack(fill="x", padx=8, pady=2)
        self.ttk.Label(f, text=label, width=22).pack(side="left")
        w = widget_fn(f)
        w.pack(side="left", fill="x", expand=True)
        return w

    def _entry(self, parent, label, var):
        return self._row(parent, label,
                         lambda f: self.ttk.Entry(f, textvariable=var))

    def _button(self, parent, text, cmd):
        b = self.ttk.Button(parent, text=text, command=cmd)
        b.pack(fill="x", padx=8, pady=3)
        return b

    def _tab(self, nb, title):
        frame = self.ttk.Frame(nb)
        nb.add(frame, text=title)
        return frame

    # ------------------------------------------------------------------
    # Tab 1: connection (`server/gui.py` connection tab; `server/main.py`)
    # ------------------------------------------------------------------

    def _build_connection_tab(self, nb):
        t = self._tab(nb, "Connection")
        self._entry(t, "HTTP port", self.var_port)
        self._entry(t, "Turntable serial", self.var_serial)
        self.ttk.Checkbutton(
            t, text="Virtual rig (ray-traced simulator)",
            variable=self.var_virtual).pack(anchor="w", padx=8)
        self._button(t, "Start capture stack", self.do_connect)
        self._button(t, "Stop", self.do_disconnect)
        self._row(t, "Status",
                  lambda f: self.ttk.Label(f, textvariable=self.var_status))

    def do_connect(self):
        def work():
            return self._build_scanner()

        def done(scanner):
            self.scanner = scanner
            self.var_status.set("ready (virtual)" if self.var_virtual.get()
                                else "ready")
            self.log_line("rig connected")

        self.run_bg("connect", work, done,
                    lambda e: self.var_status.set(f"error: {e}"))

    def _build_scanner(self):
        from .scanner import Scanner

        if self.var_virtual.get():
            from .hw.rig import VirtualRig

            rig = VirtualRig()
            self._virtual_rig = rig
            return Scanner(rig.camera, rig.projector, rig.turntable,
                           proj=rig.proj, layout=self.layout)

        from .hw.camera import PullCamera
        from .hw.command_server import CommandServer
        from .hw.projector import WindowProjector

        self.server = CommandServer(port=self.var_port.get()).start()
        camera = PullCamera(self.server.channel)
        projector = WindowProjector(self.proj_cfg)
        turntable = None
        port = self.var_serial.get().strip()
        if port:
            try:
                from .hw.turntable import SerialTurntable

                turntable = SerialTurntable(port, baud=self.tt_cfg.baud)
            except Exception as e:
                # The reference offers "Continue anyway (Simulation)?"
                # (`server/gui.py:690-693`); headless default: warn + no table.
                self.call_ui(self.log_line,
                             f"turntable unavailable ({e}); continuing "
                             f"without rotation")
        return Scanner(camera, projector, turntable, proj=self.proj_cfg,
                       layout=self.layout)

    def do_disconnect(self):
        if self.server is not None:
            self.server.stop()
            self.server = None
        self.scanner = None
        self.var_status.set("disconnected")
        self.log_line("disconnected")

    # ------------------------------------------------------------------
    # Tab 2: calibration (`server/gui.py:470-523`)
    # ------------------------------------------------------------------

    def _build_calibration_tab(self, nb):
        t = self._tab(nb, "Calibration")
        self._entry(t, "Pose index", self.var_pose)
        self._button(t, "Capture pose", self.do_calib_capture)
        self._button(t, "Analyze poses (reprojection)", self.do_calib_analyze)
        # Pose-culling list (the reference's prompt_pose_selection dialog,
        # `server/gui.py:500-523`): Analyze fills one checkbox per pose
        # with its reprojection errors; Final calibrates on the CHECKED
        # subset only (all poses until an analysis has run).
        self._row(t, "Poses (after analyze)",
                  lambda f: self.ttk.Label(f, text="all (run Analyze to "
                                                   "cull)"))
        self.pose_list_frame = self.ttk.Frame(t)
        self.pose_list_frame.pack(fill="x", padx=30)
        self.pose_checks: dict = {}
        self._button(t, "Final stereo calibration (selected poses)",
                     self.do_calib_final)
        self._entry(t, "Calibration file", self.var_calib_file)

    def _need_scanner(self):
        if self.scanner is None:
            self.log_line("connect a rig first (Connection tab)")
            return True
        return False

    def do_calib_capture(self):
        if self._need_scanner():
            return
        pose = self.var_pose.get()
        self.run_bg(
            "calib-capture",
            lambda: self.scanner.capture_calibration_pose(pose),
            lambda out: (self.log_line(f"pose {pose} captured -> {out}"),
                         self.var_pose.set(pose + 1)))

    def do_calib_analyze(self):
        from . import calibration

        calib_dir = self.layout.calib_dir()

        def work():
            return calibration.analyze_calibration(calib_dir)

        def done(res):
            errors, _poses = res
            self.log_line("per-pose reprojection (px): " + ", ".join(
                f"{p}: cam={ce:.2f} proj={pe:.2f}"
                for p, (ce, pe) in errors.items()))
            self._populate_pose_checks(errors)

        self.run_bg("calib-analyze", work, done,
                    on_error=lambda e: self.log_line(f"analyze failed: {e}"))

    def _populate_pose_checks(self, errors):
        """Rebuild the pose-culling checkboxes from an analysis result
        ({pose: (cam_err, proj_err)}); everything starts checked, like the
        reference's 'all' default (`server/gui.py:514-515`)."""
        for child in self.pose_list_frame.winfo_children():
            child.destroy()
        self.pose_checks = {}
        for pose, (ce, pe) in errors.items():
            var = self.tk.BooleanVar(value=True)
            self.ttk.Checkbutton(
                self.pose_list_frame,
                text=f"{pose}   cam={ce:.2f}px  proj={pe:.2f}px",
                variable=var).pack(anchor="w")
            self.pose_checks[pose] = var

    def do_calib_final(self):
        from . import calibration

        out = self.var_calib_file.get()
        selection = {p: bool(v.get()) for p, v in self.pose_checks.items()}
        pose_dirs = selected_pose_dirs(self.layout.pose_dirs(), selection)
        if len(pose_dirs) < 3:
            self.log_line(f"need >= 3 selected poses ({len(pose_dirs)} "
                          f"checked)")
            return

        def work():
            return calibration.calibrate_final(pose_dirs, out)

        self.run_bg("calib-final", work,
                    lambda res: self.log_line(
                        f"calibration saved -> {out} "
                        f"({len(pose_dirs)} poses, "
                        f"stereo RMS {res[1].rms:.3f})"))

    # ------------------------------------------------------------------
    # Tab 3: scanning (`server/gui.py:686-773`)
    # ------------------------------------------------------------------

    def _build_scan_tab(self, nb):
        t = self._tab(nb, "Scan")
        self._entry(t, "Scan name", self.var_scan_name)
        self._button(t, "Capture single scan", self.do_single_scan)
        self._entry(t, "Turns", self.var_turns)
        self._entry(t, "Degrees per turn", self.var_degrees)
        self.ttk.Checkbutton(t, text="Resume incomplete session",
                             variable=self.var_resume).pack(anchor="w",
                                                            padx=8)
        self._button(t, "START AUTO SCAN", self.do_auto_scan)

    def do_single_scan(self):
        if self._need_scanner():
            return
        name = self.var_scan_name.get()
        self.run_bg("scan", lambda: self.scanner.capture_scan(name),
                    lambda out: self.log_line(f"scan captured -> {out}"))

    def do_auto_scan(self):
        if self._need_scanner():
            return
        name = self.var_scan_name.get()
        turns, degs = self.var_turns.get(), self.var_degrees.get()
        resume = self.var_resume.get()

        def progress(p):
            self.call_ui(self.log_line,
                         f"stop {p.stop}/{p.total_stops} "
                         f"elapsed {p.elapsed_s:.0f}s "
                         f"avg {p.avg_stop_s:.1f}s "
                         f"remaining ~{p.remaining_s:.0f}s")

        self.run_bg(
            "auto-scan",
            lambda: self.scanner.auto_scan_360(
                name, degrees_per_turn=degs, turns=turns, resume=resume,
                on_progress=progress),
            lambda stops: self.log_line(f"auto scan done: {len(stops)} "
                                        f"stops"))

    # ------------------------------------------------------------------
    # Tab 4: cloud generation (`server/gui.py:549-567`, batch `:600-615`)
    # ------------------------------------------------------------------

    def _build_cloud_tab(self, nb):
        t = self._tab(nb, "Cloud")
        self._entry(t, "Scan folder (or batch root)", self.var_scan_dir)
        self._entry(t, "Calibration .mat", self.var_calib_file)
        self._entry(t, "Output .ply / dir", self.var_cloud_out)
        self._row(t, "Thresholds", lambda f: self.ttk.Combobox(
            f, textvariable=self.var_thresholds,
            values=("adaptive", "fixed"), state="readonly"))
        self._button(t, "Generate point cloud(s)", self.do_cloud_gen)
        self._button(t, "Preview cloud (PNG)",
                     lambda: self.do_preview(self.var_cloud_out.get))

    def do_cloud_gen(self):
        from .cli import process_cloud

        argv = ["-i", self.var_scan_dir.get(),
                "-c", self.var_calib_file.get(),
                "-o", self.var_cloud_out.get(),
                "--thresholds", self.var_thresholds.get()]
        self.run_bg("cloud-gen", lambda: process_cloud.main(argv),
                    lambda rc: self.log_line(
                        f"cloud generation {'done' if rc == 0 else 'failed'}"
                        f" -> {self.var_cloud_out.get()}"))

    # ------------------------------------------------------------------
    # Tab 5: processing/merge (`server/gui.py:620-641`)
    # ------------------------------------------------------------------

    def _build_process_tab(self, nb):
        t = self._tab(nb, "Process")
        self._entry(t, "Cloud folder", self.var_merge_dir)
        self._entry(t, "Merged output", self.var_merge_out)
        self._row(t, "Method", lambda f: self.ttk.Combobox(
            f, textvariable=self.var_merge_method,
            values=("posegraph", "sequential"), state="readonly"))
        self._entry(t, "Voxel size", self.var_voxel)
        self._button(t, "Merge 360 point clouds", self.do_merge)
        self._button(t, "Remove background (plane)", self.do_remove_bg)
        self._button(t, "Remove outliers (SOR)", self.do_remove_outliers)
        self._button(t, "Preview merged (PNG)",
                     lambda: self.do_preview(self.var_merge_out.get))
        self._button(t, "Preview outliers (PNG)",
                     lambda: self.do_preview(self.var_merge_out.get,
                                             mode="outliers"))
        self._button(t, "Preview plane split (PNG)",
                     lambda: self.do_preview(self.var_merge_out.get,
                                             mode="plane"))

    def do_merge(self):
        from .models import merge

        folder, out = self.var_merge_dir.get(), self.var_merge_out.get()
        params = merge.MergeParams(voxel_size=self.var_voxel.get())
        method = self.var_merge_method.get()

        self.run_bg(
            "merge",
            lambda: merge.merge_360_files(folder, out, params=params,
                                          method=method),
            lambda merged: self.log_line(
                f"merged {folder} -> {out} ({len(merged)} pts)"))

    def _cleanup(self, fn, tag):
        from .io import ply as ply_io

        src = self.var_merge_out.get()

        def work():
            cloud = ply_io.read_ply(src)
            cleaned = fn(cloud)
            ply_io.write_ply(src, cleaned)
            return len(cloud), len(cleaned)

        self.run_bg(tag, work,
                    lambda r: self.log_line(f"{tag}: {r[0]} -> {r[1]} pts "
                                            f"({src})"))

    def do_remove_bg(self):
        from .models import merge

        self._cleanup(merge.remove_background, "remove-background")

    def do_remove_outliers(self):
        from .models import merge

        self._cleanup(merge.remove_outliers, "remove-outliers")

    def do_preview(self, path_getter, mode: str | None = None):
        """Render a .ply/.stl to PNG (``cli view``) and pop it up in a
        Toplevel — the offline twin of the reference's Open3D viewer
        buttons (`Old/New360.py:72`, `Old/StatisticalOutlierRemoval.py:66`).
        Tk ≥ 8.6 reads PNG natively; headless use still gets the file."""
        src = path_getter() if callable(path_getter) else path_getter
        if not src:
            self.log_line("preview: set an output path first")
            return
        png = os.path.splitext(src)[0] + (f"_{mode}" if mode else "") + ".png"

        def work():
            from .cli import view as view_cli

            argv = [src, "-o", png] + ([f"--{mode}"] if mode else [])
            rc = view_cli.main(argv)
            if rc != 0:
                raise RuntimeError(f"view exited {rc}")
            return png

        def done(path):
            self.log_line(f"preview -> {path}")
            try:
                top = self.tk.Toplevel(self.root)
                top.title(path)
                photo = self.tk.PhotoImage(file=path)
                label = self.ttk.Label(top, image=photo)
                label.image = photo  # keep a ref: Tk GCs otherwise
                label.pack()
            except Exception as e:  # headless / pre-8.6 Tk: file still wrote
                self.log_line(f"preview window unavailable ({e}); "
                              f"open {path} manually")

        self.run_bg("preview", work, done,
                    on_error=lambda e: self.log_line(f"preview failed: {e}"))

    # ------------------------------------------------------------------
    # Tab 6: meshing (`server/gui.py:643-684`)
    # ------------------------------------------------------------------

    def _build_mesh_tab(self, nb):
        t = self._tab(nb, "Mesh")
        self._entry(t, "Input cloud", self.var_mesh_in)
        self._entry(t, "Output STL", self.var_mesh_out)
        self._entry(t, "Poisson depth", self.var_mesh_depth)
        self._entry(t, "Density trim quantile", self.var_mesh_trim)
        self._row(t, "Normal orientation", lambda f: self.ttk.Combobox(
            f, textvariable=self.var_mesh_orient,
            values=("radial", "tangent"), state="readonly"))
        self._button(t, "Run 360 meshing", self.do_mesh)
        self._button(t, "Preview mesh (PNG)",
                     lambda: self.do_preview(self.var_mesh_out.get))

    def do_mesh(self):
        from .io import ply as ply_io
        from .models import meshing

        src, out = self.var_mesh_in.get(), self.var_mesh_out.get()
        depth = self.var_mesh_depth.get()
        trim = self.var_mesh_trim.get()
        orient = self.var_mesh_orient.get()

        def work():
            cloud = ply_io.read_ply(src)
            return meshing.mesh_360(cloud, out, depth=depth,
                                    quantile_trim=trim,
                                    orientation_mode=orient)

        self.run_bg("mesh", work,
                    lambda mesh: self.log_line(
                        f"meshed -> {out} ({len(mesh.vertices)} verts, "
                        f"{len(mesh.faces)} faces)"))


def main() -> int:
    import tkinter as tk

    root = tk.Tk()
    ScannerGUI(root, session_base=os.environ.get("SL_SESSION_BASE", "."))
    root.mainloop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
