"""SplatScene: seed Gaussians on the TSDF iso-shell, render, save/load.

Seeding is ONE jitted compaction pass over the volume's active bricks
(`ops/tsdf.py` layout): voxels inside the truncation band
(|tsdf| < ``iso_band``, observed) are the shell candidates; a halo
central-difference over face-neighbor bricks gives each its SDF
gradient; the stratified compaction (`ops/pointcloud.stratified_
indices` — the same machinery the streaming model buffer uses) picks
``capacity`` of them at static shape. Each splat lands ON the
iso-surface (voxel center − sdf·∇̂, the projective snap), its disc
frame comes from the gradient (outward normal = −∇̂), its DC color from
the volume's fused RGB — so a scene is renderable the moment it is
seeded, before any appearance fitting.

Every seeded array has ``capacity`` rows + a valid mask; the splat
count never appears in a shape (the `stream/` static-shape rule), so
one seed program serves a growing volume and one render program per
resolution serves every view.
"""

from __future__ import annotations

import functools
import io as _io
import zlib
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ..fusion.volume import TSDFVolume
from ..ops import pointcloud
from ..ops import splat_render as sr
from ..ops import tsdf as tsdf_ops
from ..utils.log import get_logger

log = get_logger(__name__)

#: npz schema stamp for save/load (bump on layout change).
_SCENE_VERSION = 1


class SplatParams(NamedTuple):
    """Static seeding/appearance knobs (hashable — they key the seed
    program exactly like ``TSDFParams`` keys integration)."""

    capacity: int = 8192        # splat slots (static; valid mask inside)
    iso_band: float = 0.5       # |tsdf| (trunc units) seeding band
    scale_mult: float = 1.6     # tangent σ = mult × survivor spacing
    normal_scale: float = 0.35  # normal-axis σ / tangent σ (disc shape)
    opacity_init: float = 2.5   # opacity logit at seed time (α ≈ 0.92)
    min_weight: float = 0.0     # observation mask threshold


@functools.lru_cache(maxsize=None)
def _seed_fn(tparams: tsdf_ops.TSDFParams, sparams: SplatParams):
    """Volume state → splat arrays, one launch, shapes fixed by
    (brick cap, splat capacity)."""
    cap_b = int(tparams.max_bricks)
    scap = sparams.capacity
    trunc = jnp.float32(tparams.trunc_voxels)

    def halo_grad(t3, nbr):
        """Central-difference gradient with face-neighbor halos; absent
        neighbors replicate the own edge (zero gradient across the
        boundary — never an invented crossing)."""
        pad = jnp.pad(t3, ((0, 0), (1, 1), (1, 1), (1, 1)), mode="edge")
        ext = jnp.concatenate([t3, jnp.zeros((1, 8, 8, 8), t3.dtype)])
        have = nbr < cap_b
        idx = jnp.minimum(nbr, cap_b)
        # dirs6 order of tsdf._neighbor_fn: +x −x +y −y +z −z.
        planes = (
            (0, ext[idx[:, 0], 0, :, :], (slice(None), 9,
                                          slice(1, 9), slice(1, 9))),
            (1, ext[idx[:, 1], 7, :, :], (slice(None), 0,
                                          slice(1, 9), slice(1, 9))),
            (2, ext[idx[:, 2], :, 0, :], (slice(None), slice(1, 9), 9,
                                          slice(1, 9))),
            (3, ext[idx[:, 3], :, 7, :], (slice(None), slice(1, 9), 0,
                                          slice(1, 9))),
            (4, ext[idx[:, 4], :, :, 0], (slice(None), slice(1, 9),
                                          slice(1, 9), 9)),
            (5, ext[idx[:, 5], :, :, 7], (slice(None), slice(1, 9),
                                          slice(1, 9), 0)),
        )
        for d, plane, sl in planes:
            pad = pad.at[sl].set(jnp.where(have[:, d][:, None, None],
                                           plane, pad[sl]))
        gx = 0.5 * (pad[:, 2:, 1:-1, 1:-1] - pad[:, :-2, 1:-1, 1:-1])
        gy = 0.5 * (pad[:, 1:-1, 2:, 1:-1] - pad[:, 1:-1, :-2, 1:-1])
        gz = 0.5 * (pad[:, 1:-1, 1:-1, 2:] - pad[:, 1:-1, 1:-1, :-2])
        return gx, gy, gz

    def run(tsdf, weight, rgb, coords, nbr, block_valid, origin, voxel):
        t3 = tsdf.reshape(cap_b, 8, 8, 8)
        gx, gy, gz = halo_grad(t3, nbr)
        grad = jnp.stack([gx, gy, gz], axis=-1).reshape(cap_b, 512, 3)
        gnorm = jnp.linalg.norm(grad, axis=-1)
        observed = weight > sparams.min_weight
        near = (jnp.abs(tsdf) < sparams.iso_band) & observed \
            & (gnorm > 1e-6) & block_valid[:, None]

        flat_mask = near.reshape(-1)
        n_near = jnp.sum(flat_mask.astype(jnp.int32))
        idx, v = pointcloud.stratified_indices(flat_mask, scap)
        bk = idx // 512
        intra = idx % 512
        vox = (coords[bk] * 8
               + jnp.stack([intra // 64, (intra // 8) % 8, intra % 8],
                           axis=-1))
        center = (vox.astype(jnp.float32) + 0.5) * voxel + origin[None, :]
        g = grad.reshape(-1, 3)[idx]
        ghat = g / jnp.maximum(jnp.linalg.norm(g, axis=-1, keepdims=True),
                               1e-9)
        sdf_w = tsdf.reshape(-1)[idx] * trunc * voxel
        means = center - sdf_w[:, None] * ghat       # snap onto the shell
        normals = -ghat                              # outward (+ inside)
        # Tangent σ from the survivor spacing: stratified thinning keeps
        # every band voxel until capacity, then spreads them — area per
        # splat grows by the thinning ratio, σ by its square root.
        thin = jnp.sqrt(jnp.maximum(
            n_near.astype(jnp.float32) / float(scap), 1.0))
        s_t = jnp.log(sparams.scale_mult * voxel * thin)
        s_n = jnp.log(sparams.scale_mult * sparams.normal_scale * voxel
                      * thin)
        log_scales = jnp.broadcast_to(
            jnp.stack([s_t, s_t, s_n]), (scap, 3)).astype(jnp.float32)
        sh = jnp.zeros((scap, 4, 3), jnp.float32)
        sh = sh.at[:, 0, :].set(rgb.reshape(-1, 3)[idx] / 255.0)
        opacity = jnp.full((scap,), sparams.opacity_init, jnp.float32)
        means = jnp.where(v[:, None], means, 0.0)
        normals = jnp.where(v[:, None], normals,
                            jnp.asarray([0.0, 0.0, 1.0], jnp.float32))
        return means, normals, log_scales, sh, opacity, v, n_near

    return jax.jit(run)


class SplatScene:
    """One renderable splat set: device arrays + world framing.

    ``means``/``normals`` are anchored (geometry belongs to the TSDF);
    ``colors_sh``/``opacity``/``log_scales`` are the appearance state
    `splat/fit.py` optimizes. ``bbox`` frames the orbit camera so a
    scene renders without its source volume."""

    def __init__(self, params: SplatParams, means, normals, log_scales,
                 colors_sh, opacity, valid, bbox=None, voxel_size=0.0):
        self.params = params
        self.means = jnp.asarray(means, jnp.float32)
        self.normals = jnp.asarray(normals, jnp.float32)
        self.log_scales = jnp.asarray(log_scales, jnp.float32)
        self.colors_sh = jnp.asarray(colors_sh, jnp.float32)
        self.opacity = jnp.asarray(opacity, jnp.float32)
        self.valid = jnp.asarray(valid, bool)
        self.voxel_size = float(voxel_size)
        if bbox is None:
            v = np.asarray(self.valid)
            pts = np.asarray(self.means)[v]
            bbox = (pts.min(axis=0), pts.max(axis=0)) if pts.shape[0] \
                else (np.zeros(3, np.float32), np.ones(3, np.float32))
        self.bbox = (np.asarray(bbox[0], np.float32),
                     np.asarray(bbox[1], np.float32))
        self.fit_stats: dict = {}

    @property
    def n_splats(self) -> int:
        return int(jnp.sum(self.valid.astype(jnp.int32)))

    @property
    def capacity(self) -> int:
        return int(self.means.shape[0])

    # -- rendering ---------------------------------------------------------

    def camera(self, azim: float, elev: float, width: int, height: int,
               zoom: float = 2.1):
        return sr.orbit_camera(self.bbox[0], self.bbox[1], azim, elev,
                               width, height, zoom=zoom)

    def render_camera(self, camera, cfg: sr.RenderConfig,
                      use_pallas: bool | None = None):
        """((H, W, 3) float 0–1, alpha) from an explicit camera tuple."""
        return sr.render(self.means, self.normals, self.log_scales,
                         self.colors_sh, self.opacity, self.valid,
                         camera, cfg, use_pallas=use_pallas)

    def render(self, azim: float = 30.0, elev: float = 20.0,
               width: int = 384, height: int = 288, zoom: float = 2.1,
               use_pallas: bool | None = None) -> np.ndarray:
        """Novel orbit view → host (H, W, 3) uint8. Angles/zoom are
        traced operands: a sweep reuses one program per (width,
        height)."""
        cfg = sr.RenderConfig(width=int(width), height=int(height))
        img, _ = self.render_camera(
            self.camera(azim, elev, cfg.width, cfg.height, zoom), cfg,
            use_pallas=use_pallas)
        return sr.to_uint8(img)

    # -- persistence -------------------------------------------------------

    def to_bytes(self) -> bytes:
        """The scene as .npz bytes (the ``GET /session/<id>/splats``
        payload and ``cli render`` input)."""
        buf = _io.BytesIO()
        np.savez_compressed(
            buf, version=np.int32(_SCENE_VERSION),
            params=np.asarray(tuple(self.params), np.float64),
            means=np.asarray(self.means), normals=np.asarray(self.normals),
            log_scales=np.asarray(self.log_scales),
            colors_sh=np.asarray(self.colors_sh),
            opacity=np.asarray(self.opacity),
            valid=np.asarray(self.valid),
            bbox_lo=self.bbox[0], bbox_hi=self.bbox[1],
            voxel_size=np.float64(self.voxel_size))
        return buf.getvalue()

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.to_bytes())

    @classmethod
    def from_bytes(cls, data: bytes) -> "SplatScene":
        try:
            z = np.load(_io.BytesIO(data), allow_pickle=False)
        except (ValueError, OSError, zlib.error) as e:
            raise ValueError(f"not a splat scene archive: {e}")
        if "version" not in z or int(z["version"]) != _SCENE_VERSION:
            raise ValueError(
                f"splat scene version {z.get('version')} unsupported "
                f"(this build reads v{_SCENE_VERSION})")
        p = z["params"]
        params = SplatParams(capacity=int(p[0]), iso_band=float(p[1]),
                             scale_mult=float(p[2]),
                             normal_scale=float(p[3]),
                             opacity_init=float(p[4]),
                             min_weight=float(p[5]))
        return cls(params, z["means"], z["normals"], z["log_scales"],
                   z["colors_sh"], z["opacity"], z["valid"],
                   bbox=(z["bbox_lo"], z["bbox_hi"]),
                   voxel_size=float(z["voxel_size"]))

    @classmethod
    def load(cls, path: str) -> "SplatScene":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    def stats(self) -> dict:
        return {
            "splats": self.n_splats,
            "capacity": self.capacity,
            "voxel_size": round(self.voxel_size, 6),
            **{k: v for k, v in self.fit_stats.items()},
        }


def seed_from_volume(volume: TSDFVolume,
                     params: SplatParams = SplatParams()) -> SplatScene:
    """TSDF volume → :class:`SplatScene` (module docstring). Pure read:
    the volume state is NOT donated — previews keep integrating into it
    and re-seeding after more stops is the intended refresh."""
    state = volume._state
    nbr, block_valid = tsdf_ops.neighbor_table(state, volume.params)
    out = _seed_fn(volume.params, params)(
        state.tsdf, state.weight, state.rgb, state.brick_coords, nbr,
        block_valid, jnp.asarray(volume.origin, jnp.float32),
        jnp.float32(volume.voxel_size))
    means, normals, log_scales, sh, opacity, valid, n_near = out
    scene = SplatScene(params, means, normals, log_scales, sh, opacity,
                       valid, voxel_size=volume.voxel_size)
    n = scene.n_splats
    if n == 0:
        log.warning("splat seeding found no shell voxels (empty or "
                    "unobserved volume)")
    else:
        log.debug("seeded %d/%d splats from %d shell voxels (voxel %.4f)",
                  n, params.capacity, int(n_near), volume.voxel_size)
    return scene


def splat_scene_from_cloud(cloud, params: SplatParams = SplatParams(),
                           depth: int = 7, max_bricks: int = 8192,
                           orientation_mode: str = "radial") -> SplatScene:
    """Oriented/colored cloud → fused TSDF → seeded scene — the
    `mesh_from_cloud`-style one-shot entry (``cli render`` over a .ply).
    Sign from the oriented normals, colors from ``cloud.colors`` (gray
    when absent); appearance starts at the fused DC colors — pass the
    scene through `splat/fit.py` with captured views to add view
    dependence."""
    from ..models import meshing
    from ..ops.marching_jax import _bucket

    pts = np.asarray(cloud.points, np.float32)
    if pts.shape[0] < 16:
        raise ValueError(f"too few points to splat ({pts.shape[0]})")
    normals = meshing.ensure_oriented_normals(cloud, orientation_mode)
    grid_depth = min(max(int(depth), 5), 9)
    tparams = tsdf_ops.TSDFParams(grid_depth=grid_depth,
                                  max_bricks=int(max_bricks))
    n = pts.shape[0]
    cap = _bucket(n)
    pad = cap - n
    has_colors = cloud.colors is not None and len(cloud.colors) == n
    cols = np.asarray(cloud.colors, np.float32) if has_colors \
        else np.full((n, 3), 180.0, np.float32)
    vol = TSDFVolume.from_bounds(tparams, pts.min(axis=0),
                                 pts.max(axis=0))
    vol.integrate_oriented(
        np.concatenate([pts, np.zeros((pad, 3), np.float32)]),
        np.concatenate([cols, np.zeros((pad, 3), np.float32)]),
        np.concatenate([np.ones(n, bool), np.zeros(pad, bool)]),
        np.concatenate([normals.astype(np.float32),
                        np.tile(np.asarray([[0.0, 0.0, 1.0]], np.float32),
                                (pad, 1))]))
    scene = seed_from_volume(vol, params)
    log.info("splat scene from %d points: %d splats (depth=%d, "
             "colored=%s)", n, scene.n_splats, grid_depth, has_colors)
    return scene
