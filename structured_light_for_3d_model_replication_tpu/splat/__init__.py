"""splat/ — the Gaussian appearance tier on the fused TSDF shell.

The third result type next to clouds and meshes (ROADMAP: "rendered,
not just extracted, previews"): anisotropic Gaussians SEEDED on the
TSDF iso-shell `fusion/` maintains (one compaction pass over active
bricks — Splatonic's sparse-processing argument applied to appearance
state), FITTED against the per-stop RGB the capture already produced
(view-dependent color as low-order SH + opacity + scale, a jitted
donated fixed-shape SGD loop — no new capture), and RENDERED from any
novel view by the tile-binned sorted-alpha-composite rasterizer
(`ops/splat_render.py`, Pallas tile kernel on TPU backends).

Per Gaussian-Plus-SDF SLAM (PAPERS.md) the splats stay ANCHORED on the
SDF: positions and normals come from the volume and are never optimized
— geometry lives in one place (the TSDF), appearance in another (the
splats), so the fit is small, convex-ish and deterministic, and a
re-seed after further integration never fights a drifted splat cloud.

Entry points: :func:`splat_scene_from_cloud` (the `mesh_from_cloud`-
style one-shot), :func:`seed_from_volume` (streaming / fusion path),
:class:`SplatScene` (render / save / load), `splat/preview.py`'s
:class:`SplatPreviewMesher` (the streaming previewer lane), serve's
``GET /session/<id>/render`` + ``result_format="render_png"``, and
``cli render``. docs/RENDERING.md covers the architecture.
"""

from .fit import fit_appearance, fit_pinhole, psnr
from .model import (
    SplatParams,
    SplatScene,
    seed_from_volume,
    splat_scene_from_cloud,
)
from .preview import SplatPreviewMesher

__all__ = [
    "SplatParams",
    "SplatPreviewMesher",
    "SplatScene",
    "fit_appearance",
    "fit_pinhole",
    "psnr",
    "seed_from_volume",
    "splat_scene_from_cloud",
]
