"""View-dependent appearance fitting against the captured per-stop RGB.

No new capture: every structured-light stop already shipped a dense RGB
frame (the white-reference texture decode carries per pixel) and a pose
(the session's ring solve). This module re-uses them as a supervision
set: render the splat scene from a stop's camera, compare to that
stop's (valid-masked, downsampled) colors, descend. Per the
Gaussian-Plus-SDF split, GEOMETRY is frozen — means/normals stay
anchored on the TSDF shell — and only appearance moves: per-splat SH
color (degree 1: DC + 3 linear bands per channel), opacity logit and
log-scales.

Static-shape discipline: the whole optimization is ONE jitted Adam step
donated in/out (params and optimizer state alias across iterations —
the `stream/session._fuse_fn` pattern applied to an optimizer), with
the frame index a TRACED scalar into the stacked (F, h, w, …) frame
buffer — F, the fit resolution and the splat capacity key the program,
the iteration count never does. Gradients flow through the XLA
composite (`ops/splat_render._composite_xla`); the Pallas kernel is a
read-only fast path and is never differentiated.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import splat_render as sr
from ..utils.log import get_logger

log = get_logger(__name__)

_BETA1, _BETA2, _EPS = 0.9, 0.999, 1e-8


def psnr(img, ref, mask=None) -> float:
    """PSNR in dB between images in 0–1 scale; ``mask`` restricts the
    mean to covered pixels (a captured stop's decode-valid region)."""
    a = np.asarray(img, np.float64)
    b = np.asarray(ref, np.float64)
    if mask is not None:
        m = np.asarray(mask, bool)
        if not m.any():
            return 0.0
        a = a[m]
        b = b[m]
    mse = float(np.mean((a - b) ** 2))
    return float(10.0 * np.log10(1.0 / max(mse, 1e-12)))


def fit_pinhole(points, valid, height: int, width: int):
    """Recover ``(fx, fy, cx, cy)`` from ONE decoded stop's camera-frame
    points — two tiny least squares (u = fx·x/z + cx over the pixel
    grid), so sessions need no calibration plumbing to fit appearance.
    Returns None when the stop has too few usable pixels."""
    pts = np.asarray(points, np.float64).reshape(height, width, 3)
    val = np.asarray(valid, bool).reshape(height, width)
    z = pts[..., 2]
    ok = val & (z > 1e-6)
    if int(ok.sum()) < 64:
        return None
    jj, ii = np.meshgrid(np.arange(width, dtype=np.float64),
                         np.arange(height, dtype=np.float64))
    xz = (pts[..., 0] / np.where(ok, z, 1.0))[ok]
    yz = (pts[..., 1] / np.where(ok, z, 1.0))[ok]
    one = np.ones_like(xz)
    (fx, cx), *_ = np.linalg.lstsq(np.stack([xz, one], 1), jj[ok],
                                   rcond=None)
    (fy, cy), *_ = np.linalg.lstsq(np.stack([yz, one], 1), ii[ok],
                                   rcond=None)
    if not (np.isfinite([fx, fy, cx, cy]).all() and fx > 0 and fy > 0):
        return None
    return float(fx), float(fy), float(cx), float(cy)


def frame_target(colors, valid, height: int, width: int, stride: int):
    """One dense stop frame → the fit-resolution target: strided
    subsample of the (H, W) pixel grid. ``colors`` is a DECODE frame —
    0–255 scale (uint8 or float, `models/pipeline` colors), always
    divided by 255 (a value-range heuristic here would misread a dark
    float frame as already normalized). Returns ``(target (h, w, 3)
    f32 0–1, mask (h, w) bool)`` host arrays.

    The mask is the decode-valid region ERODED by one fit-resolution
    pixel: silhouette pixels mix foreground and background at the
    capture AND sit at the shell's observation fringe, so both the fit
    loss and the PSNR gate measure interior appearance (the render
    still has to cover the interior wall-to-wall — background showing
    through any interior pixel is fully penalized)."""
    img = np.asarray(colors).reshape(height, width, 3)
    msk = np.asarray(valid, bool).reshape(height, width)
    t = img[::stride, ::stride].astype(np.float32) / 255.0
    m = msk[::stride, ::stride]
    er = m.copy()
    er[1:] &= m[:-1]
    er[:-1] &= m[1:]
    er[:, 1:] &= m[:, :-1]
    er[:, :-1] &= m[:, 1:]
    return np.clip(t, 0.0, 1.0), er


@functools.lru_cache(maxsize=None)
def _fit_step_fn(cfg: sr.RenderConfig, lr_color: float, lr_opacity: float,
                 lr_scale: float, band_decay: float):
    """One Adam step over (colors_sh, opacity, log_scales); params and
    moments donated in/out. Program keyed by (render cfg, lrs, splat
    capacity & frame-buffer shapes) — the frame INDEX is traced.

    ``band_decay`` multiplicatively shrinks the linear SH bands each
    step: with a handful of supervision views the bands can absorb
    per-view residual (coverage gaps, pose jitter) as fake view
    dependence that extrapolates badly to held-out views — the decay
    keeps only view dependence the data keeps re-earning."""

    def loss_fn(fit_params, frozen, frame, mask, cam):
        colors_sh, opacity, log_scales = fit_params
        means, normals, valid = frozen
        img, _ = sr._render_fn(means, normals, log_scales, colors_sh,
                               opacity, valid, *cam, cfg,
                               use_pallas=False)
        m = mask.astype(jnp.float32)[..., None]
        return jnp.sum(m * (img - frame) ** 2) \
            / jnp.maximum(jnp.sum(m) * 3.0, 1.0)

    lrs = (lr_color, lr_opacity, lr_scale)

    def step(fit_params, m1, m2, t, frozen, frames, masks, cams, i):
        frame = frames[i]
        mask = masks[i]
        cam = tuple(c[i] for c in cams)
        loss, grads = jax.value_and_grad(loss_fn)(fit_params, frozen,
                                                  frame, mask, cam)
        t = t + 1.0
        bc1 = 1.0 - _BETA1 ** t
        bc2 = 1.0 - _BETA2 ** t
        new_p, new_m1, new_m2 = [], [], []
        for p, g, a, b, lr in zip(fit_params, grads, m1, m2, lrs):
            a = _BETA1 * a + (1.0 - _BETA1) * g
            b = _BETA2 * b + (1.0 - _BETA2) * g * g
            upd = lr * (a / bc1) / (jnp.sqrt(b / bc2) + _EPS)
            new_p.append(p - upd)
            new_m1.append(a)
            new_m2.append(b)
        sh = new_p[0]
        new_p[0] = sh.at[:, 1:, :].multiply(jnp.float32(band_decay))
        return tuple(new_p), tuple(new_m1), tuple(new_m2), t, loss

    return jax.jit(step, donate_argnums=(0, 1, 2, 3))


def fit_appearance(scene, frames, masks, cameras,
                   fit_cfg: sr.RenderConfig | None = None,
                   iters: int = 60, lr_color: float = 0.08,
                   lr_opacity: float = 0.05, lr_scale: float = 0.01,
                   band_decay: float = 0.997):
    """Fit the scene's appearance against captured views, in place.

    ``frames`` (F, h, w, 3) float 0–1, ``masks`` (F, h, w) bool,
    ``cameras`` a list of F render camera tuples (``stop_camera`` at
    fit-resolution intrinsics). ``fit_cfg`` defaults to the frame shape.
    Frames are visited round-robin (traced index — one compiled step).
    Returns the scene with ``fit_stats`` filled (loss trajectory ends,
    seconds, iterations)."""
    frames = jnp.asarray(frames, jnp.float32)
    masks = jnp.asarray(masks, bool)
    F, h, w = frames.shape[:3]
    if fit_cfg is None:
        fit_cfg = sr.RenderConfig(width=w, height=h)
    cams = tuple(
        jnp.stack([jnp.asarray(c[k], jnp.float32) for c in cameras])
        for k in range(6))
    step = _fit_step_fn(fit_cfg, float(lr_color), float(lr_opacity),
                        float(lr_scale), float(band_decay))
    fit_params = (scene.colors_sh, scene.opacity, scene.log_scales)
    m1 = tuple(jnp.zeros_like(p) for p in fit_params)
    m2 = tuple(jnp.zeros_like(p) for p in fit_params)
    t = jnp.zeros((), jnp.float32)
    frozen = (scene.means, scene.normals, scene.valid)
    t0 = time.monotonic()
    loss0 = loss = None
    for it in range(int(iters)):
        fit_params, m1, m2, t, loss = step(
            fit_params, m1, m2, t, frozen, frames, masks, cams,
            jnp.int32(it % F))
        if it == 0:
            loss0 = loss  # device value — no per-iteration host sync
    first = float(loss0) if loss0 is not None else None
    last = float(loss) if loss is not None else None
    scene.colors_sh, scene.opacity, scene.log_scales = fit_params
    scene.fit_stats = {
        "fit_iters": int(iters),
        "fit_frames": int(F),
        "fit_loss_first": round(first, 6) if first is not None else None,
        "fit_loss_last": round(last, 6) if last is not None else None,
        "fit_seconds": round(time.monotonic() - t0, 3),
    }
    log.debug("appearance fit: %d iters over %d frames, loss %.5f -> "
              "%.5f in %.2fs", iters, F, first or 0.0, last or 0.0,
              scene.fit_stats["fit_seconds"])
    return scene
