"""SplatPreviewMesher: the rendered-preview lane for streaming sessions.

Extends the TSDF previewer (`fusion/preview.py` — geometry previews
stay the extracted colored mesh, so the STL preview endpoint keeps
working unchanged) with the appearance tier:

* each fused stop's DENSE frame (the decode's per-pixel colors + valid
  mask, camera-frame points for the one-time pinhole fit) is observed
  into a bounded round-robin frame buffer at a fixed fit resolution —
  work per stop is one strided host subsample, no device programs;
* the splat scene is LAZY: seeded from the volume and fitted against
  the buffered frames only when a render is actually requested (the
  serve render endpoint, ``--preview-render``, finalize's
  ``render_png``) and only when stops arrived since the last build —
  the INGEST path never runs seed/fit work itself. The build is SPLIT
  so serve can run it off the session lock
  (:meth:`~SplatPreviewMesher.begin_scene_build` — the one cheap seed
  pass, under the lock / :meth:`~SplatPreviewMesher.finish_scene_build`
  — the expensive fixed-iteration fit, lock-FREE on an immutable
  snapshot / :meth:`~SplatPreviewMesher.adopt_scene` — publish,
  newest-stops-wins, under the lock again): a live-polling render
  client no longer delays the next stop's ingest by the rebuild
  (the ROADMAP async-scene-build item; regression-tested in
  tests/test_stream.py). ``ensure_scene`` composes the three for
  synchronous callers (CLI, finalize);
* re-builds are from-scratch (re-seed + fixed-iteration fit), so a
  render is a deterministic function of the volume + frame buffer —
  no incremental optimizer drift, and the serve/CLI parity contract
  (same scene bytes ⇒ same pixels) holds.

Static shapes: the seed program is keyed by (TSDFParams, SplatParams),
the fit step by the fit resolution, the render by (capacity, render
size) — a 20-frame novel-view sweep after warmup compiles NOTHING
(asserted in tests/test_splat.py).
"""

from __future__ import annotations

import time

import numpy as np

from ..fusion.preview import TSDFPreviewMesher
from ..io.png import png_bytes
from ..ops import splat_render as sr
from ..ops.tsdf import TSDFParams
from ..utils.log import get_logger
from .fit import fit_appearance, fit_pinhole, frame_target
from .model import SplatParams, SplatScene, seed_from_volume

log = get_logger(__name__)


class _SceneBuild:
    """One in-flight lazy scene rebuild (the begin/finish/adopt split
    of :class:`SplatPreviewMesher`): the seeded scene plus an immutable
    snapshot of the fit inputs, so the expensive fit phase can run
    without the session lock."""

    __slots__ = ("scene", "stops", "frames", "cams", "t0", "done")

    def __init__(self, scene, stops, frames, cams, t0, done=False):
        self.scene = scene
        self.stops = stops
        self.frames = frames
        self.cams = cams
        self.t0 = t0
        self.done = done


class SplatPreviewMesher(TSDFPreviewMesher):
    """Drop-in previewer (`stream/preview.make_previewer` lane
    ``representation="splat"``): TSDF mesh previews + rendered novel
    views."""

    def __init__(self, voxel_size_hint: float,
                 params: TSDFParams = TSDFParams(max_bricks=4096),
                 splat_params: SplatParams = SplatParams(),
                 fit_iters: int = 40, max_frames: int = 8,
                 fit_pixels: int = 12288,
                 render_sizes: tuple = ((384, 288),), **kw):
        super().__init__(voxel_size_hint, params=params, **kw)
        self.splat_params = splat_params
        self.fit_iters = int(fit_iters)
        self.max_frames = max(1, int(max_frames))
        self.fit_pixels = int(fit_pixels)
        self.render_sizes = tuple((int(w), int(h))
                                  for w, h in render_sizes)
        self.intrinsics: tuple | None = None   # (fx, fy, cx, cy) full-res
        self.frame_shape: tuple | None = None
        self.stride: int = 1
        self._frames: list = []        # (target, mask) host arrays
        self._cams: list = []          # render camera tuples at fit res
        self._frames_seen = 0
        self._scene: SplatScene | None = None
        self._scene_stops = -1         # stops_integrated at last build
        self.last_render_meta: dict = {}

    # -- frame observation (per fused stop, host-side) ---------------------

    def observe_frame(self, points, colors, valid, pose,
                      frame_shape) -> bool:
        """Buffer one stop's dense frame for the appearance fit.

        ``points``/``colors``/``valid`` are the stop's dense decode
        arrays (camera frame, (H·W, …)); ``pose`` the stop's camera→
        model 4×4. Returns False when the frame is unusable (pinhole
        fit failed) — rendering still works from the volume's DC
        colors."""
        h, w = int(frame_shape[0]), int(frame_shape[1])
        if self.frame_shape is None:
            self.frame_shape = (h, w)
            stride = 1
            while (h // stride) * (w // stride) > self.fit_pixels:
                stride += 1
            self.stride = stride
        elif (h, w) != self.frame_shape:
            log.warning("splat frame shape changed %s -> %s; frame "
                        "dropped", self.frame_shape, (h, w))
            return False
        if self.intrinsics is None:
            fit = fit_pinhole(np.asarray(points), np.asarray(valid), h, w)
            if fit is None:
                log.debug("splat pinhole fit abstained (stop too sparse)")
                return False
            self.intrinsics = fit
        target, mask = frame_target(colors, valid, h, w, self.stride)
        fx, fy, cx, cy = self.intrinsics
        s = float(self.stride)
        cam = sr.stop_camera(np.asarray(pose, np.float64),
                             fx / s, fy / s, cx / s, cy / s)
        if len(self._frames) < self.max_frames:
            self._frames.append((target, mask))
            self._cams.append(cam)
        else:
            slot = self._frames_seen % self.max_frames
            self._frames[slot] = (target, mask)
            self._cams[slot] = cam
        self._frames_seen += 1
        return True

    # -- lazy scene build --------------------------------------------------

    @property
    def scene_stale(self) -> bool:
        return (self._scene is None or self.volume is None
                or self._scene_stops != self.volume.stops_integrated)

    def begin_scene_build(self) -> "_SceneBuild | None":
        """Phase 1 (call under the session lock): snapshot the build
        inputs and run the CHEAP seed pass. Returns None before the
        first integrated stop; a non-stale scene returns a done token
        (finish/adopt are then no-ops). The token holds everything the
        fit needs — the frame buffer entries are immutable tuples and
        the volume is not touched again — so phase 2 runs without the
        lock while ingest keeps mutating the live buffers."""
        if self.volume is None:
            return None
        if not self.scene_stale:
            return _SceneBuild(scene=self._scene,
                               stops=self._scene_stops, frames=(),
                               cams=(), t0=time.monotonic(), done=True)
        t0 = time.monotonic()
        scene = seed_from_volume(self.volume, self.splat_params)
        return _SceneBuild(scene=scene,
                           stops=self.volume.stops_integrated,
                           frames=tuple(self._frames),
                           cams=tuple(self._cams), t0=t0)

    def finish_scene_build(self, token: "_SceneBuild") -> "_SceneBuild":
        """Phase 2 (lock-free): the fixed-iteration appearance fit —
        the expensive part of a rebuild. Deterministic function of the
        token's snapshot, so two racing builds of the same stop count
        produce identical scenes."""
        if token.done:
            return token
        if token.frames and token.scene.n_splats:
            # Pad the buffer to the FIXED max_frames slot count by
            # cycling what exists (duplicate supervision ≈ extra epochs
            # on fewer frames — harmless and deterministic): the fit
            # step's program is keyed by the frame-buffer length, so a
            # growing buffer would otherwise recompile it at every size
            # 1..max_frames — including inside the first render
            # requests of a session the replica warmup claimed warm.
            idx = [i % len(token.frames)
                   for i in range(self.max_frames)]
            frames = np.stack([token.frames[i][0] for i in idx])
            masks = np.stack([token.frames[i][1] for i in idx])
            fit_appearance(token.scene, frames, masks,
                           [token.cams[i] for i in idx],
                           iters=self.fit_iters)
        token.scene.fit_stats["build_seconds"] = round(
            time.monotonic() - token.t0, 3)
        token.done = True
        return token

    def adopt_scene(self, token: "_SceneBuild") -> SplatScene:
        """Phase 3 (call under the session lock): publish the built
        scene. Newest-stops wins — a racing build that fused MORE stops
        keeps its (fresher) scene; the returned scene is the token's
        own build either way, so the caller renders exactly what it
        asked for."""
        if self._scene is None or self._scene_stops <= token.stops:
            self._scene = token.scene
            self._scene_stops = token.stops
        return token.scene

    def ensure_scene(self) -> SplatScene | None:
        """Synchronous compose of the three build phases (offline/CLI
        callers, finalize): seed + fit if stops arrived since the last
        build; None before the first integrated stop."""
        token = self.begin_scene_build()
        if token is None:
            return None
        self.finish_scene_build(token)
        return self.adopt_scene(token)

    # -- rendering ---------------------------------------------------------

    def render_size_ok(self, width: int, height: int) -> bool:
        return (int(width), int(height)) in self.render_sizes

    def render_image(self, azim: float, elev: float,
                     width: int | None = None,
                     height: int | None = None,
                     scene: "SplatScene | None" = None
                     ) -> np.ndarray | None:
        """(H, W, 3) uint8 novel view, or None before the first stop.
        ``scene`` renders a PRE-BUILT scene (the serve path, which ran
        the build phases off the session lock) instead of triggering a
        synchronous ``ensure_scene`` here."""
        if scene is None:
            scene = self.ensure_scene()
        if scene is None:
            return None
        w, h = self.render_sizes[0]
        if width is not None and height is not None:
            w, h = int(width), int(height)
        t0 = time.monotonic()
        img = scene.render(azim=float(azim), elev=float(elev),
                           width=w, height=h)
        self.last_render_meta = {
            "azim": round(float(azim), 3), "elev": round(float(elev), 3),
            "width": w, "height": h, "splats": scene.n_splats,
            "render_s": round(time.monotonic() - t0, 4),
            "fit_frames": len(self._frames),
        }
        return img

    def render_png(self, azim: float, elev: float,
                   width: int | None = None,
                   height: int | None = None,
                   scene: "SplatScene | None" = None
                   ) -> tuple[bytes, dict] | None:
        img = self.render_image(azim, elev, width, height, scene=scene)
        if img is None:
            return None
        return png_bytes(img), dict(self.last_render_meta)

    def scene_bytes(self, scene: "SplatScene | None" = None
                    ) -> bytes | None:
        """The current scene as .npz bytes (the ``/session/<id>/splats``
        payload; ``cli render`` re-renders it bit-identically)."""
        if scene is None:
            scene = self.ensure_scene()
        return None if scene is None else scene.to_bytes()

    def stats(self) -> dict:
        out = super().stats()
        out.update(fit_frames=len(self._frames),
                   frames_seen=self._frames_seen,
                   scene_stale=self.scene_stale)
        if self._scene is not None:
            out.update(self._scene.stats())
        return out
