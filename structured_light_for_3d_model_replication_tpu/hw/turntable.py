"""Turntable drivers: serial ESP32 protocol + timing-faithful simulator.

The reference drives a stepper turntable over a 115200-baud serial line with
a newline-terminated decimal-degrees protocol and a ``DONE`` completion reply
(`server/arduino.py:16-71`; firmware `ESP_code.ino:21-44` and the NEMA17
variant `Old/arduino_turntable.txt:17-80`). Semantics preserved here:

* ``rotate(deg)`` sends ``f"{deg}\n"`` and returns immediately;
* ``wait_for_done(timeout)`` blocks for the ``DONE`` line; on timeout the
  caller warns and continues (`server/gui.py:760-762` — a missed DONE is not
  fatal, the scan proceeds);
* port auto-discovery tries likely device names when none is given
  (`server/arduino.py:16-33`).

:class:`SimulatedTurntable` replaces the reference's inline
"Simulation mode" sleep (`server/gui.py:690-693,764-765`) with a first-class
driver: same API, a 10 RPM motion model (`ESP_code.ino:12`), and an angle
readout the virtual rig uses to rotate the synthetic scene.
"""

from __future__ import annotations

import glob
import threading
import time

from ..health import ScanFault
from ..utils.log import get_logger

log = get_logger(__name__)

BAUD_RATE = 115200
DONE_TOKEN = "DONE"
DEFAULT_RPM = 10.0  # ESP_code.ino:12 — 10 RPM stepper


class TurntableError(ScanFault):
    """Turntable transport failure (part of the scan error taxonomy)."""


class SerialTurntable:
    """PC↔ESP32 driver (`server/arduino.py`). Needs pyserial; import is
    lazy so the rest of the framework stays importable without it."""

    def __init__(self, port: str | None = None, baud: int = BAUD_RATE,
                 timeout: float = 1.0):
        try:
            import serial  # type: ignore
        except ImportError as e:  # pragma: no cover - env without pyserial
            raise TurntableError(
                "pyserial is not installed; use SimulatedTurntable") from e
        self._serial_mod = serial
        self._conn = None
        self.port = port
        self.baud = baud
        self.timeout = timeout

    @property
    def connected(self) -> bool:
        return self._conn is not None and self._conn.is_open

    def connect(self) -> bool:
        """Open the port (auto-discover if unset), give the MCU its reset
        settle time (`server/arduino.py:36-39`: 2 s after open)."""
        candidates = ([self.port] if self.port
                      else sorted(glob.glob("/dev/ttyUSB*"))
                      + sorted(glob.glob("/dev/ttyACM*")))
        for cand in candidates:
            try:
                self._conn = self._serial_mod.Serial(
                    cand, self.baud, timeout=self.timeout)
                time.sleep(2.0)  # board resets on open
                self._conn.reset_input_buffer()
                self.port = cand
                log.info("turntable connected on %s", cand)
                return True
            # Only transport-level failures mean "try the next port";
            # anything else (bad baud type, programming error) must surface.
            except (self._serial_mod.SerialException,
                    OSError) as e:  # pragma: no cover - hardware path
                log.debug("no turntable on %s: %s", cand, e)
                # A post-open failure (e.g. unplugged during the reset
                # sleep) leaves a half-open handle in self._conn: close it
                # so `connected` cannot report True after a failed probe.
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except (self._serial_mod.SerialException, OSError):
                        log.debug("close of half-open %s failed", cand)
                    self._conn = None
        log.warning("turntable connection failed; tried %s",
                    candidates or "no candidate ports")
        return False

    def rotate(self, degrees: float) -> None:
        if not self.connected:
            raise TurntableError("not connected")
        self._conn.write(f"{degrees}\n".encode("ascii"))
        self._conn.flush()

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        """Block for the ``DONE`` line; False on timeout (caller decides —
        the reference warns and continues)."""
        if not self.connected:
            raise TurntableError("not connected")
        deadline = time.monotonic() + timeout
        buf = b""
        while time.monotonic() < deadline:
            chunk = self._conn.readline()
            buf += chunk
            if DONE_TOKEN.encode() in buf:
                return True
        log.warning("turntable DONE timeout after %.1fs", timeout)
        return False

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class SimulatedTurntable:
    """Headless turntable with the real driver's API and timing shape.

    Motion completes after ``|deg| / (rpm·6) `` seconds (10 RPM → 6°/s) on a
    background timer, so orchestration code exercises the same
    rotate→wait_for_done handshake it would against hardware. ``angle_deg``
    accumulates the commanded rotations for the virtual rig.
    """

    def __init__(self, rpm: float = DEFAULT_RPM, time_scale: float = 1.0):
        self.rpm = rpm
        self.time_scale = time_scale  # tests shrink real waits
        self.angle_deg = 0.0
        self._done = threading.Event()
        self._done.set()
        self._lock = threading.Lock()
        self._timer: threading.Timer | None = None
        self._pending = 0.0
        self._gen = 0
        self.connected = True

    def connect(self) -> bool:
        return True

    def rotate(self, degrees: float) -> None:
        with self._lock:
            # A new command supersedes an in-flight move: cancel its timer
            # and land its rotation NOW (the real firmware is blocking, so
            # overlap only happens if the caller skipped wait_for_done).
            # The generation counter makes a fired-but-lock-blocked timer
            # from the old move a no-op.
            self._gen += 1
            gen = self._gen
            if self._timer is not None:
                self._timer.cancel()
                if not self._done.is_set():
                    self.angle_deg = (self.angle_deg + self._pending) % 360.0
            self._done.clear()
            self._pending = degrees
            duration = abs(degrees) / (self.rpm * 6.0) * self.time_scale

            def finish():
                with self._lock:
                    if self._gen != gen:
                        return
                    self.angle_deg = (self.angle_deg + degrees) % 360.0
                    self._done.set()

            self._timer = threading.Timer(duration, finish)
            self._timer.daemon = True
            self._timer.start()

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        ok = self._done.wait(timeout)
        if not ok:
            log.warning("simulated turntable DONE timeout after %.1fs",
                        timeout)
        return ok

    def close(self) -> None:
        pass
