"""Hardware/device-communication layer (reference L4 + satellites).

Three transports with the reference's exact wire protocols — pull-mode HTTP
command channel (`server/server.py`), push-mode Android Camera2 host client
(`android_camera_host/`), serial turntable (`server/arduino.py` /
`ESP_code.ino`) — plus headless virtual equivalents for every device so the
full capture pipeline runs without hardware (:mod:`.rig`).

`WindowProjector` needs cv2 and `SerialTurntable` needs pyserial; both import
lazily inside the class so this package (and the virtual rig) works on bare
images.
"""

from .camera import (  # noqa: F401
    CameraSettings,
    LocalCamera,
    PullCamera,
    PushCamera,
    SyntheticCamera,
)
from .command_server import CommandChannel, CommandServer  # noqa: F401
from .faults import (  # noqa: F401
    CallSchedule,
    FaultPlan,
    FaultRule,
    FlakyCamera,
    FlakyChannel,
    FlakyTurntable,
)
from .projector import VirtualProjector, WindowProjector  # noqa: F401
from .rig import VirtualRig  # noqa: F401
from .turntable import SerialTurntable, SimulatedTurntable, TurntableError  # noqa: F401
