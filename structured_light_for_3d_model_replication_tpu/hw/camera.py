"""Camera backends: pull-mode phone, push-mode Android host, synthetic.

One ``capture(path) -> bool`` surface over the reference's three capture
paths:

* :class:`PullCamera` — the shipped path: arm a ``capture`` command on the
  :mod:`command_server` channel and wait for the phone browser's upload
  (`server/sl_system.py:88-109` + `frotend/App.tsx:195-248`).
* :class:`PushCamera` — the Android Camera2 host path: request the JPEG
  directly over HTTP from the NanoHTTPD server on :8765
  (`android_camera_host/.../CameraHostServer.kt:14-78`, client
  `Old/android_camera_host_client.py:8-104`): ``GET /status``,
  ``GET /capabilities``, ``POST /settings``, ``POST /capture/jpeg`` with
  capture metadata in the ``X-Capture-Meta`` response header.
* :class:`SyntheticCamera` — headless: shades whatever the virtual projector
  currently displays through the synthetic scene raycaster
  (`models/synthetic.FrameShader`). This is the phone simulator the
  reference lacks (SURVEY §4).
"""

from __future__ import annotations

import dataclasses
import json
import urllib.request

import numpy as np

from ..io.images import write_frame
from ..utils.log import get_logger

log = get_logger(__name__)


class PullCamera:
    """Capture by command/upload handshake over a CommandChannel."""

    def __init__(self, channel, timeout: float = 20.0):
        self.channel = channel
        self.timeout = timeout

    @property
    def connected(self) -> bool:
        return self.channel.connected

    def capture(self, path: str) -> bool:
        return self.channel.trigger_capture(path, timeout=self.timeout)


@dataclasses.dataclass
class CameraSettings:
    """Manual Camera2 controls for structured light: auto-exposure and
    autofocus OFF so frames are photometrically consistent across the stack
    (`Old/scanner_controller_android.py:37-43`)."""

    ae_mode: str = "off"
    iso: int = 400
    exposure_ns: int = 20_000_000
    af_mode: str = "off"
    focus_diopters: float = 2.0
    awb_mode: str = "auto"
    zoom: float = 1.0
    torch: bool = False

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()


class PushCamera:
    """Client for the Android Camera2 host's push-mode REST protocol."""

    def __init__(self, base_url: str = "http://127.0.0.1:8765",
                 timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.last_meta: dict | None = None

    def _get(self, route: str) -> dict:
        with urllib.request.urlopen(self.base_url + route,
                                    timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def status(self) -> dict:
        return self._get("/status")

    def capabilities(self) -> dict:
        return self._get("/capabilities")

    @property
    def connected(self) -> bool:
        try:
            return bool(self.status())
        except Exception:
            return False

    def apply_settings(self, settings: CameraSettings) -> dict:
        req = urllib.request.Request(
            self.base_url + "/settings", data=settings.to_json(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def capture_jpeg(self) -> bytes:
        """JPEG bytes; capture metadata lands in ``self.last_meta``
        (`CameraHostServer.kt:59-66`: body = image, meta = header)."""
        req = urllib.request.Request(self.base_url + "/capture/jpeg",
                                     data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            meta = r.headers.get("X-Capture-Meta")
            self.last_meta = json.loads(meta) if meta else None
            return r.read()

    def capture(self, path: str) -> bool:
        try:
            data = self.capture_jpeg()
        except Exception as e:
            log.warning("push capture failed: %s", e)
            return False
        with open(path, "wb") as f:
            f.write(data)
        return True


class LocalCamera:
    """Local USB/builtin webcam via ``cv2.VideoCapture`` — the no-phone
    capture path of the reference's webcam calibration rig
    (`Old/sl_calib_capture.py:46-123`: open ``CAM_ID``, force
    ``CAP_PROP_FRAME_WIDTH/HEIGHT``, ``cap.read()`` per projected frame).

    ``flush`` frames are read and discarded before the kept one:
    ``VideoCapture`` buffers a few frames internally, so without the flush a
    capture taken right after the projector swaps patterns can return a
    frame photographed under the PREVIOUS pattern — fatal for Gray-code
    decoding. (The reference sidesteps this with 200–500 ms ``waitKey``
    dwells; flushing is deterministic.)

    cv2 imports lazily so the package works on bare images.
    """

    def __init__(self, device_id: int = 0, width: int | None = 1920,
                 height: int | None = 1080, flush: int = 2):
        import cv2  # lazy: only this class needs it

        self._cv2 = cv2
        self.device_id = device_id
        self.flush = flush
        self._cap = cv2.VideoCapture(device_id)
        if not self._cap.isOpened():
            raise RuntimeError(f"cannot open local camera {device_id}")
        if width is not None:
            self._cap.set(cv2.CAP_PROP_FRAME_WIDTH, width)
        if height is not None:
            self._cap.set(cv2.CAP_PROP_FRAME_HEIGHT, height)
        self.connected = True

    def capture_array(self) -> np.ndarray:
        for _ in range(self.flush):
            self._cap.read()
        ok, frame = self._cap.read()
        if not ok or frame is None:
            raise RuntimeError(f"camera {self.device_id} returned no frame")
        return frame  # BGR uint8, as cv2 delivers it

    def capture(self, path: str) -> bool:
        try:
            frame = self.capture_array()
        except Exception as e:
            log.warning("local capture failed: %s", e)
            return False
        return bool(self._cv2.imwrite(path, frame))

    def release(self) -> None:
        self._cap.release()
        self.connected = False


class SyntheticCamera:
    """Renders the virtual projector's current frame through the scene.

    The shader (scene geometry at the current turntable pose) is supplied by
    the owning rig via ``shader_fn`` so rotation invalidation lives in one
    place (`hw/rig.py`).
    """

    def __init__(self, projector, shader_fn):
        self.projector = projector
        self._shader_fn = shader_fn
        self.connected = True

    def capture_array(self) -> np.ndarray:
        return self._shader_fn().shade(self.projector.current_frame)

    def capture(self, path: str) -> bool:
        write_frame(path, self.capture_array())
        return True
