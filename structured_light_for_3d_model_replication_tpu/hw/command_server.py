"""Pull-mode command channel: PC↔phone HTTP control + image upload plane.

TPU-framework equivalent of the reference's Flask server
(`server/server.py`): the phone browser polls ``GET /poll_command`` every
500 ms (`frotend/App.tsx:5,195-220`), deduplicates on the command's UUID, and
answers a ``capture`` command by POSTing the JPEG to ``/upload``
(`frotend/App.tsx:222-248`). The PC side arms a capture with
:meth:`CommandChannel.trigger_capture` and blocks on an event with a 20 s
abort timeout (`server/sl_system.py:88-109`).

Differences from the reference, on purpose:

* stdlib ``ThreadingHTTPServer`` — no web-framework dependency;
* ``CommandChannel`` state is guarded by a lock (SURVEY §5 flags the
  reference's ``SERVER_STATE`` two-thread mutation without one as a known
  hazard — fixed here, not preserved);
* the disconnect watchdog (`server/server.py:80-93`: connected flips false
  after 5 s of poll silence) is event-driven rather than a polling thread.
"""

from __future__ import annotations

import email.parser
import email.policy
import json
import os
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.log import get_logger

log = get_logger(__name__)

POLL_SILENCE_DISCONNECT_S = 5.0   # server/server.py:86
CAPTURE_TIMEOUT_S = 20.0          # server/sl_system.py:103


class CommandChannel:
    """Thread-safe command/upload handshake state (SERVER_STATE analogue,
    `server/server.py:18-25`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._uploaded = threading.Event()
        self._command = "idle"
        self._command_id = str(uuid.uuid4())
        self._save_path: str | None = None
        self._last_poll = 0.0
        self.on_upload = None  # optional callback(path)

    # -- PC side -----------------------------------------------------------

    def trigger_capture(self, save_path: str,
                        timeout: float = CAPTURE_TIMEOUT_S) -> bool:
        """Arm a capture command and block until the client uploads (True)
        or the timeout lapses (False; command resets to idle either way) —
        `SLSystem.trigger_capture` semantics (`server/sl_system.py:88-109`).
        """
        with self._lock:
            self._uploaded.clear()
            self._save_path = save_path
            self._command_id = str(uuid.uuid4())
            self._command = "capture"
        ok = self._uploaded.wait(timeout)
        with self._lock:
            self._command = "idle"
            # Disarm so a LATE upload from this (timed-out) capture can't
            # satisfy the next trigger with the wrong image.
            self._save_path = None
        if not ok:
            log.warning("capture timed out after %.0fs (%s)", timeout,
                        save_path)
        return ok

    @property
    def connected(self) -> bool:
        with self._lock:
            return (time.monotonic() - self._last_poll
                    ) < POLL_SILENCE_DISCONNECT_S

    # -- HTTP side ---------------------------------------------------------

    def poll(self) -> dict:
        # BOTH keys: the reference frontend reads ``data.action``
        # (`frotend/App.tsx:207`, `server/server.py:44`), this framework's
        # client reads ``data.command`` — serving both makes either client a
        # drop-in against this server.
        with self._lock:
            self._last_poll = time.monotonic()
            return {"action": self._command, "command": self._command,
                    "id": self._command_id}

    def accept_upload(self, data: bytes) -> str:
        with self._lock:
            # Only an ARMED capture accepts an upload; anything else is a
            # stray (double upload, or a late one from a timed-out command).
            path = self._save_path if self._command == "capture" else None
            cmd_id = self._command_id
        if path is None:
            raise RuntimeError("upload with no capture armed")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
        if self.on_upload is not None:
            self.on_upload(path)
        with self._lock:
            # Signal only if THIS command is still the armed one: a slow
            # upload that straddles a timeout + re-arm must not satisfy the
            # NEXT capture (its file was written to the old path).
            if self._command_id == cmd_id:
                self._uploaded.set()
        return path


def _extract_upload(handler: BaseHTTPRequestHandler) -> bytes:
    """File bytes from a POST body: multipart/form-data (what the React
    client sends, `frotend/App.tsx:236-247`) or a raw body."""
    length = int(handler.headers.get("Content-Length", 0))
    body = handler.rfile.read(length)
    ctype = handler.headers.get("Content-Type", "")
    if ctype.startswith("multipart/form-data"):
        # Reparse with the email machinery: prepend the header block.
        msg = email.parser.BytesParser(policy=email.policy.HTTP).parsebytes(
            b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + body)
        for part in msg.iter_parts():
            if part.get_filename() or part.get_content_type().startswith(
                    "image/"):
                return part.get_payload(decode=True)
        raise ValueError("multipart body without a file part")
    return body


class _Handler(BaseHTTPRequestHandler):
    channel: CommandChannel  # set by make_server

    def _json(self, obj, status=200):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path == "/poll_command":
            self._json(self.channel.poll())
        elif self.path == "/status":
            self._json({"connected": self.channel.connected})
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        if self.path == "/upload":
            try:
                path = self.channel.accept_upload(_extract_upload(self))
                self._json({"saved": os.path.basename(path)})
            except Exception as e:
                log.warning("upload failed: %s", e)
                self._json({"error": str(e)}, 400)
        else:
            self._json({"error": "not found"}, 404)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        log.debug("http: " + fmt, *args)


class CommandServer:
    """Owns the HTTP listener thread (daemonized like `server/main.py:17`)."""

    def __init__(self, channel: CommandChannel | None = None,
                 host: str = "0.0.0.0", port: int = 5000):
        self.channel = channel or CommandChannel()
        handler = type("BoundHandler", (_Handler,),
                       {"channel": self.channel})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._started = False

    def start(self) -> "CommandServer":
        self._thread.start()
        self._started = True
        log.info("command server on :%d", self.port)
        return self

    def stop(self) -> None:
        # shutdown() waits on serve_forever's exit event and would deadlock
        # if the serve thread never started.
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()
