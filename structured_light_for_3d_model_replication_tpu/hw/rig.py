"""Virtual scanning rig: projector + camera + turntable, fully headless.

Binds a synthetic :class:`~..models.synthetic.Scene` to the hardware
abstractions so the complete capture stack — pattern display, camera
trigger, turntable rotation, file layout — runs with zero hardware. The
reference can only simulate the turntable (`server/gui.py:690-693`); its
capture path needs a physical phone (SURVEY §4). This rig closes that gap
and doubles as the integration-test harness for the scanner orchestrator.

The turntable angle rotates the SCENE (object on the table), not the camera
— same physics as the real rig (`models/synthetic.rotated_scene`).
"""

from __future__ import annotations

import numpy as np

from ..config import ProjectorConfig
from ..models import synthetic
from .camera import SyntheticCamera
from .projector import VirtualProjector
from .turntable import SimulatedTurntable


class VirtualRig:
    def __init__(
        self,
        scene: synthetic.Scene | None = None,
        cam_height: int = 96,
        cam_width: int = 160,
        proj: ProjectorConfig = ProjectorConfig(width=256, height=128),
        calibration=None,
        time_scale: float = 0.0,
    ):
        self.scene = scene or synthetic.Scene()
        self.cam_height, self.cam_width = cam_height, cam_width
        self.proj = proj
        if calibration is None:
            calibration = synthetic.default_calibration(cam_height, cam_width,
                                                        proj)
        self.cam_K, self.proj_K, self.R, self.T = calibration
        self.projector = VirtualProjector(proj, record=True)
        self.turntable = SimulatedTurntable(time_scale=time_scale)
        self.camera = SyntheticCamera(self.projector, self._shader)
        self._shader_cache: tuple[float, synthetic.FrameShader] | None = None

    def _shader(self) -> synthetic.FrameShader:
        angle = self.turntable.angle_deg
        if self._shader_cache is None or self._shader_cache[0] != angle:
            sc = synthetic.rotated_scene(self.scene, angle)
            self._shader_cache = (angle, synthetic.FrameShader(
                sc, self.cam_K, self.proj_K, self.R, self.T,
                self.cam_height, self.cam_width, self.proj))
        return self._shader_cache[1]

    @property
    def ground_truth(self) -> dict:
        """Analytic ground truth at the CURRENT turntable angle."""
        return self._shader().ground_truth

    def white_frame(self) -> np.ndarray:
        return np.full((self.proj.height, self.proj.width),
                       self.proj.brightness, np.uint8)
