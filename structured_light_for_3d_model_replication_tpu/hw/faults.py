"""Deterministic fault injection for the hardware layer (chaos harness).

The rig is hardware-in-the-loop — phone uploads over HTTP, an ESP32
turntable over serial — so the interesting failures are the ones a clean
virtual rig never produces: capture timeouts, all-black/saturated frames
(torch glitch, exposure misfire), duplicated frames (stale buffer served
twice), truncated uploads (connection dropped mid-POST), missed turntable
``DONE`` lines. This module wraps any camera/turntable/channel in a
schedule-driven fault injector so the containment layer
(`scanner.RetryPolicy`, the quality gates in `models/scan360`) can be
proven against EXACTLY reproducible failure runs:

* :class:`FaultPlan` — per-(capture path, attempt) fault kinds, so a
  "transient" fault (fails attempt 0, clean on retry) and a "hard" fault
  (fails every attempt) are both one rule; deterministic by construction,
  with a seeded generator for randomized-but-reproducible campaigns.
* :class:`FlakyCamera` — wraps any ``capture(path) -> bool`` camera.
  ``timeout`` faults return False without writing (the pull-channel abort
  shape, `server/sl_system.py:102-104`); corruption faults let the inner
  capture succeed and then damage the file — those frames upload "fine"
  and must be caught downstream by the decode-coverage gate (or, for
  truncation, the scanner's frame verification).
* :class:`FlakyTurntable` / :class:`FlakyChannel` — missed DONE lines,
  dead rotations, dropped command handshakes on a per-call schedule.

Corruption composes with :mod:`..models.realism`: :func:`realism_post`
routes every *successful* frame through the photoreal degradation chain,
so a chaos run can be photometrically realistic AND fault-injected.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Sequence

import numpy as np

from ..utils.log import get_logger

log = get_logger(__name__)

#: Camera fault kinds understood by :class:`FlakyCamera`.
CAMERA_FAULTS = ("timeout", "black", "saturated", "duplicate", "truncate")
#: Turntable fault kinds understood by :class:`FlakyTurntable`.
TURNTABLE_FAULTS = ("done_timeout", "stuck")
#: Device (accelerator) fault kinds understood by :class:`FaultyDevice`.
DEVICE_FAULTS = ("device_lost", "nan_output", "latency", "hang")
#: Env var carrying a JSON :class:`DeviceFaultPlan` for subprocess
#: replicas and the lane-chaos bench (the chaos harness sets it;
#: production never does).
DEVICE_FAULTS_ENV = "SL_DEVICE_FAULTS"


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """Faults for captures whose path contains ``match``.

    ``kinds[a]`` is the fault injected on attempt ``a`` for that frame
    (attempts count per path); attempts beyond the list are clean.
    ``always`` repeats ``kinds[-1]`` forever — a hard failure no retry
    policy can outlast.
    """

    match: str
    kinds: tuple[str, ...]
    always: bool = False

    def kind_for(self, attempt: int) -> str | None:
        if attempt < len(self.kinds):
            return self.kinds[attempt]
        if self.always and self.kinds:
            return self.kinds[-1]
        return None


class FaultPlan:
    """Ordered rule list; first rule whose ``match`` is a substring of the
    capture path wins. Stateless — attempt counting lives in the wrapper,
    so one plan can drive several runs."""

    def __init__(self, rules: Sequence[FaultRule] = ()):
        self.rules = list(rules)
        for r in self.rules:
            for k in r.kinds:
                if k not in CAMERA_FAULTS:
                    raise ValueError(f"unknown camera fault kind {k!r}")

    def fault_for(self, path: str, attempt: int) -> str | None:
        for rule in self.rules:
            if rule.match in path:
                return rule.kind_for(attempt)
        return None

    @classmethod
    def transient(cls, match: str, kind: str = "timeout",
                  times: int = 1) -> FaultRule:
        """Rule failing the first ``times`` attempts, then clean."""
        return FaultRule(match=match, kinds=(kind,) * times)

    @classmethod
    def hard(cls, match: str, kind: str = "timeout") -> FaultRule:
        """Rule failing EVERY attempt."""
        return FaultRule(match=match, kinds=(kind,), always=True)

    @classmethod
    def seeded(cls, seed: int, matches: Sequence[str],
               p_transient: float = 0.1, p_hard: float = 0.0,
               kinds: Sequence[str] = ("timeout",)) -> "FaultPlan":
        """Reproducible random campaign over ``matches`` (e.g. the stop
        directories of a session): each match independently draws a
        transient or hard fault of a random kind."""
        rng = np.random.default_rng(seed)
        rules = []
        for m in matches:
            u = float(rng.random())
            kind = str(rng.choice(list(kinds)))
            if u < p_hard:
                rules.append(cls.hard(m, kind))
            elif u < p_hard + p_transient:
                rules.append(cls.transient(m, kind))
        return cls(rules)


class CallSchedule:
    """call-index → fault kind, for devices whose faults are per call
    rather than per file (turntable rotations, channel triggers)."""

    def __init__(self, faults: dict[int, str] | None = None):
        self.faults = dict(faults or {})
        self.calls = 0

    def next(self) -> str | None:
        kind = self.faults.get(self.calls)
        self.calls += 1
        return kind

    @classmethod
    def seeded(cls, seed: int, n_calls: int,
               rates: dict[str, float]) -> "CallSchedule":
        rng = np.random.default_rng(seed)
        faults: dict[int, str] = {}
        for i in range(n_calls):
            u = float(rng.random())
            acc = 0.0
            for kind, p in sorted(rates.items()):
                acc += p
                if u < acc:
                    faults[i] = kind
                    break
        return cls(faults)


# ---------------------------------------------------------------------------
# Frame corruption models
# ---------------------------------------------------------------------------


def corrupt_frame_file(path: str, kind: str,
                       duplicate_of: str | None = None) -> bool:
    """Damage an already-written frame file in place; True iff the file
    was actually modified (``duplicate`` with no prior frame is a no-op).

    ``black``/``saturated`` rewrite the image at its own size (an exposure
    misfire uploads a well-formed but informationless frame); ``duplicate``
    replaces the bytes with another frame's (stale camera buffer served
    twice); ``truncate`` chops the file mid-stream (connection dropped
    mid-upload — the result is NOT a decodable image)."""
    from ..io import images as img_io

    if kind in ("black", "saturated"):
        frame = img_io._imread_gray(path)
        value = 0 if kind == "black" else 255
        img_io.write_frame(path, np.full_like(frame, value))
    elif kind == "duplicate":
        if duplicate_of is None or not os.path.exists(duplicate_of):
            return False  # nothing to duplicate yet (first frame): no-op
        with open(duplicate_of, "rb") as src, open(path, "wb") as dst:
            dst.write(src.read())
    elif kind == "truncate":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 3))
    else:
        raise ValueError(f"unknown corruption kind {kind!r}")
    return True


def realism_post(cam_K: np.ndarray, params=None,
                 seed: int = 0) -> Callable[[str], None]:
    """Post-capture hook routing every successful frame through the
    photoreal sensor chain (`models.realism.degrade_frame`) — compose with
    :class:`FlakyCamera` for photometrically-degraded chaos runs."""
    from ..io import images as img_io
    from ..models import realism

    if params is None:
        params = realism.SensorParams()
    rng = np.random.default_rng(seed)

    def post(path: str) -> None:
        frame = img_io._imread_gray(path)
        img_io.write_frame(path, realism.degrade_frame(frame, cam_K, params,
                                                       rng))

    return post


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------


class FlakyCamera:
    """Schedule-driven fault wrapper over any ``capture(path)`` camera.

    ``injected`` logs every (path, attempt, kind) actually fired, so a
    chaos test can assert the health report records EXACTLY the injected
    faults and nothing else.
    """

    def __init__(self, inner, plan: FaultPlan,
                 post: Callable[[str], None] | None = None):
        self.inner = inner
        self.plan = plan
        self.post = post
        self.attempts: dict[str, int] = defaultdict(int)
        self.injected: list[tuple[str, int, str]] = []
        self._last_good: str | None = None

    @property
    def connected(self) -> bool:
        return bool(getattr(self.inner, "connected", True))

    def capture(self, path: str) -> bool:
        attempt = self.attempts[path]
        self.attempts[path] += 1
        kind = self.plan.fault_for(path, attempt)
        if kind == "timeout":
            self.injected.append((path, attempt, kind))
            log.debug("chaos: capture timeout injected (%s attempt %d)",
                      path, attempt)
            return False
        if not self.inner.capture(path):
            return False
        applied = False
        if kind is not None:
            applied = corrupt_frame_file(path, kind,
                                         duplicate_of=self._last_good)
        if applied:
            # Ledger records only faults that actually FIRED — a chaos
            # test asserting health == injected must not be lied to by a
            # no-op (duplicate with no prior frame).
            self.injected.append((path, attempt, kind))
            log.debug("chaos: frame corruption %r injected (%s attempt %d)",
                      kind, path, attempt)
        else:
            if self.post is not None:
                self.post(path)
            self._last_good = path
        return True


class FlakyTurntable:
    """Fault wrapper over any rotate/wait_for_done turntable.

    ``done_timeout``: the move happens but the DONE line is lost (the
    warn-and-continue case, `server/gui.py:760-762`). ``stuck``: the
    rotation command is swallowed — the table never moves, and DONE (for
    that move) never comes.
    """

    def __init__(self, inner, schedule: CallSchedule):
        self.inner = inner
        self.schedule = schedule
        self.injected: list[tuple[int, str]] = []
        self._pending: str | None = None

    @property
    def connected(self) -> bool:
        return bool(getattr(self.inner, "connected", True))

    @property
    def angle_deg(self) -> float:
        return self.inner.angle_deg

    def connect(self) -> bool:
        return self.inner.connect()

    def rotate(self, degrees: float) -> None:
        kind = self.schedule.next()
        self._pending = kind
        if kind is not None:
            self.injected.append((self.schedule.calls - 1, kind))
            if kind == "stuck":
                log.debug("chaos: rotation swallowed (%.1f°)", degrees)
                return
        self.inner.rotate(degrees)

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        kind, self._pending = self._pending, None
        if kind == "stuck":
            return False        # nothing is moving; DONE never comes
        done = self.inner.wait_for_done(timeout)
        if kind == "done_timeout":
            return False        # move completed but the line was lost
        return done

    def close(self) -> None:
        self.inner.close()


# ---------------------------------------------------------------------------
# Device (accelerator) fault injection — the serve tier's lane boundary
# ---------------------------------------------------------------------------


class DeviceLostError(RuntimeError):
    """The launch's view of a dead chip: the runtime refused the program
    because the device is gone (the ``DEVICE_LOST`` shape real backends
    raise). The serve tier's lane-health escalation keys on this class
    (plus the per-backend taxonomy below for real runtime errors,
    `serve/worker.py`)."""


#: Env var extending :data:`DEVICE_LOSS_TAXONOMY` with deployment
#: vocabulary the table doesn't ship (a fleet's driver build may word a
#: dead chip its own way). Accepts a JSON object keyed by backend —
#: ``{"tpu": ["pattern", ...]}`` or ``{"tpu": {"patterns": [...],
#: "types": [...]}}`` — or a bare comma-separated pattern list applied
#: to every backend. Malformed values are logged and ignored, never
#: raised (the BlobFaultPlan env idiom).
DEVICE_LOSS_PATTERNS_ENV = "SL_DEVICE_LOSS_PATTERNS"

#: Per-backend device-loss vocabulary: ``types`` are exception CLASS
#: names (matched against the exception's MRO — lets an extension key on
#: an unambiguous error class instead of prose), ``patterns`` are
#: lowercase message substrings. The split by backend exists because the
#: same word means different things per runtime: a TPU "halted" is a
#: dead chip, a CPU "halted" is somebody's debugger — classifying with
#: one flat list (the old string sniff) either over-fires on healthy
#: backends or under-fires on real losses. Deliberately NOT listed:
#: allocation failures ("out of memory", "RESOURCE_EXHAUSTED") — an OOM
#: lane is overloaded, not dead, and must feed the governor's breaker,
#: never the lane-death escalation.
DEVICE_LOSS_TAXONOMY: dict[str, dict[str, tuple[str, ...]]] = {
    # CPU devices don't die under a living process: only the generic
    # (injected-fault) vocabulary classifies.
    "cpu": {
        "types": (),
        "patterns": ("device_lost", "device lost", "device is gone"),
    },
    "tpu": {
        "types": (),
        "patterns": ("device_lost", "device lost", "device is gone",
                     "tpu is halted", "core halted",
                     "slice health check failed",
                     "failed to connect to tpu driver"),
    },
    "gpu": {
        "types": (),
        "patterns": ("device_lost", "device lost", "device is gone",
                     "cuda_error_device_unavailable",
                     "cuda_error_ecc_uncorrectable",
                     "fell off the bus", "gpu is lost"),
    },
}

# jax.default_backend() spellings that aren't taxonomy keys.
_BACKEND_ALIASES = {"cuda": "gpu", "rocm": "gpu"}

# (raw env string, parsed extension) — re-parsed only when the env var
# actually changes, so the per-launch classifier costs one dict probe.
_env_taxonomy_cache: tuple[str | None, dict] = (None, {})


def _env_taxonomy() -> dict:
    global _env_taxonomy_cache
    raw = os.environ.get(DEVICE_LOSS_PATTERNS_ENV)
    if raw == _env_taxonomy_cache[0]:
        return _env_taxonomy_cache[1]
    ext: dict[str, dict[str, tuple[str, ...]]] = {}
    if raw and raw.strip():
        try:
            doc = json.loads(raw)
        except ValueError:
            doc = None
        if isinstance(doc, dict):
            for backend, spec in doc.items():
                if isinstance(spec, dict):
                    ext[backend] = {
                        "types": tuple(spec.get("types", ())),
                        "patterns": tuple(
                            str(p).lower()
                            for p in spec.get("patterns", ())),
                    }
                else:
                    ext[backend] = {"types": (), "patterns": tuple(
                        str(p).lower() for p in spec)}
        elif doc is None:
            # Not JSON: a bare comma list — every backend learns it.
            pats = tuple(p.strip().lower()
                         for p in raw.split(",") if p.strip())
            ext = {b: {"types": (), "patterns": pats}
                   for b in DEVICE_LOSS_TAXONOMY}
        else:
            log.error("ignoring malformed %s: not a JSON object or "
                      "pattern list", DEVICE_LOSS_PATTERNS_ENV)
    _env_taxonomy_cache = (raw, ext)
    return ext


def _loss_entries(backend: str | None) -> list[dict]:
    """Taxonomy entries to consult: the backend's own (plus its env
    extension), or — when the backend can't be resolved — the union of
    every backend's (the conservative superset: an unclassifiable
    runtime must not silence a real loss)."""
    ext = _env_taxonomy()
    if backend is not None:
        backend = _BACKEND_ALIASES.get(backend, backend)
        if backend in DEVICE_LOSS_TAXONOMY or backend in ext:
            entries = []
            if backend in DEVICE_LOSS_TAXONOMY:
                entries.append(DEVICE_LOSS_TAXONOMY[backend])
            if backend in ext:
                entries.append(ext[backend])
            return entries
    return list(DEVICE_LOSS_TAXONOMY.values()) + list(ext.values())


def is_device_loss(exc: BaseException, backend: str | None = None) -> bool:
    """Device-loss classifier shared by the worker and the probe: the
    injected :class:`DeviceLostError`, or a real runtime error matching
    the backend's row of :data:`DEVICE_LOSS_TAXONOMY` (error-type name
    or message vocabulary, extensible via ``SL_DEVICE_LOSS_PATTERNS``).

    ``backend`` defaults to the live ``jax.default_backend()``;
    unresolvable (no jax, broken runtime) falls back to matching every
    backend's vocabulary — over-matching a dying process beats
    under-matching a dead chip."""
    if isinstance(exc, DeviceLostError):
        return True
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = None
    names = {c.__name__ for c in type(exc).__mro__}
    msg = str(exc).lower()
    for entry in _loss_entries(backend):
        if names.intersection(entry["types"]):
            return True
        if any(p in msg for p in entry["patterns"]):
            return True
    return False


@dataclasses.dataclass(frozen=True)
class DeviceFaultRule:
    """Faults for launches on devices whose label contains ``device``.

    Launches count per device (the wrapper/injector owns the counter —
    plans stay stateless, the :class:`FaultPlan` rule). The fault fires
    on launch indices ``[after_launches, after_launches + count)``;
    ``count = -1`` repeats forever — a genuinely dead chip no retry can
    outlast. ``stall_s`` is the injected delay for ``latency`` (the
    launch then proceeds) and ``hang`` (the launch stalls the worker's
    heartbeat — the watchdog's wedge signal — then raises device-lost).
    """

    device: str
    kind: str
    after_launches: int = 0
    count: int = -1
    stall_s: float = 0.25

    def fires(self, launch: int) -> bool:
        if launch < self.after_launches:
            return False
        if self.count < 0:
            return True
        return launch < self.after_launches + self.count


class DeviceFaultPlan:
    """Ordered rule list; first rule whose ``device`` is a substring of
    the lane's device label wins. Stateless — launch counting lives in
    :class:`DeviceFaultInjector`, so one plan drives several runs (and
    serializes to/from the ``SL_DEVICE_FAULTS`` env for subprocess
    replicas, the :class:`~..serve.blobstore.BlobFaultPlan` idiom)."""

    def __init__(self, rules: Sequence[DeviceFaultRule] = ()):
        self.rules = list(rules)
        for r in self.rules:
            if r.kind not in DEVICE_FAULTS:
                raise ValueError(f"unknown device fault kind {r.kind!r}")

    def fault_for(self, device_label: str,
                  launch: int) -> DeviceFaultRule | None:
        for rule in self.rules:
            if rule.device in device_label:
                return rule if rule.fires(launch) else None
        return None

    # -- env round-trip (subprocess replicas / chaos bench) ------------

    def to_env(self) -> str:
        return json.dumps({"rules": [dataclasses.asdict(r)
                                     for r in self.rules]})

    @classmethod
    def from_env(cls, env: str = DEVICE_FAULTS_ENV
                 ) -> "DeviceFaultPlan | None":
        spec = os.environ.get(env)
        if not spec:
            return None
        try:
            doc = json.loads(spec)
        except ValueError as e:
            log.error("ignoring malformed %s: %s", env, e)
            return None
        allowed = {f.name for f in dataclasses.fields(DeviceFaultRule)}
        try:
            rules = [DeviceFaultRule(
                **{k: v for k, v in r.items() if k in allowed})
                for r in doc.get("rules", [])]
            return cls(rules)
        except (TypeError, ValueError) as e:
            log.error("ignoring malformed %s: %s", env, e)
            return None

    @classmethod
    def seeded(cls, seed: int, devices: Sequence[str],
               p_dead: float = 0.0, p_nan: float = 0.0,
               after_launches: int = 0) -> "DeviceFaultPlan":
        """Reproducible random campaign over device labels: each device
        independently draws a permanent device-loss or NaN-output fault
        (hw/faults determinism rule — same seed, same casualties)."""
        rng = np.random.default_rng(seed)
        rules = []
        for d in devices:
            u = float(rng.random())
            if u < p_dead:
                rules.append(DeviceFaultRule(
                    device=d, kind="device_lost",
                    after_launches=after_launches))
            elif u < p_dead + p_nan:
                rules.append(DeviceFaultRule(
                    device=d, kind="nan_output",
                    after_launches=after_launches))
        return cls(rules)


class DeviceFaultInjector:
    """Per-process launch counters + fired-fault ledger over one plan.

    ``injected`` logs every (monotonic t, device, launch index, kind)
    that actually fired, so the lane-chaos gate can measure
    ``lane_failover_s`` from the FIRST injection and assert the lane
    health report records exactly the injected faults."""

    def __init__(self, plan: DeviceFaultPlan, sleep=time.sleep):
        self.plan = plan
        self._sleep = sleep
        self._lock = threading.Lock()
        self._launches: dict[str, int] = defaultdict(int)
        self.injected: list[tuple[float, str, int, str]] = []

    def next_fault(self, device_label: str) -> DeviceFaultRule | None:
        """Count one launch on ``device_label``; the rule that fires for
        it, if any (recorded in the ledger). Quarantine PROBES count as
        launches too, deliberately: a dead device receives no worker
        launches, so a count-limited (transient) outage could otherwise
        never expire while quarantined — probe attempts are what walk
        the fault window shut, and a probe a rule fires against IS an
        injected fault in the ledger."""
        with self._lock:
            launch = self._launches[device_label]
            self._launches[device_label] += 1
            rule = self.plan.fault_for(device_label, launch)
            if rule is not None:
                self.injected.append((time.monotonic(), device_label,
                                      launch, rule.kind))
        if rule is not None:
            log.debug("chaos: device fault %r injected (%s launch %d)",
                      rule.kind, device_label, launch)
        return rule

    def first_fault_t(self) -> float | None:
        """Monotonic stamp of the first fired fault (the lane-chaos
        bench's ``lane_failover_s`` zero point), or None."""
        with self._lock:
            return self.injected[0][0] if self.injected else None

    def fire_pre_launch(self, rule: DeviceFaultRule,
                        device_label: str) -> None:
        """The pre-launch side of a fired rule: stall and/or raise.
        ``nan_output`` does nothing here (the launch must succeed so
        the poisoned payload flows through the readback path)."""
        if rule.kind in ("latency", "hang"):
            self._sleep(rule.stall_s)
        if rule.kind == "device_lost" or rule.kind == "hang":
            raise DeviceLostError(
                f"injected device loss on {device_label} "
                f"(kind={rule.kind})")

    @staticmethod
    def poison_output(out):
        """The post-launch side of ``nan_output``: the launch succeeded
        but the chip returned garbage — every point lane becomes NaN
        while validity still claims them good (exactly the payload the
        SL_SANITIZE finite-check must catch at the readback
        boundary)."""
        import types

        points = np.asarray(out.points, dtype=np.float32).copy()
        points[...] = np.nan
        return types.SimpleNamespace(points=points, colors=out.colors,
                                     valid=out.valid)


class FaultyDevice:
    """Wraps one AOT executable at the lane boundary (`serve/worker.py`):
    launches on the wrapped device consult the injector first, so a
    seeded plan turns one chip of a healthy pool into a dead / stalling
    / NaN-emitting one without touching the runtime."""

    def __init__(self, compiled, device_label: str,
                 injector: DeviceFaultInjector):
        self.compiled = compiled
        self.device_label = device_label
        self.injector = injector

    def __call__(self, *args):
        rule = self.injector.next_fault(self.device_label)
        if rule is not None:
            self.injector.fire_pre_launch(rule, self.device_label)
        out = self.compiled(*args)
        if rule is not None and rule.kind == "nan_output":
            out = self.injector.poison_output(out)
        return out


class FaultySpan:
    """Span-member targeting for the sharded cross-chip tier: wraps one
    sharded AOT executable and consults the injector for EVERY device in
    the program's span, so an ``SL_DEVICE_FAULTS`` rule naming a single
    member (``"cpu:0"``) kills/poisons the whole sharded launch — which
    is exactly what a real mesh does when one chip dies. Each member's
    launch counter advances per sharded launch (a span launch IS a
    launch on every member), keeping count-limited (transient) rules'
    windows consistent with the per-lane wrapper's.

    The raised loss deliberately does NOT name the guilty member to the
    caller-visible error flow the worker classifies on — attribution is
    the probe-convict protocol's job (`serve/service.py`,
    docs/ROBUSTNESS.md), and a chaos error that confessed would test
    nothing."""

    def __init__(self, compiled, span: Sequence[str],
                 injector: DeviceFaultInjector):
        self.compiled = compiled
        self.span = tuple(span)
        self.injector = injector

    def __call__(self, *args):
        fired = None
        for label in self.span:
            rule = self.injector.next_fault(label)
            if rule is not None and fired is None:
                fired = (rule, label)
        if fired is not None:
            rule, label = fired
            if rule.kind in ("latency", "hang"):
                self.injector._sleep(rule.stall_s)
            if rule.kind in ("device_lost", "hang"):
                raise DeviceLostError(
                    "injected device loss on sharded span "
                    f"{'+'.join(self.span)} (kind={rule.kind})")
        out = self.compiled(*args)
        if fired is not None and fired[0].kind == "nan_output":
            out = self.injector.poison_output(out)
        return out


class FlakyChannel:
    """Fault wrapper over a ``CommandChannel``-shaped object: a ``drop``
    fault swallows the trigger (the phone never saw the command — the
    poll response was lost in flight)."""

    def __init__(self, inner, schedule: CallSchedule):
        self.inner = inner
        self.schedule = schedule
        self.injected: list[tuple[int, str]] = []

    @property
    def connected(self) -> bool:
        return self.inner.connected

    def trigger_capture(self, save_path: str, timeout: float = 20.0) -> bool:
        kind = self.schedule.next()
        if kind is not None:
            self.injected.append((self.schedule.calls - 1, kind))
            log.debug("chaos: trigger dropped (%s)", save_path)
            return False
        return self.inner.trigger_capture(save_path, timeout=timeout)
