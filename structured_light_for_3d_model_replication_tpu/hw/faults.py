"""Deterministic fault injection for the hardware layer (chaos harness).

The rig is hardware-in-the-loop — phone uploads over HTTP, an ESP32
turntable over serial — so the interesting failures are the ones a clean
virtual rig never produces: capture timeouts, all-black/saturated frames
(torch glitch, exposure misfire), duplicated frames (stale buffer served
twice), truncated uploads (connection dropped mid-POST), missed turntable
``DONE`` lines. This module wraps any camera/turntable/channel in a
schedule-driven fault injector so the containment layer
(`scanner.RetryPolicy`, the quality gates in `models/scan360`) can be
proven against EXACTLY reproducible failure runs:

* :class:`FaultPlan` — per-(capture path, attempt) fault kinds, so a
  "transient" fault (fails attempt 0, clean on retry) and a "hard" fault
  (fails every attempt) are both one rule; deterministic by construction,
  with a seeded generator for randomized-but-reproducible campaigns.
* :class:`FlakyCamera` — wraps any ``capture(path) -> bool`` camera.
  ``timeout`` faults return False without writing (the pull-channel abort
  shape, `server/sl_system.py:102-104`); corruption faults let the inner
  capture succeed and then damage the file — those frames upload "fine"
  and must be caught downstream by the decode-coverage gate (or, for
  truncation, the scanner's frame verification).
* :class:`FlakyTurntable` / :class:`FlakyChannel` — missed DONE lines,
  dead rotations, dropped command handshakes on a per-call schedule.

Corruption composes with :mod:`..models.realism`: :func:`realism_post`
routes every *successful* frame through the photoreal degradation chain,
so a chaos run can be photometrically realistic AND fault-injected.
"""

from __future__ import annotations

import dataclasses
import os
from collections import defaultdict
from typing import Callable, Sequence

import numpy as np

from ..utils.log import get_logger

log = get_logger(__name__)

#: Camera fault kinds understood by :class:`FlakyCamera`.
CAMERA_FAULTS = ("timeout", "black", "saturated", "duplicate", "truncate")
#: Turntable fault kinds understood by :class:`FlakyTurntable`.
TURNTABLE_FAULTS = ("done_timeout", "stuck")


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """Faults for captures whose path contains ``match``.

    ``kinds[a]`` is the fault injected on attempt ``a`` for that frame
    (attempts count per path); attempts beyond the list are clean.
    ``always`` repeats ``kinds[-1]`` forever — a hard failure no retry
    policy can outlast.
    """

    match: str
    kinds: tuple[str, ...]
    always: bool = False

    def kind_for(self, attempt: int) -> str | None:
        if attempt < len(self.kinds):
            return self.kinds[attempt]
        if self.always and self.kinds:
            return self.kinds[-1]
        return None


class FaultPlan:
    """Ordered rule list; first rule whose ``match`` is a substring of the
    capture path wins. Stateless — attempt counting lives in the wrapper,
    so one plan can drive several runs."""

    def __init__(self, rules: Sequence[FaultRule] = ()):
        self.rules = list(rules)
        for r in self.rules:
            for k in r.kinds:
                if k not in CAMERA_FAULTS:
                    raise ValueError(f"unknown camera fault kind {k!r}")

    def fault_for(self, path: str, attempt: int) -> str | None:
        for rule in self.rules:
            if rule.match in path:
                return rule.kind_for(attempt)
        return None

    @classmethod
    def transient(cls, match: str, kind: str = "timeout",
                  times: int = 1) -> FaultRule:
        """Rule failing the first ``times`` attempts, then clean."""
        return FaultRule(match=match, kinds=(kind,) * times)

    @classmethod
    def hard(cls, match: str, kind: str = "timeout") -> FaultRule:
        """Rule failing EVERY attempt."""
        return FaultRule(match=match, kinds=(kind,), always=True)

    @classmethod
    def seeded(cls, seed: int, matches: Sequence[str],
               p_transient: float = 0.1, p_hard: float = 0.0,
               kinds: Sequence[str] = ("timeout",)) -> "FaultPlan":
        """Reproducible random campaign over ``matches`` (e.g. the stop
        directories of a session): each match independently draws a
        transient or hard fault of a random kind."""
        rng = np.random.default_rng(seed)
        rules = []
        for m in matches:
            u = float(rng.random())
            kind = str(rng.choice(list(kinds)))
            if u < p_hard:
                rules.append(cls.hard(m, kind))
            elif u < p_hard + p_transient:
                rules.append(cls.transient(m, kind))
        return cls(rules)


class CallSchedule:
    """call-index → fault kind, for devices whose faults are per call
    rather than per file (turntable rotations, channel triggers)."""

    def __init__(self, faults: dict[int, str] | None = None):
        self.faults = dict(faults or {})
        self.calls = 0

    def next(self) -> str | None:
        kind = self.faults.get(self.calls)
        self.calls += 1
        return kind

    @classmethod
    def seeded(cls, seed: int, n_calls: int,
               rates: dict[str, float]) -> "CallSchedule":
        rng = np.random.default_rng(seed)
        faults: dict[int, str] = {}
        for i in range(n_calls):
            u = float(rng.random())
            acc = 0.0
            for kind, p in sorted(rates.items()):
                acc += p
                if u < acc:
                    faults[i] = kind
                    break
        return cls(faults)


# ---------------------------------------------------------------------------
# Frame corruption models
# ---------------------------------------------------------------------------


def corrupt_frame_file(path: str, kind: str,
                       duplicate_of: str | None = None) -> bool:
    """Damage an already-written frame file in place; True iff the file
    was actually modified (``duplicate`` with no prior frame is a no-op).

    ``black``/``saturated`` rewrite the image at its own size (an exposure
    misfire uploads a well-formed but informationless frame); ``duplicate``
    replaces the bytes with another frame's (stale camera buffer served
    twice); ``truncate`` chops the file mid-stream (connection dropped
    mid-upload — the result is NOT a decodable image)."""
    from ..io import images as img_io

    if kind in ("black", "saturated"):
        frame = img_io._imread_gray(path)
        value = 0 if kind == "black" else 255
        img_io.write_frame(path, np.full_like(frame, value))
    elif kind == "duplicate":
        if duplicate_of is None or not os.path.exists(duplicate_of):
            return False  # nothing to duplicate yet (first frame): no-op
        with open(duplicate_of, "rb") as src, open(path, "wb") as dst:
            dst.write(src.read())
    elif kind == "truncate":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 3))
    else:
        raise ValueError(f"unknown corruption kind {kind!r}")
    return True


def realism_post(cam_K: np.ndarray, params=None,
                 seed: int = 0) -> Callable[[str], None]:
    """Post-capture hook routing every successful frame through the
    photoreal sensor chain (`models.realism.degrade_frame`) — compose with
    :class:`FlakyCamera` for photometrically-degraded chaos runs."""
    from ..io import images as img_io
    from ..models import realism

    if params is None:
        params = realism.SensorParams()
    rng = np.random.default_rng(seed)

    def post(path: str) -> None:
        frame = img_io._imread_gray(path)
        img_io.write_frame(path, realism.degrade_frame(frame, cam_K, params,
                                                       rng))

    return post


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------


class FlakyCamera:
    """Schedule-driven fault wrapper over any ``capture(path)`` camera.

    ``injected`` logs every (path, attempt, kind) actually fired, so a
    chaos test can assert the health report records EXACTLY the injected
    faults and nothing else.
    """

    def __init__(self, inner, plan: FaultPlan,
                 post: Callable[[str], None] | None = None):
        self.inner = inner
        self.plan = plan
        self.post = post
        self.attempts: dict[str, int] = defaultdict(int)
        self.injected: list[tuple[str, int, str]] = []
        self._last_good: str | None = None

    @property
    def connected(self) -> bool:
        return bool(getattr(self.inner, "connected", True))

    def capture(self, path: str) -> bool:
        attempt = self.attempts[path]
        self.attempts[path] += 1
        kind = self.plan.fault_for(path, attempt)
        if kind == "timeout":
            self.injected.append((path, attempt, kind))
            log.debug("chaos: capture timeout injected (%s attempt %d)",
                      path, attempt)
            return False
        if not self.inner.capture(path):
            return False
        applied = False
        if kind is not None:
            applied = corrupt_frame_file(path, kind,
                                         duplicate_of=self._last_good)
        if applied:
            # Ledger records only faults that actually FIRED — a chaos
            # test asserting health == injected must not be lied to by a
            # no-op (duplicate with no prior frame).
            self.injected.append((path, attempt, kind))
            log.debug("chaos: frame corruption %r injected (%s attempt %d)",
                      kind, path, attempt)
        else:
            if self.post is not None:
                self.post(path)
            self._last_good = path
        return True


class FlakyTurntable:
    """Fault wrapper over any rotate/wait_for_done turntable.

    ``done_timeout``: the move happens but the DONE line is lost (the
    warn-and-continue case, `server/gui.py:760-762`). ``stuck``: the
    rotation command is swallowed — the table never moves, and DONE (for
    that move) never comes.
    """

    def __init__(self, inner, schedule: CallSchedule):
        self.inner = inner
        self.schedule = schedule
        self.injected: list[tuple[int, str]] = []
        self._pending: str | None = None

    @property
    def connected(self) -> bool:
        return bool(getattr(self.inner, "connected", True))

    @property
    def angle_deg(self) -> float:
        return self.inner.angle_deg

    def connect(self) -> bool:
        return self.inner.connect()

    def rotate(self, degrees: float) -> None:
        kind = self.schedule.next()
        self._pending = kind
        if kind is not None:
            self.injected.append((self.schedule.calls - 1, kind))
            if kind == "stuck":
                log.debug("chaos: rotation swallowed (%.1f°)", degrees)
                return
        self.inner.rotate(degrees)

    def wait_for_done(self, timeout: float = 30.0) -> bool:
        kind, self._pending = self._pending, None
        if kind == "stuck":
            return False        # nothing is moving; DONE never comes
        done = self.inner.wait_for_done(timeout)
        if kind == "done_timeout":
            return False        # move completed but the line was lost
        return done

    def close(self) -> None:
        self.inner.close()


class FlakyChannel:
    """Fault wrapper over a ``CommandChannel``-shaped object: a ``drop``
    fault swallows the trigger (the phone never saw the command — the
    poll response was lost in flight)."""

    def __init__(self, inner, schedule: CallSchedule):
        self.inner = inner
        self.schedule = schedule
        self.injected: list[tuple[int, str]] = []

    @property
    def connected(self) -> bool:
        return self.inner.connected

    def trigger_capture(self, save_path: str, timeout: float = 20.0) -> bool:
        kind = self.schedule.next()
        if kind is not None:
            self.injected.append((self.schedule.calls - 1, kind))
            log.debug("chaos: trigger dropped (%s)", save_path)
            return False
        return self.inner.trigger_capture(save_path, timeout=timeout)
