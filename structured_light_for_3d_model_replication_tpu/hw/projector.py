"""Projector display drivers: fullscreen window + virtual frame buffer.

The reference displays patterns through a borderless OpenCV window moved onto
the projector's extended desktop (`server/sl_system.py:22-37`:
``namedWindow`` / ``moveWindow(offset)`` / ``setWindowProperty(FULLSCREEN)``)
with a per-frame ``waitKey`` dwell (`server/sl_system.py:464-465`: 200 ms
scan, 250 ms calibration).

:class:`VirtualProjector` is the headless counterpart: it holds the currently
"displayed" frame in memory where the synthetic camera (and any test) can see
it, with the dwell collapsed to zero. Orchestration code is written against
the common ``show / close`` surface so the same scan loop drives either.
"""

from __future__ import annotations

import numpy as np

from ..config import ProjectorConfig
from ..utils.log import get_logger

log = get_logger(__name__)


class WindowProjector:
    """Physical projector via a fullscreen cv2 window on the extended
    desktop. Lazy cv2 import — everything else runs without OpenCV."""

    WINDOW_NAME = "slproj"

    def __init__(self, proj: ProjectorConfig = ProjectorConfig(),
                 offset_x: int | None = None, dwell_ms: int = 200):
        import cv2  # lazy: display host only

        self._cv2 = cv2
        self.proj = proj
        self.dwell_ms = dwell_ms
        offset = proj.offset_x if offset_x is None else offset_x
        cv2.namedWindow(self.WINDOW_NAME, cv2.WINDOW_NORMAL)
        cv2.moveWindow(self.WINDOW_NAME, offset, 0)
        cv2.setWindowProperty(self.WINDOW_NAME, cv2.WND_PROP_FULLSCREEN,
                              cv2.WINDOW_FULLSCREEN)

    def show(self, frame: np.ndarray, dwell_ms: int | None = None) -> None:
        """Display the frame and block for the projection dwell so the
        camera sees a settled image (`server/sl_system.py:464-465`)."""
        self._cv2.imshow(self.WINDOW_NAME, np.asarray(frame))
        # waitKey(0) means "block for a keypress" to OpenCV — clamp so a
        # zero dwell pumps the event loop without hanging the scan.
        self._cv2.waitKey(max(1, self.dwell_ms if dwell_ms is None
                              else dwell_ms))

    def close(self) -> None:
        self._cv2.destroyWindow(self.WINDOW_NAME)


class VirtualProjector:
    """In-memory projector: ``current_frame`` is what a virtual camera sees.

    ``history`` (optional) records every shown frame for protocol assertions
    in tests — e.g. that a scan displayed the 46 frames in order.
    """

    def __init__(self, proj: ProjectorConfig = ProjectorConfig(),
                 record: bool = False):
        self.proj = proj
        self.current_frame = np.zeros((proj.height, proj.width), np.uint8)
        self.record = record
        self.history: list[np.ndarray] = []
        self.closed = False

    def show(self, frame: np.ndarray, dwell_ms: int | None = None) -> None:
        frame = np.asarray(frame, np.uint8)
        if frame.shape[:2] != (self.proj.height, self.proj.width):
            raise ValueError(
                f"frame {frame.shape[:2]} != projector "
                f"{(self.proj.height, self.proj.width)}")
        self.current_frame = frame
        if self.record:
            self.history.append(frame.copy())

    def close(self) -> None:
        self.closed = True
