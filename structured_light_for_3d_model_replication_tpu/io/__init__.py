"""I/O layer: codecs (PLY/STL/.mat) + frame staging + session layout.

Replaces the reference's L1 persistence (SURVEY.md §1): hand-rolled ASCII PLY
(`server/sl_system.py:671-691`), Open3D cloud/mesh I/O
(`server/processing.py:19,49,181,248,310`), scipy .mat calibration container
(`server/sl_system.py:406-415,493`), and the dated directory layout
(`server/config.py:10`, `server/gui.py:31-40`).
"""

from .images import (  # noqa: F401
    device_stack,
    list_frames,
    load_stack,
    load_white_rgb,
    numeric_sort,
    write_frame,
)
from .layout import SessionLayout, frame_name, list_clouds  # noqa: F401
from .matcal import load_calibration_mat, save_calibration_mat  # noqa: F401
from .ply import PointCloud, read_ply, write_ply  # noqa: F401
from .png import decode_png, png_bytes, write_png  # noqa: F401
from .stl import TriangleMesh, read_stl, write_stl  # noqa: F401
