"""Captured-frame I/O: scan folders → device arrays.

The reference re-reads every frame from disk *inside* its decode loop, one
``cv2.imread`` per bit-plane pass (`server/sl_system.py:549-564`) — 2x44
full-frame reads interleaved with compute. Here the whole stack is decoded on
the host once (threaded — JPEG/PNG decode is CPU-bound and releases the GIL)
and staged to HBM in one ``jax.device_put``, so the TPU kernels see a single
(F, H, W) array.

Frame-number protocol (reference `server/sl_system.py:133-150`): file
``{idx:02d}`` with 01=white, 02=black, then (pattern, inverse) pairs for each
column bit, then each row bit.
"""

from __future__ import annotations

import concurrent.futures as _fut
import glob
import os
import re

import numpy as np

_EXTS = (".bmp", ".png", ".jpg", ".jpeg")


def _cv2():
    """cv2 if present, else None (this image ships PIL but not OpenCV)."""
    try:
        import cv2
        return cv2
    except ImportError:
        return None


def _imread_gray(path: str) -> np.ndarray:
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
        if img is None:
            raise IOError(f"failed to read image {path}")
        return img
    from PIL import Image

    return np.asarray(Image.open(path).convert("L"))


def _imread_rgb(path: str) -> np.ndarray:
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise IOError(f"failed to read image {path}")
        return img[..., ::-1].copy()  # BGR -> RGB at the boundary
    from PIL import Image

    return np.asarray(Image.open(path).convert("RGB"))


def list_frames(folder: str) -> list[str]:
    """Sorted frame files; tries each extension like the reference
    (`multi_point_cloud_process.py` globs .bmp then falls back to .png)."""
    for ext in _EXTS:
        files = sorted(glob.glob(os.path.join(folder, f"*{ext}")))
        if files:
            return files
    raise FileNotFoundError(f"no frames ({'/'.join(_EXTS)}) in {folder}")


def load_stack(
    folder: str,
    expected_frames: int | None = None,
    workers: int = 8,
) -> np.ndarray:
    """(F, H, W) uint8 grayscale stack from a capture folder."""
    files = list_frames(folder)
    if expected_frames is not None and len(files) != expected_frames:
        raise ValueError(
            f"{folder}: found {len(files)} frames, expected {expected_frames}"
        )
    with _fut.ThreadPoolExecutor(max_workers=workers) as ex:
        frames = list(ex.map(_imread_gray, files))
    shapes = {f.shape for f in frames}
    if len(shapes) != 1:
        raise ValueError(f"{folder}: inconsistent frame shapes {shapes}")
    return np.stack(frames)


def load_white_rgb(folder: str) -> np.ndarray:
    """(H, W, 3) uint8 RGB texture = frame 01 (the white reference), used for
    point colors (`server/sl_system.py:646-651`)."""
    return _imread_rgb(list_frames(folder)[0])


def device_stack(folder: str, expected_frames: int | None = None):
    """Load + one host→HBM transfer. Returns a (F, H, W) uint8 device array."""
    import jax

    return jax.device_put(load_stack(folder, expected_frames))


def write_frame(path: str, img: np.ndarray) -> None:
    """uint8 (H, W) or (H, W, 3) RGB → file (extension picks the codec)."""
    cv2 = _cv2()
    if cv2 is not None:
        out = img[..., ::-1] if img.ndim == 3 else img  # RGB -> BGR
        if not cv2.imwrite(path, out):
            raise IOError(f"failed to write image {path}")
        return
    from PIL import Image

    Image.fromarray(np.asarray(img, np.uint8)).save(path)


_NUM_RE = re.compile(r"(\d+)")


def numeric_sort(paths: list[str]) -> list[str]:
    """Sort by the last integer in the basename, then lexically — the legacy
    fix for '10.ply' < '2.ply' (`Old/new360Merge.py:7-20`)."""
    def key(p):
        nums = _NUM_RE.findall(os.path.basename(p))
        return (int(nums[-1]) if nums else -1, p)

    return sorted(paths, key=key)
