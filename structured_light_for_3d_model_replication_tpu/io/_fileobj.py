"""Path-or-file-object plumbing shared by the binary codecs.

The PLY/STL codecs accept either a filesystem path (opened and closed
here) or an already-open binary file object (the caller's — e.g. the
serving layer's in-memory buffers streaming results to HTTP responses).
One owner for that contract, imported by both codecs.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def binary_sink(path_or_file):
    """Yield a binary writable for a path or an already-open file object
    (only paths are opened/closed here — a caller's buffer stays theirs)."""
    if hasattr(path_or_file, "write"):
        yield path_or_file
    else:
        with open(path_or_file, "wb") as f:
            yield f


@contextlib.contextmanager
def binary_source(path_or_file):
    if hasattr(path_or_file, "read"):
        yield path_or_file
    else:
        with open(path_or_file, "rb") as f:
            yield f
