"""Dependency-free RGB8 PNG encode/decode (stdlib zlib + struct).

Factored out of `viz` so every producer of rendered previews — the
offline viewer, the splat render endpoints (`serve/`), ``cli render``
and the streaming ``--preview-render`` lane — shares ONE encoder, and
so in-memory consumers (HTTP payloads, result formats) get bytes
without a filesystem round trip. ``decode_png`` reads back what
``png_bytes`` wrote (filter 0 only) — a round-trip/testing helper, not
a general decoder.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np


def png_bytes(image: np.ndarray) -> bytes:
    """(H, W, 3) uint8 → PNG file bytes."""
    img = np.ascontiguousarray(np.asarray(image, np.uint8))
    if img.ndim != 3 or img.shape[2] != 3:
        raise ValueError(f"expected (H, W, 3) uint8, got {img.shape}")
    h, w = img.shape[:2]
    raw = np.concatenate(
        [np.zeros((h, 1), np.uint8), img.reshape(h, w * 3)], axis=1
    ).tobytes()

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload)))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)  # 8-bit RGB
    return (b"\x89PNG\r\n\x1a\n"
            + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw, 6))
            + chunk(b"IEND", b""))


def write_png(path, image: np.ndarray) -> None:
    """(H, W, 3) uint8 → PNG file (path or binary file object)."""
    data = png_bytes(image)
    if hasattr(path, "write"):
        path.write(data)
        return
    with open(path, "wb") as f:
        f.write(data)


def decode_png(data: bytes) -> np.ndarray:
    """PNG bytes (as written by :func:`png_bytes`) → (H, W, 3) uint8."""
    if data[:8] != b"\x89PNG\r\n\x1a\n":
        raise ValueError("not a PNG")
    pos, w, h, idat = 8, 0, 0, b""
    while pos < len(data):
        (ln,) = struct.unpack(">I", data[pos:pos + 4])
        tag = data[pos + 4:pos + 8]
        payload = data[pos + 8:pos + 8 + ln]
        if tag == b"IHDR":
            w, h, depth, ctype = struct.unpack(">IIBB", payload[:10])
            if depth != 8 or ctype != 2:
                raise ValueError("only 8-bit RGB supported")
        elif tag == b"IDAT":
            idat += payload
        pos += 12 + ln
    rows = np.frombuffer(zlib.decompress(idat),
                         np.uint8).reshape(h, 1 + w * 3)
    if np.any(rows[:, 0]):
        raise ValueError("only filter 0 supported")
    return rows[:, 1:].reshape(h, w, 3).copy()
