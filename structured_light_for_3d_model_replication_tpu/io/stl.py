"""STL mesh codec (binary read/write, ASCII write).

Replaces ``o3d.io.write_triangle_mesh`` as used for the final printable
output (`server/processing.py:248,310`). Binary STL is the default (5x
smaller, one structured ``tofile``); ASCII provided for inspection.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ._fileobj import binary_sink

_BIN_DT = np.dtype([
    ("normal", "<f4", (3,)),
    ("v0", "<f4", (3,)),
    ("v1", "<f4", (3,)),
    ("v2", "<f4", (3,)),
    ("attr", "<u2"),
])


@dataclasses.dataclass
class TriangleMesh:
    """Host-side mesh container (analogue of ``o3d.geometry.TriangleMesh``)."""

    vertices: np.ndarray                      # (V, 3) float32
    faces: np.ndarray                         # (F, 3) int32
    vertex_normals: np.ndarray | None = None  # (V, 3) float32
    vertex_colors: np.ndarray | None = None   # (V, 3) uint8

    def face_normals(self) -> np.ndarray:
        v = self.vertices
        f = self.faces
        n = np.cross(v[f[:, 1]] - v[f[:, 0]], v[f[:, 2]] - v[f[:, 0]])
        ln = np.linalg.norm(n, axis=-1, keepdims=True)
        return (n / np.maximum(ln, 1e-12)).astype(np.float32)

    def compute_vertex_normals(self) -> np.ndarray:
        """Area-weighted vertex normals (``compute_vertex_normals``,
        `server/processing.py:247,307`); also stored on self."""
        v = self.vertices
        f = self.faces
        fn = np.cross(v[f[:, 1]] - v[f[:, 0]], v[f[:, 2]] - v[f[:, 0]])
        vn = np.zeros_like(v)
        for k in range(3):  # scatter-add, 3 passes
            np.add.at(vn, f[:, k], fn)
        ln = np.linalg.norm(vn, axis=-1, keepdims=True)
        self.vertex_normals = (vn / np.maximum(ln, 1e-12)).astype(np.float32)
        return self.vertex_normals


def write_stl(path, mesh: TriangleMesh, binary: bool = True) -> None:
    """``path`` is a filesystem path or (binary mode only) an open binary
    file object — the serving layer streams STL results straight to HTTP
    responses."""
    v = np.asarray(mesh.vertices, np.float32)
    f = np.asarray(mesh.faces, np.int64)
    fn = mesh.face_normals()
    if binary:
        rec = np.zeros(f.shape[0], dtype=_BIN_DT)
        rec["normal"] = fn
        rec["v0"] = v[f[:, 0]]
        rec["v1"] = v[f[:, 1]]
        rec["v2"] = v[f[:, 2]]
        with binary_sink(path) as out:
            out.write(b"\0" * 80)
            out.write(np.uint32(f.shape[0]).tobytes())
            # Buffer-protocol write, not tofile: the sink may be an
            # in-memory buffer, and rec.data avoids tobytes's full
            # transient copy (~50 MB on a 1M-face mesh).
            out.write(rec.data)
    else:
        with open(path, "w") as out:
            out.write("solid mesh\n")
            tri = v[f]  # (F, 3, 3)
            for i in range(f.shape[0]):
                out.write(f"facet normal {fn[i,0]:e} {fn[i,1]:e} {fn[i,2]:e}\n"
                          "  outer loop\n")
                for k in range(3):
                    out.write(f"    vertex {tri[i,k,0]:e} {tri[i,k,1]:e} "
                              f"{tri[i,k,2]:e}\n")
                out.write("  endloop\nendfacet\n")
            out.write("endsolid mesh\n")


def read_stl(path: str) -> TriangleMesh:
    """Read a binary or ASCII STL. Duplicate vertices are merged exactly
    (bit-equal), so a write/read roundtrip restores shared topology."""
    with open(path, "rb") as f:
        head = f.read(80)
        # ASCII files start with 'solid' AND contain 'facet' soon after; some
        # binary writers also start the comment header with 'solid'.
        if head[:5] == b"solid" and b"facet" in head + f.read(200):
            return _read_stl_ascii(path)
        f.seek(80)
        n = int(np.frombuffer(f.read(4), "<u4")[0])
        rec = np.fromfile(f, dtype=_BIN_DT, count=n)
        if rec.shape[0] != n:
            raise ValueError(
                f"{path}: truncated binary STL ({rec.shape[0]}/{n} facets)")
    tris = np.stack([rec["v0"], rec["v1"], rec["v2"]], axis=1)  # (F, 3, 3)
    return _mesh_from_tris(tris)


def _mesh_from_tris(tris: np.ndarray) -> TriangleMesh:
    flat = np.ascontiguousarray(tris.reshape(-1, 3), np.float32)
    verts, inv = np.unique(flat.view([("", "<f4")] * 3), return_inverse=True)
    vertices = verts.view("<f4").reshape(-1, 3)
    faces = inv.reshape(-1, 3).astype(np.int32)
    return TriangleMesh(vertices.astype(np.float32), faces)


def _read_stl_ascii(path: str) -> TriangleMesh:
    verts = []
    with open(path) as f:
        for line in f:
            tok = line.split()
            if tok and tok[0] == "vertex":
                verts.append([float(tok[1]), float(tok[2]), float(tok[3])])
    if len(verts) % 3:
        raise ValueError(f"{path}: ASCII STL vertex count not divisible by 3")
    return _mesh_from_tris(np.asarray(verts, np.float32).reshape(-1, 3, 3))
