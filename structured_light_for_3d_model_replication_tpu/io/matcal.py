"""Calibration container I/O — MATLAB ``.mat`` interop.

The reference persists calibration as a ``.mat`` with keys
``{Nc, Oc, wPlaneCol, wPlaneRow, cam_K, proj_K, R, T}`` in these exact
layouts (`server/sl_system.py:406-415`):

* ``Nc``        (3, H*W)  — camera rays, transposed flat grid
* ``Oc``        (3, 1)
* ``wPlaneCol`` (4, proj_w) — stored TRANSPOSED (written ``wPlaneCol.T``)
* ``wPlaneRow`` (4, proj_h) — ditto
* ``cam_K``/``proj_K`` (3, 3), ``R`` (3, 3), ``T`` (3, 1)

Files written here load in the reference pipeline and vice versa, so an
existing calibration survives a backend switch (`server/gui.py:543-547` reuses
the .mat across sessions).
"""

from __future__ import annotations

import numpy as np
import scipy.io

from ..ops.triangulate import Calibration, camera_rays, make_calibration

_KEYS = ("Nc", "Oc", "wPlaneCol", "wPlaneRow", "cam_K", "proj_K", "R", "T")


def save_calibration_mat(path: str, calib: Calibration) -> None:
    """Serialize a device-resident Calibration into the reference layout."""
    Nc = np.asarray(calib.Nc, np.float64).reshape(-1, 3).T  # (3, H*W)
    scipy.io.savemat(path, {
        "Nc": Nc,
        "Oc": np.asarray(calib.Oc, np.float64).reshape(3, 1),
        "wPlaneCol": np.asarray(calib.plane_cols, np.float64).T,  # (4, W)
        "wPlaneRow": np.asarray(calib.plane_rows, np.float64).T,  # (4, H)
        "cam_K": np.asarray(calib.cam_K, np.float64),
        "proj_K": np.asarray(calib.proj_K, np.float64),
        "R": np.asarray(calib.R, np.float64),
        "T": np.asarray(calib.T, np.float64).reshape(3, 1),
    })


def load_calibration_mat(
    path: str,
    cam_height: int,
    cam_width: int,
) -> Calibration:
    """Load a reference-layout ``.mat`` into a device Calibration.

    The stored flat ray grid carries no (H, W); callers pass the capture
    resolution. If the stored grid size disagrees (the reference hits this
    when scan resolution differs from calibration resolution), rays are
    regenerated from ``cam_K`` exactly as the reference does
    (`server/sl_system.py:605-621`).
    """
    data = scipy.io.loadmat(path)
    missing = [k for k in _KEYS if k not in data]
    if missing:
        raise ValueError(f"{path}: calibration file missing keys {missing}")

    cam_K = np.asarray(data["cam_K"], np.float32)
    proj_K = np.asarray(data["proj_K"], np.float32)
    R = np.asarray(data["R"], np.float32)
    T = np.asarray(data["T"], np.float32).reshape(3)

    Nc_flat = np.asarray(data["Nc"], np.float32)  # (3, H*W)
    if Nc_flat.shape[1] == cam_height * cam_width:
        Nc = Nc_flat.T.reshape(cam_height, cam_width, 3)
    else:
        Nc = np.asarray(camera_rays(cam_K, cam_height, cam_width))

    plane_cols = np.asarray(data["wPlaneCol"], np.float32).T  # (W, 4)
    plane_rows = np.asarray(data["wPlaneRow"], np.float32).T  # (H, 4)

    base = make_calibration(cam_K, proj_K, R, T, cam_height, cam_width,
                            proj_width=plane_cols.shape[0],
                            proj_height=plane_rows.shape[0])
    # Prefer the planes/rays as stored (they are the calibration artifact);
    # make_calibration supplies consistent dtypes/devices for the rest.
    return base._replace(
        Nc=_as_dev(Nc),
        plane_cols=_as_dev(plane_cols),
        plane_rows=_as_dev(plane_rows),
    )


def _as_dev(x):
    import jax.numpy as jnp

    return jnp.asarray(x, jnp.float32)
