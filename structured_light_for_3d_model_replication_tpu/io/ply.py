"""PLY point-cloud codec (reader + writer, ASCII and binary_little_endian).

Replaces two things in the reference:

* the hand-rolled per-point ASCII writer (`server/sl_system.py:671-691`,
  `multi_point_cloud_process.py:121-133`) — a pure-Python loop over millions of
  points. Here ASCII goes through one ``np.savetxt``-style vectorized format
  and binary through a single structured-array ``tofile``, both O(N) C-speed.
* Open3D's ``o3d.io.read_point_cloud`` / ``write_point_cloud``
  (`server/processing.py:19,49,181`).

The reference's ASCII layout (x y z at %.4f + uchar red green blue) is the
default ASCII schema, so files interchange with clouds produced by the
reference. NOTE the reference swizzles BGR→RGB *at write time* because its
textures come from OpenCV; this codec stores colors as given (RGB in, RGB out).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ._fileobj import binary_sink, binary_source

_PLY_TO_NP = {
    "char": "i1", "int8": "i1",
    "uchar": "u1", "uint8": "u1",
    "short": "i2", "int16": "i2",
    "ushort": "u2", "uint16": "u2",
    "int": "i4", "int32": "i4",
    "uint": "u4", "uint32": "u4",
    "float": "f4", "float32": "f4",
    "double": "f8", "float64": "f8",
}


@dataclasses.dataclass
class PointCloud:
    """Host-side cloud container: the framework's analogue of
    ``o3d.geometry.PointCloud``. Device code operates on the raw arrays."""

    points: np.ndarray                   # (N, 3) float32
    colors: np.ndarray | None = None     # (N, 3) uint8
    normals: np.ndarray | None = None    # (N, 3) float32

    def __len__(self) -> int:
        return int(self.points.shape[0])


def _parse_header(f):
    """Returns (fmt, n_vertex, vertex_props, skip_elements) after end_header."""
    magic = f.readline().strip()
    if magic != b"ply":
        raise ValueError("not a PLY file")
    fmt = None
    elements = []  # list of (name, count, [(type, name), ...])
    cur = None
    while True:
        line = f.readline()
        if not line:
            raise ValueError("unterminated PLY header")
        tok = line.strip().split()
        if not tok or tok[0] == b"comment":
            continue
        if tok[0] == b"format":
            fmt = tok[1].decode()
        elif tok[0] == b"element":
            cur = (tok[1].decode(), int(tok[2]), [])
            elements.append(cur)
        elif tok[0] == b"property":
            if tok[1] == b"list":
                # list property (faces); represented as ('list', t_count, t_item, name)
                cur[2].append(("list", tok[2].decode(), tok[3].decode(),
                               tok[4].decode()))
            else:
                cur[2].append((tok[1].decode(), tok[2].decode()))
        elif tok[0] == b"end_header":
            break
    return fmt, elements


def read_ply(path) -> PointCloud:
    """Read a PLY point cloud (vertex element; faces, if any, are skipped).
    ``path`` is a filesystem path or an open binary file object."""
    with binary_source(path) as f:
        fmt, elements = _parse_header(f)
        vertex = next((e for e in elements if e[0] == "vertex"), None)
        if vertex is None:
            raise ValueError(f"{path}: no vertex element")
        _, n, props = vertex
        for p in props:
            if p[0] == "list":
                raise ValueError("list property on vertex element unsupported")
        names = [p[1] for p in props]
        if fmt == "ascii":
            # Vertex is the first element in every writer we care about.
            raw = np.loadtxt(f, dtype=np.float64, max_rows=n, ndmin=2)
            cols = {nm: raw[:, i] for i, nm in enumerate(names)}
        elif fmt == "binary_little_endian":
            dt = np.dtype([(nm, "<" + _PLY_TO_NP[t]) for t, nm in props])
            # frombuffer on an explicit read, not fromfile: the source may
            # be an in-memory buffer (fromfile needs a real fileno).
            raw = np.frombuffer(f.read(dt.itemsize * n), dtype=dt, count=n)
            cols = {nm: raw[nm] for nm in names}
        else:
            raise ValueError(f"unsupported PLY format {fmt!r}")

    pts = np.stack([cols["x"], cols["y"], cols["z"]], axis=-1).astype(np.float32)
    colors = normals = None
    if all(k in cols for k in ("red", "green", "blue")):
        colors = np.stack([cols["red"], cols["green"], cols["blue"]],
                          axis=-1).astype(np.uint8)
    if all(k in cols for k in ("nx", "ny", "nz")):
        normals = np.stack([cols["nx"], cols["ny"], cols["nz"]],
                           axis=-1).astype(np.float32)
    return PointCloud(pts, colors, normals)


def write_ply(
    path,
    cloud: PointCloud,
    binary: bool = True,
) -> None:
    """Write a point cloud. Binary little-endian by default; ASCII matches the
    reference's schema (xyz %.4f + uchar rgb) for drop-in interop.

    ``path`` is a filesystem path or an open binary file object (the
    serving layer streams PLY results to HTTP clients without touching
    disk)."""
    pts = np.asarray(cloud.points, np.float32)
    n = pts.shape[0]
    fields = [("x", "<f4"), ("y", "<f4"), ("z", "<f4")]
    header_props = ["property float x", "property float y", "property float z"]
    if cloud.normals is not None:
        fields += [("nx", "<f4"), ("ny", "<f4"), ("nz", "<f4")]
        header_props += ["property float nx", "property float ny",
                         "property float nz"]
    if cloud.colors is not None:
        fields += [("red", "u1"), ("green", "u1"), ("blue", "u1")]
        header_props += ["property uchar red", "property uchar green",
                         "property uchar blue"]

    header = (
        "ply\n"
        f"format {'binary_little_endian' if binary else 'ascii'} 1.0\n"
        f"element vertex {n}\n" + "\n".join(header_props) + "\nend_header\n"
    )

    with binary_sink(path) as f:
        f.write(header.encode())
        if binary:
            rec = np.empty(n, dtype=np.dtype(fields))
            rec["x"], rec["y"], rec["z"] = pts[:, 0], pts[:, 1], pts[:, 2]
            if cloud.normals is not None:
                nrm = np.asarray(cloud.normals, np.float32)
                rec["nx"], rec["ny"], rec["nz"] = nrm[:, 0], nrm[:, 1], nrm[:, 2]
            if cloud.colors is not None:
                col = np.asarray(cloud.colors, np.uint8)
                rec["red"], rec["green"], rec["blue"] = (
                    col[:, 0], col[:, 1], col[:, 2])
            # Buffer-protocol write, not tofile: the sink may be an
            # in-memory buffer (tofile needs a real fileno), and rec.data
            # avoids tobytes's full transient copy on multi-MB clouds.
            f.write(rec.data)
        else:
            parts = ["%.4f %.4f %.4f"]
            arrays = [pts]
            if cloud.normals is not None:
                parts.append("%.4f %.4f %.4f")
                arrays.append(np.asarray(cloud.normals, np.float32))
            if cloud.colors is not None:
                parts.append("%d %d %d")
                arrays.append(np.asarray(cloud.colors))
            full = np.concatenate([a.astype(np.float64) for a in arrays], axis=1)
            np.savetxt(f, full, fmt=" ".join(parts))


# ---------------------------------------------------------------------------
# Triangle meshes (vertex + face elements) — the vertex-COLORED mesh
# carrier STL cannot be (fusion/ extracts per-vertex RGB; docs/MESHING.md)
# ---------------------------------------------------------------------------


def write_ply_mesh(path, mesh, binary: bool = True) -> None:
    """Write a :class:`..io.stl.TriangleMesh` as PLY (vertex + face
    elements), carrying per-vertex normals and RGB when present — the
    colored-mesh output path of ``cli mesh`` and ``serve``'s
    ``mesh_ply`` result format. ``path`` is a filesystem path or an
    open binary file object (the serving layer streams to HTTP)."""
    v = np.asarray(mesh.vertices, np.float32)
    faces = np.asarray(mesh.faces, np.int32)
    n, nf = v.shape[0], faces.shape[0]
    fields = [("x", "<f4"), ("y", "<f4"), ("z", "<f4")]
    props = ["property float x", "property float y", "property float z"]
    normals = getattr(mesh, "vertex_normals", None)
    colors = getattr(mesh, "vertex_colors", None)
    if normals is not None:
        fields += [("nx", "<f4"), ("ny", "<f4"), ("nz", "<f4")]
        props += ["property float nx", "property float ny",
                  "property float nz"]
    if colors is not None:
        fields += [("red", "u1"), ("green", "u1"), ("blue", "u1")]
        props += ["property uchar red", "property uchar green",
                  "property uchar blue"]
    header = (
        "ply\n"
        f"format {'binary_little_endian' if binary else 'ascii'} 1.0\n"
        f"element vertex {n}\n" + "\n".join(props) + "\n"
        f"element face {nf}\n"
        "property list uchar int vertex_indices\nend_header\n"
    )
    with binary_sink(path) as f:
        f.write(header.encode())
        if binary:
            rec = np.empty(n, dtype=np.dtype(fields))
            rec["x"], rec["y"], rec["z"] = v[:, 0], v[:, 1], v[:, 2]
            if normals is not None:
                nr = np.asarray(normals, np.float32)
                rec["nx"], rec["ny"], rec["nz"] = nr[:, 0], nr[:, 1], \
                    nr[:, 2]
            if colors is not None:
                c = np.asarray(colors, np.uint8)
                rec["red"], rec["green"], rec["blue"] = c[:, 0], c[:, 1], \
                    c[:, 2]
            f.write(rec.data)
            frec = np.empty(nf, dtype=np.dtype([("n", "u1"),
                                                ("v", "<i4", (3,))]))
            frec["n"] = 3
            frec["v"] = faces
            f.write(frec.data)
        else:
            parts = ["%.6f %.6f %.6f"]
            arrays = [v.astype(np.float64)]
            if normals is not None:
                parts.append("%.4f %.4f %.4f")
                arrays.append(np.asarray(normals, np.float64))
            if colors is not None:
                parts.append("%d %d %d")
                arrays.append(np.asarray(colors, np.float64))
            np.savetxt(f, np.concatenate(arrays, axis=1),
                       fmt=" ".join(parts))
            np.savetxt(f, np.concatenate(
                [np.full((nf, 1), 3, np.int64),
                 faces.astype(np.int64)], axis=1), fmt="%d")


def read_ply_mesh(path):
    """Read a PLY triangle mesh (vertex + triangular face elements) into
    a :class:`..io.stl.TriangleMesh`, recovering per-vertex normals/RGB
    when present. Faces must be triangles (this codec's writers only
    emit triangles; a mixed-arity file raises)."""
    from .stl import TriangleMesh

    with binary_source(path) as f:
        fmt, elements = _parse_header(f)
        vertex = next((e for e in elements if e[0] == "vertex"), None)
        face = next((e for e in elements if e[0] == "face"), None)
        if vertex is None or face is None:
            raise ValueError(f"{path}: expected vertex + face elements")
        _, n, props = vertex
        for p in props:
            if p[0] == "list":
                raise ValueError(
                    f"{path}: list property on vertex element unsupported")
        names = [p[1] for p in props]
        _, nf, fprops = face
        flist = next((p for p in fprops if p[0] == "list"), None)
        if flist is None:
            raise ValueError(f"{path}: face element has no list property")
        if fmt == "ascii":
            vraw = np.loadtxt(f, dtype=np.float64, max_rows=n, ndmin=2)
            cols = {nm: vraw[:, i] for i, nm in enumerate(names)}
            fraw = np.loadtxt(f, dtype=np.int64, max_rows=nf, ndmin=2)
            if fraw.size and not np.all(fraw[:, 0] == 3):
                raise ValueError(f"{path}: non-triangle faces")
            faces = fraw[:, 1:4].astype(np.int32) if fraw.size else \
                np.zeros((0, 3), np.int32)
        elif fmt == "binary_little_endian":
            dt = np.dtype([(nm, "<" + _PLY_TO_NP[t]) for t, nm in props])
            vraw = np.frombuffer(f.read(dt.itemsize * n), dtype=dt,
                                 count=n)
            cols = {nm: vraw[nm] for nm in names}
            fdt = np.dtype([("n", _PLY_TO_NP[flist[1]]),
                            ("v", "<" + _PLY_TO_NP[flist[2]], (3,))])
            fraw = np.frombuffer(f.read(fdt.itemsize * nf), dtype=fdt,
                                 count=nf)
            if nf and not np.all(fraw["n"] == 3):
                raise ValueError(f"{path}: non-triangle faces")
            faces = fraw["v"].astype(np.int32)
        else:
            raise ValueError(f"unsupported PLY format {fmt!r}")

    verts = np.stack([cols["x"], cols["y"], cols["z"]],
                     axis=-1).astype(np.float32)
    mesh = TriangleMesh(vertices=verts, faces=faces)
    if all(k in cols for k in ("nx", "ny", "nz")):
        mesh.vertex_normals = np.stack(
            [cols["nx"], cols["ny"], cols["nz"]], axis=-1).astype(
            np.float32)
    if all(k in cols for k in ("red", "green", "blue")):
        mesh.vertex_colors = np.stack(
            [cols["red"], cols["green"], cols["blue"]], axis=-1).astype(
            np.uint8)
    return mesh
