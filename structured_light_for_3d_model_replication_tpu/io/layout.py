"""Filesystem session layout — the checkpoint/resume system.

The reference's durability model is its directory tree: every stage writes
files the next stage re-reads, so any stage can resume from disk
(SURVEY.md §5; layout constants at `server/gui.py:31-40,82-83,703-740`):

    {dd_mm_YYYY}_3Dscan/
      calib/pose_N/{01..NN}.png     calibration captures, one folder per pose
      calib/calib.mat               the stereo calibration artifact
      scans/{name}/{01..NN}.bmp     single scans
      scans_360/{base}_{deg}deg_AUTO/{base}_{angle}deg_scan/   auto-scan stops

This module makes that layout first-class: typed paths, enumeration with
numeric ordering, and resume detection (which stops already have frames /
clouds) so an interrupted 360° run restarts where it left off.
"""

from __future__ import annotations

import dataclasses
import glob
import os

from ..config import dated_output_root
from .images import numeric_sort


def frame_name(idx: int, ext: str = "png") -> str:
    """1-based protocol index → filename (`{idx:02d}` per the reference's
    capture numbering `server/sl_system.py:158-178,436-451`)."""
    return f"{idx:02d}.{ext}"


@dataclasses.dataclass(frozen=True)
class SessionLayout:
    root: str

    @classmethod
    def today(cls, base: str = ".") -> "SessionLayout":
        return cls(dated_output_root(base))

    # -- calibration ------------------------------------------------------
    @property
    def calib_dir(self) -> str:
        return os.path.join(self.root, "calib")

    @property
    def calib_mat(self) -> str:
        return os.path.join(self.calib_dir, "calib.mat")

    def pose_dir(self, pose: int) -> str:
        return os.path.join(self.calib_dir, f"pose_{pose}")

    def pose_dirs(self) -> list[str]:
        return numeric_sort(glob.glob(os.path.join(self.calib_dir, "pose_*")))

    # -- single scans -----------------------------------------------------
    @property
    def scans_dir(self) -> str:
        return os.path.join(self.root, "scans")

    def scan_dir(self, name: str) -> str:
        return os.path.join(self.scans_dir, name)

    # -- 360° auto scans --------------------------------------------------
    @property
    def scans_360_dir(self) -> str:
        return os.path.join(self.root, "scans_360")

    def auto_session_dir(self, base: str, degrees: float) -> str:
        return os.path.join(self.scans_360_dir,
                            f"{base}_{degrees:g}deg_AUTO")

    def stop_dir(self, base: str, degrees: float, angle: float) -> str:
        return os.path.join(self.auto_session_dir(base, degrees),
                            f"{base}_{angle:g}deg_scan")

    def stop_dirs(self, base: str, degrees: float) -> list[str]:
        pat = os.path.join(self.auto_session_dir(base, degrees), "*_scan")
        return numeric_sort(glob.glob(pat))

    # -- resume -----------------------------------------------------------
    def completed_stops(self, base: str, degrees: float,
                        expected_frames: int) -> list[str]:
        """Stop folders that already hold a full frame stack — the resume
        point for an interrupted auto-scan."""
        done = []
        for d in self.stop_dirs(base, degrees):
            n = 0
            for ext in ("bmp", "png", "jpg", "jpeg"):
                n = max(n, len(glob.glob(os.path.join(d, f"*.{ext}"))))
            if n >= expected_frames:
                done.append(d)
        return done

    def ensure(self) -> "SessionLayout":
        for d in (self.calib_dir, self.scans_dir, self.scans_360_dir):
            os.makedirs(d, exist_ok=True)
        return self


def list_clouds(folder: str) -> list[str]:
    """All .ply files, numerically ordered (`server/processing.py:121-129`
    sorts lexically; the legacy numeric sort is strictly better)."""
    return numeric_sort(glob.glob(os.path.join(folder, "*.ply")))
