"""structured_light_for_3d_model_replication_tpu — TPU-native structured-light 3D scanning.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the reference
scan-to-print pipeline (Nuttoty/Structured_Light_for_3D_Model_Replication):
Gray-code pattern projection, per-pixel decode, ray-plane triangulation,
point-cloud cleanup, multi-view registration/merge, and surface meshing —
re-designed TPU-first (dense masked compute, static shapes, shard_map over
device meshes) rather than translated from the reference's NumPy/Open3D code.

Subpackages
-----------
ops       — jitted compute kernels (patterns, decode, triangulate, pointcloud,
            registration, meshing)
models    — pipelines that compose the ops (scan pipeline, oracle, synthetic
            scanner), plus calibration
parallel  — device-mesh / sharding layer (batch DP over scans, spatial tiling)
io        — PLY/STL/.mat/image-stack codecs
hw        — hardware edge (capture server, turntable driver)
utils     — profiling, misc
"""

__version__ = "0.1.0"

from . import config  # noqa: F401
