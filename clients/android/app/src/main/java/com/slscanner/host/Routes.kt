// Route table binding the HTTP server to the camera engine — the wire
// contract the PC's PushCamera speaks (structured_light_for_3d_model_replication_tpu/hw/camera.py).
package com.slscanner.host

import java.net.URLEncoder

class Routes(
    private val camera: CameraController,
    private val onCapture: () -> Unit,
) {
    fun handle(req: Request): Response = when {
        req.path == "/status" && req.method == "GET" -> status()
        req.path == "/capabilities" && req.method == "GET" ->
            Response.json(camera.capabilities())
        req.path == "/settings" && req.method == "POST" -> settings(req)
        req.path == "/capture/jpeg" && req.method == "POST" -> capture()
        else -> Response.error(404, "no route ${req.method} ${req.path}")
    }

    private fun status(): Response = Response.json(
        Json.obj(
            "camera" to if (camera.isOpen) "ready" else "closed",
            "settings" to settingsJson(),
        ).toString())

    private fun settingsJson() = Json.obj(
        "ae" to if (camera.settings.aeOn) "on" else "off",
        "exposure_ns" to camera.settings.exposureNs,
        "iso" to camera.settings.iso,
        "af" to if (camera.settings.afOn) "on" else "off",
        "focus_diopters" to camera.settings.focusDiopters,
        "awb" to if (camera.settings.awbAuto) "auto" else "off",
        "zoom" to camera.settings.zoom,
        "stabilization" to
            if (camera.settings.stabilization) "on" else "off",
        "jpeg_quality" to camera.settings.jpegQuality,
        "target_width" to camera.settings.targetWidth,
    )

    private fun settings(req: Request): Response {
        val body = Json.parse(req.body)
        val s = camera.settings
        if (body.has("ae")) s.aeOn = body.getString("ae") != "off"
        if (body.has("exposure_ns"))
            s.exposureNs = body.getLong("exposure_ns")
        if (body.has("iso")) s.iso = body.getInt("iso")
        if (body.has("af")) s.afOn = body.getString("af") != "off"
        if (body.has("focus_diopters"))
            s.focusDiopters = body.getDouble("focus_diopters").toFloat()
        if (body.has("awb")) s.awbAuto = body.getString("awb") != "off"
        if (body.has("zoom")) s.zoom = body.getDouble("zoom").toFloat()
        if (body.has("stabilization"))
            s.stabilization = body.getString("stabilization") == "on"
        if (body.has("jpeg_quality"))
            s.jpegQuality = body.getInt("jpeg_quality").coerceIn(1, 100)
        if (body.has("target_width")) {
            s.targetWidth = body.getInt("target_width")
            camera.close()  // re-pick the JPEG stream size on next open
        }
        return Response.json(settingsJson().toString())
    }

    private fun capture(): Response {
        val (bytes, meta) = camera.captureJpeg()
        onCapture()
        return Response(
            status = 200,
            contentType = "image/jpeg",
            body = bytes,
            extraHeaders = mapOf(
                "X-Capture-Meta" to URLEncoder.encode(meta, "UTF-8")),
        )
    }
}
