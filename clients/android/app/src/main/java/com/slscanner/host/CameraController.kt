// Camera2 capture engine with full manual control.
//
// Structured-light needs REPEATABLE exposure: auto-exposure re-meters every
// projected stripe pattern (dark frames meter bright, bright frames meter
// dark), which destroys the decode thresholds. So the host supports AE/AF/AWB
// fully off with explicit exposure_ns / iso / focus_diopters, applied to a
// single still-capture request per /capture/jpeg call.
package com.slscanner.host

import android.content.Context
import android.graphics.ImageFormat
import android.hardware.camera2.CameraCaptureSession
import android.hardware.camera2.CameraCharacteristics
import android.hardware.camera2.CameraDevice
import android.hardware.camera2.CameraManager
import android.hardware.camera2.CaptureRequest
import android.hardware.camera2.TotalCaptureResult
import android.media.ImageReader
import android.os.Handler
import android.os.HandlerThread
import android.util.Log
import android.util.Size
import java.util.concurrent.CountDownLatch
import java.util.concurrent.TimeUnit

data class Settings(
    var aeOn: Boolean = true,
    var exposureNs: Long? = null,
    var iso: Int? = null,
    var afOn: Boolean = true,
    var focusDiopters: Float? = null,
    var awbAuto: Boolean = true,
    var zoom: Float = 1.0f,
    var stabilization: Boolean = false,
    var jpegQuality: Int = 95,
    var targetWidth: Int = 1600,
)

class CameraController(private val context: Context) {
    private val tag = "SlCamera"
    private val thread = HandlerThread("camera").apply { start() }
    private val handler = Handler(thread.looper)

    val settings = Settings()

    private var device: CameraDevice? = null
    private var session: CameraCaptureSession? = null
    private var reader: ImageReader? = null
    private lateinit var characteristics: CameraCharacteristics
    private var cameraId: String = "0"

    val isOpen get() = session != null

    @Synchronized
    fun ensureOpen() {
        if (session != null) return
        val manager =
            context.getSystemService(Context.CAMERA_SERVICE) as CameraManager
        cameraId = manager.cameraIdList.firstOrNull { id ->
            manager.getCameraCharacteristics(id)
                .get(CameraCharacteristics.LENS_FACING) ==
                CameraCharacteristics.LENS_FACING_BACK
        } ?: manager.cameraIdList.first()
        characteristics = manager.getCameraCharacteristics(cameraId)

        val size = pickJpegSize(settings.targetWidth)
        reader = ImageReader.newInstance(size.width, size.height,
                                         ImageFormat.JPEG, 2)

        val opened = CountDownLatch(1)
        var error: Exception? = null
        manager.openCamera(cameraId, object : CameraDevice.StateCallback() {
            override fun onOpened(d: CameraDevice) {
                device = d
                d.createCaptureSession(
                    listOf(reader!!.surface),
                    object : CameraCaptureSession.StateCallback() {
                        override fun onConfigured(s: CameraCaptureSession) {
                            session = s
                            opened.countDown()
                        }

                        override fun onConfigureFailed(
                            s: CameraCaptureSession
                        ) {
                            error = IllegalStateException("configure failed")
                            opened.countDown()
                        }
                    }, handler)
            }

            override fun onDisconnected(d: CameraDevice) {
                d.close(); device = null; session = null
            }

            override fun onError(d: CameraDevice, code: Int) {
                error = IllegalStateException("camera error $code")
                d.close(); device = null
                opened.countDown()
            }
        }, handler)

        if (!opened.await(5, TimeUnit.SECONDS)) {
            throw IllegalStateException("camera open timeout")
        }
        error?.let { throw it }
        Log.i(tag, "camera $cameraId open at $size")
    }

    @Synchronized
    fun close() {
        session?.close(); session = null
        device?.close(); device = null
        reader?.close(); reader = null
    }

    private fun pickJpegSize(targetWidth: Int): Size {
        val sizes = characteristics.get(
            CameraCharacteristics.SCALER_STREAM_CONFIGURATION_MAP
        )!!.getOutputSizes(ImageFormat.JPEG)
        // Smallest size with width >= target (~1600 px class keeps upload
        // latency per stack frame bounded); fall back to the largest.
        return sizes.filter { it.width >= targetWidth }
            .minByOrNull { it.width } ?: sizes.maxByOrNull { it.width }!!
    }

    fun capabilities(): String {
        val manager =
            context.getSystemService(Context.CAMERA_SERVICE) as CameraManager
        val ch = manager.getCameraCharacteristics(
            manager.cameraIdList.first())
        val exposure =
            ch.get(CameraCharacteristics.SENSOR_INFO_EXPOSURE_TIME_RANGE)
        val iso =
            ch.get(CameraCharacteristics.SENSOR_INFO_SENSITIVITY_RANGE)
        val focus = ch.get(
            CameraCharacteristics.LENS_INFO_MINIMUM_FOCUS_DISTANCE)
        val zoom = ch.get(
            CameraCharacteristics.SCALER_AVAILABLE_MAX_DIGITAL_ZOOM)
        return Json.obj(
            "exposure_ns_min" to exposure?.lower,
            "exposure_ns_max" to exposure?.upper,
            "iso_min" to iso?.lower,
            "iso_max" to iso?.upper,
            "focus_diopters_max" to focus,
            "zoom_max" to zoom,
        ).toString()
    }

    /** One still capture; returns JPEG bytes + metadata JSON. */
    fun captureJpeg(): Pair<ByteArray, String> {
        ensureOpen()
        val s = session!!
        val rdr = reader!!
        // Drain stale images from an aborted previous capture.
        while (true) rdr.acquireLatestImage()?.close() ?: break

        val request = device!!.createCaptureRequest(
            CameraDevice.TEMPLATE_STILL_CAPTURE).apply {
            addTarget(rdr.surface)
            set(CaptureRequest.JPEG_QUALITY,
                settings.jpegQuality.toByte())
            if (!settings.aeOn) {
                set(CaptureRequest.CONTROL_AE_MODE,
                    CaptureRequest.CONTROL_AE_MODE_OFF)
                settings.exposureNs?.let {
                    set(CaptureRequest.SENSOR_EXPOSURE_TIME, it)
                }
                settings.iso?.let {
                    set(CaptureRequest.SENSOR_SENSITIVITY, it)
                }
            }
            if (!settings.afOn) {
                set(CaptureRequest.CONTROL_AF_MODE,
                    CaptureRequest.CONTROL_AF_MODE_OFF)
                settings.focusDiopters?.let {
                    set(CaptureRequest.LENS_FOCUS_DISTANCE, it)
                }
            }
            if (!settings.awbAuto) {
                set(CaptureRequest.CONTROL_AWB_MODE,
                    CaptureRequest.CONTROL_AWB_MODE_OFF)
            }
            if (settings.stabilization) {
                set(CaptureRequest.CONTROL_VIDEO_STABILIZATION_MODE,
                    CaptureRequest.CONTROL_VIDEO_STABILIZATION_MODE_ON)
            }
            if (settings.zoom > 1.0f) {
                val active = characteristics.get(
                    CameraCharacteristics.SENSOR_INFO_ACTIVE_ARRAY_SIZE)!!
                val cw = (active.width() / settings.zoom).toInt()
                val chh = (active.height() / settings.zoom).toInt()
                val cx = (active.width() - cw) / 2
                val cy = (active.height() - chh) / 2
                set(CaptureRequest.SCALER_CROP_REGION,
                    android.graphics.Rect(cx, cy, cx + cw, cy + chh))
            }
        }

        val done = CountDownLatch(1)
        var meta = "{}"
        s.capture(request.build(),
                  object : CameraCaptureSession.CaptureCallback() {
            override fun onCaptureCompleted(
                sess: CameraCaptureSession,
                req: CaptureRequest,
                result: TotalCaptureResult,
            ) {
                meta = Json.obj(
                    "exposure_ns" to
                        result.get(TotalCaptureResult.SENSOR_EXPOSURE_TIME),
                    "iso" to
                        result.get(TotalCaptureResult.SENSOR_SENSITIVITY),
                    "focus_diopters" to
                        result.get(TotalCaptureResult.LENS_FOCUS_DISTANCE),
                ).toString()
                done.countDown()
            }
        }, handler)

        if (!done.await(10, TimeUnit.SECONDS)) {
            throw IllegalStateException("capture timeout")
        }
        val image = rdr.acquireNextImage()
            ?: throw IllegalStateException("no image produced")
        image.use {
            val buf = it.planes[0].buffer
            val bytes = ByteArray(buf.remaining())
            buf.get(bytes)
            return bytes to meta
        }
    }
}
