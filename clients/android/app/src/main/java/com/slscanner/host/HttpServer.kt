// Minimal blocking HTTP/1.1 server on a raw ServerSocket — no external
// dependencies (the reference host pulls in NanoHTTPD; a scanner rig needs
// exactly four routes, so a ~150-line server is the smaller surface).
package com.slscanner.host

import android.util.Log
import java.io.BufferedOutputStream
import java.io.InputStream
import java.net.ServerSocket
import java.net.Socket
import java.nio.charset.StandardCharsets
import java.util.concurrent.Executors

data class Request(
    val method: String,
    val path: String,
    val headers: Map<String, String>,
    val body: ByteArray,
)

data class Response(
    val status: Int = 200,
    val contentType: String = "application/json",
    val body: ByteArray = ByteArray(0),
    val extraHeaders: Map<String, String> = emptyMap(),
) {
    companion object {
        fun json(text: String, status: Int = 200) =
            Response(status, "application/json",
                     text.toByteArray(StandardCharsets.UTF_8))

        fun error(status: Int, message: String) =
            json("{\"error\": \"${Json.escape(message)}\"}", status)
    }
}

class HttpServer(
    private val port: Int,
    private val handler: (Request) -> Response,
) {
    private val tag = "SlHttpServer"
    @Volatile private var socket: ServerSocket? = null
    private val pool = Executors.newFixedThreadPool(2)

    fun start() {
        val server = ServerSocket(port)
        socket = server
        Thread({
            Log.i(tag, "listening on :$port")
            while (!server.isClosed) {
                try {
                    val client = server.accept()
                    pool.execute { serve(client) }
                } catch (e: Exception) {
                    if (!server.isClosed) Log.e(tag, "accept failed", e)
                }
            }
        }, "http-accept").apply { isDaemon = true }.start()
    }

    fun stop() {
        socket?.close()
        pool.shutdownNow()
    }

    private fun serve(client: Socket) {
        client.use { sock ->
            sock.soTimeout = 10_000
            try {
                val request = parse(sock.getInputStream()) ?: return
                val response = try {
                    handler(request)
                } catch (e: Exception) {
                    Log.e(tag, "handler failed for ${request.path}", e)
                    Response.error(500, e.message ?: "internal error")
                }
                write(sock, response)
            } catch (e: Exception) {
                Log.e(tag, "connection dropped", e)
            }
        }
    }

    private fun parse(input: InputStream): Request? {
        val line = readLine(input) ?: return null
        val parts = line.split(" ")
        if (parts.size < 2) return null
        val headers = mutableMapOf<String, String>()
        while (true) {
            val h = readLine(input) ?: break
            if (h.isEmpty()) break
            val idx = h.indexOf(':')
            if (idx > 0) {
                headers[h.substring(0, idx).trim().lowercase()] =
                    h.substring(idx + 1).trim()
            }
        }
        val length = headers["content-length"]?.toIntOrNull() ?: 0
        val body = if (length > 0) input.readNBytes(length) else ByteArray(0)
        return Request(parts[0], parts[1], headers, body)
    }

    private fun readLine(input: InputStream): String? {
        val sb = StringBuilder()
        while (true) {
            val c = input.read()
            if (c == -1) return if (sb.isEmpty()) null else sb.toString()
            if (c == '\n'.code) return sb.toString().trimEnd('\r')
            sb.append(c.toChar())
        }
    }

    private fun write(sock: Socket, r: Response) {
        val out = BufferedOutputStream(sock.getOutputStream())
        val reason = if (r.status == 200) "OK" else "Error"
        out.write("HTTP/1.1 ${r.status} $reason\r\n".toByteArray())
        out.write("Content-Type: ${r.contentType}\r\n".toByteArray())
        out.write("Content-Length: ${r.body.size}\r\n".toByteArray())
        for ((k, v) in r.extraHeaders) out.write("$k: $v\r\n".toByteArray())
        out.write("Connection: close\r\n\r\n".toByteArray())
        out.write(r.body)
        out.flush()
    }
}
