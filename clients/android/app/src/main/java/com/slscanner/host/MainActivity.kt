// Foreground activity: asks for the camera permission, starts the host
// server, shows the listening address + a capture counter. The PC does the
// rest over HTTP.
package com.slscanner.host

import android.Manifest
import android.app.Activity
import android.content.pm.PackageManager
import android.os.Bundle
import android.view.WindowManager
import android.widget.TextView
import java.net.NetworkInterface

class MainActivity : Activity() {
    private lateinit var camera: CameraController
    private var server: HttpServer? = null
    private var captures = 0

    override fun onCreate(savedInstanceState: Bundle?) {
        super.onCreate(savedInstanceState)
        window.addFlags(WindowManager.LayoutParams.FLAG_KEEP_SCREEN_ON)
        setContentView(TextView(this).apply {
            id = android.R.id.text1
            textSize = 16f
            setPadding(32, 64, 32, 32)
        })
        camera = CameraController(this)
        if (checkSelfPermission(Manifest.permission.CAMERA) !=
            PackageManager.PERMISSION_GRANTED) {
            requestPermissions(arrayOf(Manifest.permission.CAMERA), 1)
        } else {
            startServer()
        }
    }

    override fun onRequestPermissionsResult(
        code: Int, permissions: Array<String>, results: IntArray,
    ) {
        if (results.firstOrNull() == PackageManager.PERMISSION_GRANTED) {
            startServer()
        } else {
            status("camera permission denied")
        }
    }

    private fun startServer() {
        val routes = Routes(camera) { captures++; updateStatus() }
        server = HttpServer(8765, routes::handle).also { it.start() }
        updateStatus()
    }

    private fun updateStatus() {
        val ips = NetworkInterface.getNetworkInterfaces().toList()
            .flatMap { it.inetAddresses.toList() }
            .filter { !it.isLoopbackAddress && it.address.size == 4 }
            .joinToString { it.hostAddress ?: "?" }
        status("SL capture host on :8765\nLAN: $ips\n" +
               "USB: adb reverse tcp:8765 tcp:8765\n" +
               "captures served: $captures")
    }

    private fun status(text: String) {
        runOnUiThread {
            findViewById<TextView>(android.R.id.text1).text = text
        }
    }

    override fun onDestroy() {
        server?.stop()
        camera.close()
        super.onDestroy()
    }
}
