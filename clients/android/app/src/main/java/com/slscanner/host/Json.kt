// Tiny JSON helpers — enough for the flat settings/status payloads this
// protocol exchanges (org.json is in the Android SDK; these wrappers keep
// call sites terse and normalize escaping).
package com.slscanner.host

import org.json.JSONObject

object Json {
    fun parse(bytes: ByteArray): JSONObject =
        if (bytes.isEmpty()) JSONObject() else JSONObject(String(bytes))

    fun obj(vararg pairs: Pair<String, Any?>): JSONObject {
        val o = JSONObject()
        for ((k, v) in pairs) o.put(k, v ?: JSONObject.NULL)
        return o
    }

    fun escape(s: String): String =
        s.replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n").replace("\r", "")
}
