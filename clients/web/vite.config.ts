import { defineConfig } from "vite";
import react from "@vitejs/plugin-react";

// The dev server must be reachable from the phone on the LAN; camera access
// needs a secure context, so use HTTPS or a localhost tunnel (adb reverse).
export default defineConfig({
  plugins: [react()],
  server: { host: true, port: 5173 },
});
