// Wire types for the PC command server
// (structured_light_for_3d_model_replication_tpu/hw/command_server.py; same shape as the
// reference protocol, server/server.py:27-78).

export interface PollResponse {
  /** Reference servers (server/server.py:44) send the verb as `action`;
   * this framework's server sends both keys. Either may be present. */
  action?: "idle" | "capture";
  command?: "idle" | "capture";
  id: string;
}

export type ConnectionState =
  | "connecting"
  | "connected"
  | "capturing"
  | "disconnected";

/** Manual camera controls ("pro mode"). All optional — a capability the
 * device lacks stays at auto. */
export interface ProSettings {
  enabled: boolean;
  /** Exposure time in milliseconds (mapped to exposureTime in 100µs units
   * where the implementation expects them). */
  shutterMs: number | null;
  iso: number | null;
  /** 0 = infinity focus; device-specific diopter scale. */
  focusDistance: number | null;
  /** EV bias applied by the auto-exposure pipeline (the reference's pro
   * slider, frotend/App.tsx:11,24) — useful when the device rejects full
   * manual exposure but still honors a bias. */
  exposureCompensation: number | null;
  zoom: number | null;
  torch: boolean;
}

export const DEFAULT_PRO: ProSettings = {
  enabled: false,
  shutterMs: null,
  iso: null,
  focusDistance: null,
  exposureCompensation: null,
  zoom: null,
  torch: false,
};

/** A selectable camera (`enumerateDevices` videoinput), like the
 * reference's device list (`frotend/App.tsx:36-37,71-85`) — a phone with
 * several rear lenses needs an explicit pick. */
export interface CameraDevice {
  deviceId: string;
  label: string;
}

/** Capability ranges discovered from MediaStreamTrack.getCapabilities(). */
export interface CapRange {
  min: number;
  max: number;
  step?: number;
}

export interface CameraCaps {
  exposureTime?: CapRange;
  iso?: CapRange;
  focusDistance?: CapRange;
  exposureCompensation?: CapRange;
  zoom?: CapRange;
  torch?: boolean;
}
