import React from "react";
import { createRoot } from "react-dom/client";
import App from "./App";
import "./style.css";

createRoot(document.getElementById("root")!).render(
  <React.StrictMode>
    <App />
  </React.StrictMode>
);
