// Phone camera client for the structured-light scanner.
//
// Protocol (capability-parity with the reference client, frotend/App.tsx,
// re-implemented from the wire contract):
//   * GET  {server}/poll_command every POLL_MS; response {command, id}.
//   * A NEW id with command="capture" → grab a frame, JPEG-encode at max
//     quality, POST multipart to {server}/upload.
//   * Ids are deduplicated so one projected pattern yields exactly one
//     upload even though polling repeats while the PC waits.
//   * Every poll uses an AbortController timeout; repeated failures flip
//     the status to disconnected (the PC side has its own 5 s watchdog).
//
// "Pro mode" drives manual sensor controls through
// MediaStreamTrack.applyConstraints (exposureTime, iso, focusDistance,
// zoom, torch) — structured light wants LOCKED exposure so stripe
// brightness is comparable across the 46-frame stack.

import React, {
  useCallback,
  useEffect,
  useRef,
  useState,
} from "react";
import {
  CameraCaps,
  CameraDevice,
  ConnectionState,
  DEFAULT_PRO,
  PollResponse,
  ProSettings,
} from "./types";

const POLL_MS = 500; // reference cadence (frotend/App.tsx:5)
const POLL_TIMEOUT_MS = 2000;
const JPEG_QUALITY = 1.0;
const LOG_LINES = 5;
const TARGET = { width: { ideal: 3840 }, height: { ideal: 2160 } };

function serverBase(): string {
  const q = new URLSearchParams(window.location.search).get("server");
  return q ?? `${window.location.protocol}//${window.location.hostname}:5000`;
}

export default function App() {
  const videoRef = useRef<HTMLVideoElement>(null);
  const canvasRef = useRef<HTMLCanvasElement>(null);
  const trackRef = useRef<MediaStreamTrack | null>(null);
  const lastIdRef = useRef<string>("");
  const failuresRef = useRef(0);

  const [status, setStatus] = useState<ConnectionState>("connecting");
  const [caps, setCaps] = useState<CameraCaps>({});
  const [pro, setPro] = useState<ProSettings>(DEFAULT_PRO);
  const [log, setLog] = useState<string[]>([]);
  const [captures, setCaptures] = useState(0);
  const [devices, setDevices] = useState<CameraDevice[]>([]);
  const [activeDeviceId, setActiveDeviceId] = useState<string>("");

  const addLog = useCallback((msg: string) => {
    setLog((l) => [
      `${new Date().toLocaleTimeString()} ${msg}`,
      ...l.slice(0, LOG_LINES - 1),
    ]);
  }, []);

  // ---- device enumeration ------------------------------------------------
  // Like the reference (`frotend/App.tsx:71-85`): list every videoinput so
  // a phone with several rear lenses can pick the right one. Labels are
  // only populated once camera permission is granted, so this re-runs
  // after the stream opens (and on the Rescan button).
  const refreshDevices = useCallback(async () => {
    try {
      const all = await navigator.mediaDevices.enumerateDevices();
      const cams: CameraDevice[] = all
        .filter((d) => d.kind === "videoinput")
        .map((d) => ({
          deviceId: d.deviceId,
          label: d.label || `Camera ${d.deviceId.slice(0, 5)}…`,
        }));
      setDevices(cams);
    } catch (e) {
      addLog(`enumerateDevices failed: ${e}`);
    }
  }, [addLog]);

  // ---- camera open -------------------------------------------------------
  useEffect(() => {
    // The effect re-runs on camera switch; `cancelled` guards the async
    // open so a stream resolving AFTER cleanup is stopped instead of
    // leaking (mobile browsers hold the device until its tracks stop).
    let cancelled = false;
    let stream: MediaStream | null = null;
    (async () => {
      try {
        // Explicit deviceId once the user picked one (`exact`, like the
        // reference's constraint at frotend/App.tsx:102); first open
        // falls back to the environment-facing default.
        const video_c: MediaTrackConstraints = activeDeviceId
          ? { deviceId: { exact: activeDeviceId }, ...TARGET }
          : { facingMode: "environment", ...TARGET };
        const s = await navigator.mediaDevices.getUserMedia({
          video: video_c,
          audio: false,
        });
        if (cancelled) {
          s.getTracks().forEach((t) => t.stop());
          return;
        }
        stream = s;
        const video = videoRef.current!;
        video.srcObject = stream;
        await video.play();
        const track = stream.getVideoTracks()[0];
        trackRef.current = track;
        const c = (track.getCapabilities?.() ?? {}) as CameraCaps;
        setCaps(c);
        const st = track.getSettings();
        addLog(`camera ${st.width}x${st.height}`);
        void refreshDevices(); // labels become visible post-permission
      } catch (e) {
        if (!cancelled) {
          addLog(`camera error: ${e}`);
          // A failed explicit-device open (unplugged / overconstrained)
          // already stopped the previous stream — fall back to the
          // default camera instead of leaving a dead feed.
          if (activeDeviceId) {
            addLog("falling back to default camera");
            setActiveDeviceId("");
          }
        }
      }
    })();
    return () => {
      cancelled = true;
      stream?.getTracks().forEach((t) => t.stop());
    };
  }, [addLog, activeDeviceId, refreshDevices]);

  // ---- capture + upload --------------------------------------------------
  const handleCapture = useCallback(
    async (id: string) => {
      const video = videoRef.current;
      const canvas = canvasRef.current;
      if (!video || !canvas || video.videoWidth === 0) {
        addLog("capture requested before camera ready");
        return;
      }
      setStatus("capturing");
      canvas.width = video.videoWidth;
      canvas.height = video.videoHeight;
      canvas.getContext("2d")!.drawImage(video, 0, 0);
      const blob: Blob = await new Promise((res) =>
        canvas.toBlob((b) => res(b!), "image/jpeg", JPEG_QUALITY)
      );
      const form = new FormData();
      form.append("file", blob, `${id}.jpg`);
      try {
        const r = await fetch(`${serverBase()}/upload`, {
          method: "POST",
          body: form,
        });
        if (!r.ok) throw new Error(`HTTP ${r.status}`);
        setCaptures((n) => n + 1);
        addLog(`frame uploaded (${(blob.size / 1024).toFixed(0)} kB)`);
      } catch (e) {
        addLog(`upload failed: ${e}`);
      } finally {
        setStatus("connected");
      }
    },
    [addLog]
  );

  // ---- poll loop ---------------------------------------------------------
  useEffect(() => {
    let live = true;
    const tick = async () => {
      if (!live) return;
      const ctrl = new AbortController();
      const timer = setTimeout(() => ctrl.abort(), POLL_TIMEOUT_MS);
      try {
        const r = await fetch(`${serverBase()}/poll_command`, {
          signal: ctrl.signal,
        });
        const data = (await r.json()) as PollResponse;
        failuresRef.current = 0;
        setStatus((s) => (s === "capturing" ? s : "connected"));
        // Reference servers send the verb as `action` (server/server.py:44),
        // this framework's server sends BOTH `action` and `command` — accept
        // either so the client drives both.
        const verb = data.action ?? data.command;
        if (verb === "capture" && data.id !== lastIdRef.current) {
          lastIdRef.current = data.id; // dedup BEFORE the async capture
          void handleCapture(data.id);
        }
      } catch {
        failuresRef.current += 1;
        if (failuresRef.current >= 3) setStatus("disconnected");
      } finally {
        clearTimeout(timer);
        if (live) setTimeout(tick, POLL_MS);
      }
    };
    void tick();
    return () => {
      live = false;
    };
  }, [handleCapture]);

  // ---- pro mode ----------------------------------------------------------
  const applyPro = useCallback(
    async (next: ProSettings) => {
      setPro(next);
      const track = trackRef.current;
      if (!track) return;
      const adv: Record<string, unknown> = {};
      if (next.enabled) {
        if (next.shutterMs != null)
          adv.exposureTime = next.shutterMs * 10; // ms → 100µs units
        if (next.iso != null) adv.iso = next.iso;
        if (next.focusDistance != null) {
          adv.focusMode = "manual";
          adv.focusDistance = next.focusDistance;
        }
        if (next.zoom != null) adv.zoom = next.zoom;
        // EV bias rides the auto-exposure pipeline: only meaningful when
        // exposure is NOT forced manual (shutter/ISO untouched) — the
        // path for devices that reject full manual control.
        if (next.exposureCompensation != null && next.shutterMs == null &&
            next.iso == null)
          adv.exposureCompensation = next.exposureCompensation;
        adv.torch = next.torch;
        if (adv.exposureTime != null || adv.iso != null)
          adv.exposureMode = "manual";
        adv.whiteBalanceMode = "manual";
      } else {
        adv.exposureMode = "continuous";
        adv.focusMode = "continuous";
        adv.whiteBalanceMode = "continuous";
        adv.torch = false;
      }
      try {
        await track.applyConstraints({ advanced: [adv] } as never);
        addLog(next.enabled ? "pro settings applied" : "auto mode");
      } catch (e) {
        addLog(`constraint rejected: ${e}`);
      }
    },
    [addLog]
  );

  const slider = (
    label: string,
    key: keyof ProSettings,
    range?: { min: number; max: number; step?: number },
    inert?: { disabled: boolean; hint: string }
  ) =>
    range && (
      <label className="slider">
        {label}
        <input
          type="range"
          min={range.min}
          max={range.max}
          step={range.step ?? (range.max - range.min) / 100}
          value={(pro[key] as number | null) ?? range.min}
          disabled={inert?.disabled ?? false}
          onChange={(e) =>
            void applyPro({ ...pro, [key]: Number(e.target.value) })
          }
        />
        <span>
          {inert?.disabled ? inert.hint : String(pro[key] ?? "auto")}
        </span>
      </label>
    );

  return (
    <div className="app">
      <header className={`status ${status}`}>
        <span>{status}</span>
        <span>{captures} frames</span>
      </header>
      <video ref={videoRef} playsInline muted />
      <canvas ref={canvasRef} style={{ display: "none" }} />
      <section className="controls">
        <label className="camera-select">
          Camera
          <select
            value={activeDeviceId}
            onChange={(e) => setActiveDeviceId(e.target.value)}
          >
            <option value="">default (rear)</option>
            {devices.map((d) => (
              <option key={d.deviceId} value={d.deviceId}>
                {d.label}
              </option>
            ))}
          </select>
          <button type="button" onClick={() => void refreshDevices()}>
            Rescan
          </button>
        </label>
        <label>
          <input
            type="checkbox"
            checked={pro.enabled}
            onChange={(e) =>
              void applyPro({ ...pro, enabled: e.target.checked })
            }
          />
          Pro mode (lock exposure for scanning)
        </label>
        {pro.enabled && (
          <>
            {slider("Shutter (ms)", "shutterMs", { min: 1, max: 100 })}
            {slider("ISO", "iso", caps.iso)}
            {slider("Focus", "focusDistance", caps.focusDistance)}
            {/* EV bias rides auto-exposure only — applyPro drops it once
                shutter or ISO forces manual mode, so reflect that in the
                control instead of leaving a silently inert slider. */}
            {slider("Exp. comp (EV)", "exposureCompensation",
                    caps.exposureCompensation, {
                      disabled: pro.shutterMs != null || pro.iso != null,
                      hint: "n/a in manual exposure",
                    })}
            {slider("Zoom", "zoom", caps.zoom)}
            {caps.torch && (
              <label>
                <input
                  type="checkbox"
                  checked={pro.torch}
                  onChange={(e) =>
                    void applyPro({ ...pro, torch: e.target.checked })
                  }
                />
                Torch
              </label>
            )}
          </>
        )}
      </section>
      <ul className="log">
        {log.map((l, i) => (
          <li key={i}>{l}</li>
        ))}
      </ul>
    </div>
  );
}
