#!/usr/bin/env python
"""CI-style smoke check for the satellite clients (web + Android host).

Preferred path: the real toolchains —
    web:     cd clients/web && npm install && npx tsc --noEmit
             (or: npx vite build)
    android: cd clients/android && gradle :app:compileDebugKotlin

Neither node nor gradle ships in the build image, so when they are absent
this script falls back to structural validation that still catches the
classes of rot that make "write-only" client code: unbalanced
brackets/braces/parens (outside strings/comments), merge-conflict
markers, imports that point at files which do not exist, and unparsable
package/tsconfig JSON. Exit code 0 = all checks passed (with the tool
tier used printed per target).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.abspath(__file__))


def _strip_code(text: str, line_comment: str = "//") -> str:
    """Remove string literals, comments and regex literals (good enough for
    bracket balancing; template literals are treated as plain strings). A
    ``/`` is a regex-literal opener, not division, when the last code
    character before it can't end an expression (``=``, ``(``, ``,``,
    ``return`` …) — that keeps a legitimately unbalanced ``/\\(/`` from
    tripping the balance check."""
    out = []
    tail = ""  # last few non-whitespace-trimmed chars — O(1) regex context
    i = 0
    n = len(text)
    # Characters after which a `/` starts a regex literal (plus start of
    # file / after keywords like return, handled below). `<`/`>` stay OUT:
    # they would make JSX closing tags (`</div>`) parse as regexes.
    regex_prefix = set("=([{,;:!&|?+-*%~^\n")
    while i < n:
        c = text[i]
        if c in "\"'`":
            q = c
            i += 1
            while i < n and text[i] != q:
                i += 2 if text[i] == "\\" else 1
            i += 1
            # The literal leaves a value behind: a following `/` is
            # division (keeps `<img src="x" />` out of the regex path).
            tail = (tail + q)[-16:]
        elif text.startswith(line_comment, i):
            while i < n and text[i] != "\n":
                i += 1
        elif text.startswith("/*", i):
            j = text.find("*/", i + 2)
            i = n if j < 0 else j + 2
        elif c == "/":
            prev_code = tail.rstrip()
            prev_ch = prev_code[-1] if prev_code else "\n"
            after_kw = re.search(r"(?:^|[^\w$])(return|typeof|case|in|of|"
                                 r"instanceof|new|do|else|yield|await)$",
                                 prev_code)
            # `>` alone is NOT a regex prefix (JSX tags), but an arrow
            # body is: `(s) => /x/.test(s)`.
            after_arrow = prev_code.endswith("=>")
            if (prev_ch in regex_prefix or after_kw or after_arrow
                    or not prev_code):
                # Regex literal: skip to the unescaped closing '/', honoring
                # character classes where '/' needs no escape.
                i += 1
                in_class = False
                while i < n and text[i] != "\n":
                    ch = text[i]
                    if ch == "\\":
                        i += 2
                        continue
                    if ch == "[":
                        in_class = True
                    elif ch == "]":
                        in_class = False
                    elif ch == "/" and not in_class:
                        i += 1
                        break
                    i += 1
            else:
                out.append(c)
                tail = (tail + c)[-16:]
                i += 1
        else:
            out.append(c)
            tail = (tail + c)[-16:]
            i += 1
    return "".join(out)


def _check_balance(path: str) -> list[str]:
    errs = []
    text = open(path, encoding="utf-8").read()
    if re.search(r"^(<<<<<<<|=======$|>>>>>>>)", text, re.M):
        errs.append(f"{path}: merge-conflict markers")
    code = _strip_code(text)
    pairs = {")": "(", "]": "[", "}": "{"}
    stack = []
    for ch in code:
        if ch in "([{":
            stack.append(ch)
        elif ch in pairs:
            if not stack or stack.pop() != pairs[ch]:
                errs.append(f"{path}: unbalanced {ch!r}")
                break
    else:
        if stack:
            errs.append(f"{path}: {len(stack)} unclosed bracket(s)")
    return errs


def _check_ts_imports(src_dir: str) -> list[str]:
    errs = []
    for dirpath, _, files in os.walk(src_dir):
        for f in files:
            if not f.endswith((".ts", ".tsx")):
                continue
            p = os.path.join(dirpath, f)
            for m in re.finditer(
                    r"""import\s[^;]*?from\s+["'](\.[^"']+)["']""",
                    open(p, encoding="utf-8").read()):
                rel = m.group(1)
                base = os.path.normpath(os.path.join(dirpath, rel))
                if not any(os.path.exists(base + ext) for ext in
                           ("", ".ts", ".tsx", ".js", "/index.ts",
                            "/index.tsx")):
                    errs.append(f"{p}: unresolved import {rel!r}")
    return errs


def check_web() -> list[str]:
    web = os.path.join(ROOT, "web")
    if shutil.which("npx") and os.path.isdir(
            os.path.join(web, "node_modules")):
        r = subprocess.run(["npx", "tsc", "--noEmit"], cwd=web)
        print("web: npx tsc --noEmit ->", r.returncode)
        return [] if r.returncode == 0 else ["web: tsc failed"]
    print("web: node toolchain unavailable — structural checks "
          "(full check: cd clients/web && npm install && npx tsc --noEmit)")
    errs = []
    for cfg in ("package.json", "tsconfig.json"):
        try:
            json.load(open(os.path.join(web, cfg)))
        except Exception as e:
            errs.append(f"web/{cfg}: {e}")
    for dirpath, _, files in os.walk(os.path.join(web, "src")):
        for f in files:
            if f.endswith((".ts", ".tsx")):
                errs += _check_balance(os.path.join(dirpath, f))
    errs += _check_ts_imports(os.path.join(web, "src"))
    # Protocol-capability contract: the client must keep the reference's
    # camera enumeration/switch flow (`frotend/App.tsx:36-37,71-85,102`) —
    # a phone with several rear lenses needs an explicit device pick.
    app = os.path.join(web, "src", "App.tsx")
    try:
        src = open(app, encoding="utf-8").read()
        for needle in ("enumerateDevices", "deviceId: { exact:",
                       "videoinput"):
            if needle not in src:
                errs.append(f"web/src/App.tsx: missing camera-switch "
                            f"capability marker {needle!r}")
    except OSError as e:
        errs.append(f"web/src/App.tsx: {e}")
    return errs


def check_android() -> list[str]:
    android = os.path.join(ROOT, "android")
    if shutil.which("gradle"):
        r = subprocess.run(["gradle", "-q", ":app:compileDebugKotlin"],
                           cwd=android)
        print("android: gradle compileDebugKotlin ->", r.returncode)
        return [] if r.returncode == 0 else ["android: compile failed"]
    print("android: gradle unavailable — structural checks (full check: "
          "cd clients/android && gradle :app:compileDebugKotlin)")
    errs = []
    found = 0
    for dirpath, _, files in os.walk(android):
        for f in files:
            if f.endswith(".kt"):
                found += 1
                errs += _check_balance(os.path.join(dirpath, f))
    if found == 0:
        errs.append("android: no Kotlin sources found")
    return errs


def main() -> int:
    errs = check_web() + check_android()
    for e in errs:
        print("FAIL:", e, file=sys.stderr)
    print("client smoke:", "FAILED" if errs else "OK")
    return 1 if errs else 0


if __name__ == "__main__":
    raise SystemExit(main())
