#!/usr/bin/env python
"""CI soak/chaos smoke for the durable serving layer: real process, real
SIGKILL, real recovery.

A ~60 s offered-load run against `cli serve --store-dir` on a tiny rig:

1. sustained submits (duplicates mixed in → content-cache hits; a
   seeded fraction corrupted via the hw/faults chaos schedule → contained
   per-job failures) plus a live 2-stop streaming session;
2. a burst of un-awaited jobs, then **SIGKILL** — no drain, no cleanup;
3. restart with ``--recover``: the journal replays — recovered burst
   jobs complete under their ORIGINAL ids, the session accepts stop 3
   and finalizes, a duplicate submit hits the persistent content cache;
4. more load, then SIGTERM → clean graceful drain (exit 0) and a
   journal-clean volume (zero live jobs/sessions on disk).

Asserted throughout: zero recompile storms (`sl_recompile_storms_total`)
and zero steady-state program-cache misses after each warmup. CI runs
this as the `soak-smoke` job with SL_SANITIZE=1 (ci.yml), uploading a
`cli diagnose` bundle on failure. The bench-scale version (minutes of
load, RSS/device-memory bounds) is bench.py config [9].
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

DEADLINE_S = 540.0
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROJ_W, PROJ_H = 64, 32          # 6+5 bits, 24 frames
CAM_H, CAM_W = 24, 40

#: Small-rig session tuning — the SINGLE source the durability tests
#: (tests/test_durability.py imports this module) and this smoke share,
#: so both gates always exercise the same compiled-program keys.
STREAM_PARAMS = {
    "method": "posegraph", "view_cap": 1024, "preview_points": 1024,
    "preview_depth": 4, "final_depth": 5, "model_cap": 8192, "window": 3,
    # The soak/fleet gates pin the legacy Poisson lane their compiled-
    # program keys were established on (the session default is "tsdf").
    "representation": "poisson",
    "merge": {"voxel_size": 4.0, "ransac_iterations": 512,
              "icp_iterations": 8, "fpfh_max_nn": 24, "normals_k": 8,
              "max_points": 1024, "posegraph_iterations": 10,
              "step_deg": 12.0},
}


def _fail(msg, procs=(), stderr_lines=None):
    print(f"SOAK SMOKE FAIL: {msg}", file=sys.stderr)
    if stderr_lines:
        print("--- server stderr ---", file=sys.stderr)
        print("".join(stderr_lines[-60:]), file=sys.stderr)
    for p in procs:
        if p is not None and p.poll() is None:
            p.kill()
    sys.exit(1)


class SpawnError(RuntimeError):
    """Serve subprocess never reached its readiness line."""


def spawn_serve(store_dir, recover=False, extra=(), sanitize=True,
                timeout_s=300.0, env_extra=None):
    """Start a tiny-rig `cli serve` subprocess over ``store_dir`` and
    wait for its readiness line; returns (proc, port, stderr_lines).
    Shared with tests/test_durability.py AND the fleet tier
    (scripts/fleet_smoke.py builds replicas from it) — one spawn
    recipe, one set of session params, no drift between the gates.
    ``extra`` flags appended LAST override the defaults (argparse
    last-wins — the fleet recipe pins --port this way); ``env_extra``
    adds environment (e.g. SL_PEER_FAULTS for the chaos harness)."""
    cmd = [sys.executable, "-m",
           "structured_light_for_3d_model_replication_tpu.cli", "serve",
           "--port", "0", "--proj-width", str(PROJ_W),
           "--proj-height", str(PROJ_H),
           "--buckets", f"{CAM_H}x{CAM_W}", "--batch-sizes", "1,2",
           "--linger-ms", "5", "--mesh-depth", "6",
           "--store-dir", store_dir, "--preview-depth", "4",
           "--stream-json", json.dumps(STREAM_PARAMS),
           "--drain-timeout", "60"]
    if recover:
        cmd.append("--recover")
    cmd += list(extra)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if sanitize:
        env.setdefault("SL_SANITIZE", "1")
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stderr=subprocess.PIPE, text=True)
    lines: list[str] = []
    port = [None]
    got = threading.Event()

    def pump():
        for line in proc.stderr:
            lines.append(line)
            m = re.search(r"serving on :(\d+)", line)
            if m:
                port[0] = int(m.group(1))
                got.set()
        got.set()

    threading.Thread(target=pump, daemon=True).start()
    if not got.wait(timeout_s) or port[0] is None:
        proc.kill()
        raise SpawnError("server never announced its port:\n"
                         + "".join(lines[-30:]))
    return proc, port[0], lines


def _metric(text: str, name: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except (ValueError, IndexError):
                pass
    return total


def main() -> int:
    t0 = time.monotonic()
    sys.path.insert(0, REPO)
    import tempfile

    import numpy as np

    from structured_light_for_3d_model_replication_tpu.config import (
        ProjectorConfig,
    )
    from structured_light_for_3d_model_replication_tpu.hw.faults import (
        CallSchedule,
    )
    from structured_light_for_3d_model_replication_tpu.models import (
        synthetic,
    )
    from structured_light_for_3d_model_replication_tpu.serve import (
        read_live_state,
    )
    from structured_light_for_3d_model_replication_tpu.serve.client import (
        ServeClient,
        ServeClientError,
    )

    proj = ProjectorConfig(width=PROJ_W, height=PROJ_H)
    cam = synthetic.default_calibration(CAM_H, CAM_W, proj)
    stack, _ = synthetic.render_scan(synthetic.Scene(), *cam,
                                     CAM_H, CAM_W, proj)
    scene = synthetic.Scene(
        wall_z=None,
        spheres=(synthetic.Sphere((0.0, 2.0, 500.0), 80.0, 0.9),
                 synthetic.Sphere((55.0, -30.0, 460.0), 35.0, 0.7)))
    ring = [s for s, _ in synthetic.render_turntable_scans(
        scene, n_stops=3, degrees_per_stop=12.0, cam_K=cam[0],
        proj_K=cam[1], R=cam[2], T=cam[3], cam_height=CAM_H,
        cam_width=CAM_W, proj=proj)]
    variants = [stack + np.uint8(1 + i) for i in range(4)]
    # Seeded chaos schedule (hw/faults): which offered submissions get a
    # corrupted stack — black (coverage-gate failure, contained) or
    # truncated (frame-count 400 at the door).
    chaos = CallSchedule.seeded(7, n_calls=64,
                                rates={"black": 0.08, "truncate": 0.07})

    store_dir = tempfile.mkdtemp(prefix="sl-soak-smoke-")
    try:
        proc, port, lines = spawn_serve(store_dir)
    except SpawnError as e:
        _fail(str(e))
    client = ServeClient(f"http://127.0.0.1:{port}", timeout_s=60.0)

    def offered_load(client, proc, lines, seconds, phase):
        # proc/lines are the CURRENT server's (phase 2 runs against the
        # recovered process — a failure must kill and dump that one,
        # not the long-dead phase-1 process).
        ok = dup_hits = contained = rejected = 0
        deadline = time.monotonic() + seconds
        i = 0
        while time.monotonic() < deadline:
            kind = chaos.next()
            try:
                if kind == "black":
                    jid = client.submit(np.zeros_like(stack))
                    st = client.wait(jid, timeout_s=60.0)
                    if st["status"] == "failed" and "StopQualityError" \
                            in st["error"]["taxonomy"]:
                        contained += 1
                    else:
                        _fail(f"black stack not contained: {st}",
                              (proc,), lines)
                elif kind == "truncate":
                    try:
                        client.submit(variants[i % 4][:3])
                        _fail("truncated stack accepted", (proc,), lines)
                    except ServeClientError:
                        rejected += 1
                else:
                    jid = client.submit(variants[i % 4])
                    st = client.wait(jid, timeout_s=60.0)
                    if st["status"] != "done":
                        _fail(f"job failed in phase {phase}: {st}",
                              (proc,), lines)
                    if st["result"].get("content_cache_hit"):
                        dup_hits += 1
                    ok += 1
            except ServeClientError as e:
                _fail(f"load error in phase {phase}: {e}", (proc,), lines)
            i += 1
        return ok, dup_hits, contained, rejected

    # Phase 1: warm the session lane first (its per-stop programs
    # compile on first use — expected, NOT a steady-state storm), then
    # sustained load with a zero-new-storms assertion over it.
    sid = client.create_session()
    for s in ring[:2]:
        st = client.wait(client.submit_stop(sid, s), timeout_s=120.0)
        if st["status"] != "done":
            _fail(f"stop failed: {st}", (proc,), lines)
    storms0 = _metric(client.metrics(), "sl_recompile_storms_total")
    ok1, hits1, contained1, rejected1 = offered_load(client, proc, lines,
                                                     20.0, 1)
    if ok1 < 4 or hits1 < 1 or (contained1 + rejected1) < 1:
        _fail(f"phase 1 too quiet: ok={ok1} hits={hits1} "
              f"chaos={contained1}+{rejected1}", (proc,), lines)
    storms1 = _metric(client.metrics(), "sl_recompile_storms_total")
    if storms1 > storms0:
        _fail("recompile storm during steady-state load", (proc,), lines)
    burst = [client.submit(stack + np.uint8(40 + i)) for i in range(6)]
    proc.kill()                                   # SIGKILL — no drain
    proc.wait(timeout=30.0)
    print(f"phase 1: {ok1} jobs ({hits1} duplicate hits, {contained1} "
          f"contained, {rejected1} rejected), session {sid} @2 stops, "
          f"killed -9 with {len(burst)} in flight "
          f"({time.monotonic() - t0:.0f}s)")

    # Phase 2: recover and carry on.
    try:
        proc2, port2, lines2 = spawn_serve(store_dir, recover=True)
    except SpawnError as e:
        _fail(str(e))
    client = ServeClient(f"http://127.0.0.1:{port2}", timeout_s=60.0)
    if not client.readyz().get("ready"):
        _fail("recovered server not ready", (proc2,), lines2)
    if not any("recovered from" in ln for ln in lines2):
        _fail("no recovery line on stderr", (proc2,), lines2)
    recovered = 0
    for jid in burst:
        try:
            st = client.wait(jid, timeout_s=120.0)
        except ServeClientError:
            continue                               # finished pre-kill
        if st["status"] != "done":
            _fail(f"recovered job {jid} failed: {st}", (proc2,), lines2)
        recovered += 1
    st = client.session_status(sid)
    if st.get("stops_fused") != 2:
        _fail(f"session not recovered: {st}", (proc2,), lines2)
    stj = client.wait(client.submit_stop(sid, ring[2]), timeout_s=120.0)
    if stj["status"] != "done":
        _fail(f"post-recovery stop failed: {stj}", (proc2,), lines2)
    fin = client.finalize_session(sid, result_format="ply")
    data = client.result(fin["job_id"])
    if not data.startswith(b"ply"):
        _fail("finalize artifact not a PLY", (proc2,), lines2)
    # Cross-restart duplicate → persistent content cache.
    jdup = client.submit(variants[0])
    stdup = client.wait(jdup, timeout_s=60.0)
    if not stdup["result"].get("content_cache_hit"):
        _fail(f"no cross-restart content hit: {stdup}", (proc2,), lines2)
    storms0 = _metric(client.metrics(), "sl_recompile_storms_total")
    ok2, hits2, contained2, rejected2 = offered_load(client, proc2,
                                                     lines2, 15.0, 2)
    metrics = client.metrics()
    if _metric(metrics, "sl_recompile_storms_total") > storms0:
        _fail("recompile storm during post-recovery steady state",
              (proc2,), lines2)
    if _metric(metrics, "serve_content_cache_hits_total") < 1:
        _fail("content cache counters missing", (proc2,), lines2)
    print(f"phase 2: recovered {recovered} burst job(s), session "
          f"finalized ({len(data)} B), {ok2} more jobs "
          f"({hits2} duplicate hits)")

    # Graceful drain + journal-clean volume.
    proc2.send_signal(signal.SIGTERM)
    try:
        rc = proc2.wait(timeout=max(10.0, DEADLINE_S
                                    - (time.monotonic() - t0)))
    except subprocess.TimeoutExpired:
        _fail("no exit after SIGTERM", (proc2,), lines2)
    if rc != 0:
        _fail(f"server exited {rc} after SIGTERM", None, lines2)
    time.sleep(0.2)
    if not any("drained clean" in ln for ln in lines2):
        _fail("no 'drained clean' on stderr", None, lines2)
    state = read_live_state(store_dir)
    if state.jobs or state.sessions:
        _fail(f"journal not clean after drain: {len(state.jobs)} jobs, "
              f"{len(state.sessions)} sessions", None, lines2)
    print(f"SOAK SMOKE PASS in {time.monotonic() - t0:.0f}s "
          "(load + chaos → SIGKILL → recover → finalize → clean drain, "
          "journal empty)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
