"""XProf the depth-10 @1M sparse Poisson solve: where do the 5.06 s go
after the round-5 splat + matvec work? Run alone."""

import glob
import json

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson_sparse as ps,
    pointcloud,
)
from structured_light_for_3d_model_replication_tpu.utils import trace  # noqa: E402

rng = np.random.default_rng(0)
n3 = 1 << 20
theta = rng.uniform(0, 2 * np.pi, n3)
zz = rng.uniform(-80, 80, n3)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts = jax.device_put(jnp.asarray(cloud))
nrm, _ = pointcloud.estimate_normals(pts, k=12)
nrm = pointcloud.orient_normals(pts, nrm,
                                jnp.asarray([0.0, 0.0, 500.0]), outward=True)
jax.block_until_ready(nrm)


def run(rep):
    grid, nb = ps.reconstruct_sparse(
        pts + jnp.float32(0.001 * rep), nrm, depth=10, cg_iters=100,
        max_blocks=196_608)
    np.asarray(jnp.sum(grid.chi))


run(-1)
with trace.device_trace("/tmp/xprof_poisson_r5"):
    run(3)
print("traced", flush=True)

from xprof.convert import raw_to_tool_data as rtd  # noqa: E402

f = glob.glob("/tmp/xprof_poisson_r5/plugins/profile/*/*.xplane.pb")
data, _ = rtd.xspace_to_tool_data(f, "hlo_stats", {})
d = json.loads(data)
cols = [c["label"] if isinstance(c, dict) else c for c in d["cols"]]
i_self = next(i for i, c in enumerate(cols) if "self" in c.lower()
              and "us" in c.lower())
i_src = next((i for i, c in enumerate(cols) if "source" in c.lower()), None)
i_cat = next((i for i, c in enumerate(cols) if "category" in c.lower()), 1)
i_prog = next((i for i, c in enumerate(cols) if "program" in c.lower()
               or "module" in c.lower()), None)
rows = []
for r in d["rows"]:
    c = r["c"] if isinstance(r, dict) else r
    vals = [x.get("v") if isinstance(x, dict) else x for x in c]
    rows.append(vals)
rows.sort(key=lambda v: -(v[i_self] or 0))
total = sum(v[i_self] or 0 for v in rows)
print(f"total self time: {total/1e3:.1f} ms; top 35:")
for v in rows[:35]:
    src = (v[i_src] or "")[:68] if i_src is not None else ""
    prog = (str(v[i_prog])[:20] if i_prog is not None else "")
    print(f"  {v[i_self]/1e3:8.2f} ms  {str(v[i_cat])[:24]:24s} {prog:20s}"
          f" {src}")
