"""Validate the bench poisson_depth14/15_1M_dense configs: realistic-
density band at depths 14-15 (small sphere + far anchors), coherent
surface, analytic error. Mirrors bench.py's deep_poisson. Times here are
indicative only (may run under CPU contention); the official record is
the driver's bench run."""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    marching,
    poisson_sparse,
)


def deep(depth, r_sphere):
    n_pts = 1 << 20
    u = np.random.default_rng(4).normal(size=(n_pts, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    pts_np = (u * r_sphere).astype(np.float32)
    anchors = np.asarray(
        [[s * 1000.0, t * 1000.0, v * 1000.0]
         for s in (-1, 1) for t in (-1, 1) for v in (-1, 1)], np.float32)
    pts_d = jax.device_put(jnp.asarray(np.vstack([pts_np, anchors])))
    nrm_d = jax.device_put(jnp.asarray(np.vstack(
        [u.astype(np.float32),
         np.tile([1.0, 0.0, 0.0], (8, 1)).astype(np.float32)])))
    jax.block_until_ready((pts_d, nrm_d))

    t0 = time.perf_counter()
    grid, nb = poisson_sparse.reconstruct_sparse(
        pts_d, nrm_d, depth=depth, cg_iters=100, max_blocks=196_608)
    np.asarray(jnp.sum(grid.chi))
    wall = time.perf_counter() - t0
    voxel = float(grid.scale)
    mesh = marching.extract_sparse(grid)
    rad = np.linalg.norm(mesh.vertices, axis=1)
    shell = rad < 500.0
    err = np.abs(rad[shell] - r_sphere)
    print(f"depth {depth}: cold wall {wall:.1f}s, blocks {int(nb)}, "
          f"voxel {voxel:.4f}, spacing "
          f"{np.sqrt(4*np.pi*r_sphere**2/n_pts)/voxel:.2f} vox, faces "
          f"{len(mesh.faces)}, shell {shell.mean():.3f}, err med "
          f"{np.median(err)/voxel:.2f} vox p90 "
          f"{np.percentile(err, 90)/voxel:.2f} vox", flush=True)


deep(14, 50.0)
deep(15, 25.0)
