"""Measure fine-band PCG iteration counts per preconditioner on one
shared system (no per-variant re-setup). Iteration counts are
platform-independent — this is how the additive/vcycle defaults in
`ops/poisson_sparse.PoissonParams` were picked; wall-clock per variant
is hardware-specific and belongs to the driver's bench run.

Measured here (depth-9 sphere, 37.9k blocks, rtol 3e-4):
jacobi 65 · vcycle 28 · chebyshev 18 · additive 26 at its tuned
default (ω=2, ci=4; the sweep below shows the plateau — ω∈[2,4] and
ci∈[4,8] all land 26-28, ω=1 costs 35, unmasked costs +6-9 more).
"""

import sys

import numpy as np

import jax.numpy as jnp

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson as dense_poisson,
    poisson_sparse as ps,
)


def main(depth=9, coarse_depth=7, n=60_000):
    rng = np.random.default_rng(1)
    u = rng.normal(size=(n, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    pts = jnp.asarray((u * 50.0).astype(np.float32))
    nrm = jnp.asarray(u.astype(np.float32))
    valid = jnp.ones(pts.shape[0], bool)
    R, Rc = 2 ** depth, 2 ** coarse_depth
    (rhs, W, nbr, bvalid, bcoords, *_rest) = ps._setup_sparse(
        pts, nrm, valid, R, 49_152, jnp.float32(4.0))
    print("blocks", int(_rest[-1]), flush=True)
    coarse = dense_poisson._solve(pts, nrm, valid, Rc, 300,
                                  jnp.float32(4.0), rtol=3e-4)
    b, x0 = ps._prolong_band(coarse.chi, rhs, nbr, bvalid, bcoords, R, Rc)
    coarse_W = dense_poisson.screen_weights(coarse.density,
                                            jnp.float32(4.0))

    _, it_j = ps._cg_sparse(b, W, x0, nbr, bvalid, 300, jnp.float32(3e-4))
    print(f"jacobi: iters {int(it_j)}", flush=True)
    for pre in ("vcycle", "chebyshev"):
        _, it = ps._pcg_sparse(b, W, x0, nbr, bvalid, bcoords, coarse_W,
                               R, Rc, 300, rtol=jnp.float32(3e-4),
                               precond=pre)
        print(f"{pre}: iters {int(it)}", flush=True)
    for om in (1.0, 2.0, 3.0):
        for ci in (4, 8, 16):
            _, it = ps._pcg_sparse(
                b, W, x0, nbr, bvalid, bcoords, coarse_W, R, Rc, 300,
                rtol=jnp.float32(3e-4), precond="additive",
                precond_coarse_iters=ci, smooth_omega=jnp.float32(om))
            print(f"additive om={om} ci={ci}: iters {int(it)}", flush=True)


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
