"""Splat accumulate variants at the TRUE production shapes (8.4M rows,
100M-row output table), with the argsort cost measured separately:

  A  unsorted scatter-add (the pre-r4 baseline)
  B  argsort + sorted scatter-add (r4 shipped)
  C  argsort + double-float prefix scan + compact + set   (r5 first cut)
  D  argsort + segmented f32 scan + drop-mode unique set  (r5 proposal)

Run alone."""

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson_sparse as ps,
    pointcloud,
)

rng = np.random.default_rng(0)
n3 = 1 << 20
theta = rng.uniform(0, 2 * np.pi, n3)
zz = rng.uniform(-80, 80, n3)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts = jax.device_put(jnp.asarray(cloud))
nrm, _ = pointcloud.estimate_normals(pts, k=12)
nrm = pointcloud.orient_normals(pts, nrm,
                                jnp.asarray([0.0, 0.0, 500.0]), outward=True)
jax.block_until_ready(nrm)

# Real dest/contrib stream from the actual setup internals at depth 10.
MAXB = 196_608
R = 1024
grid_pts, origin, scale = __import__(
    "structured_light_for_3d_model_replication_tpu.ops.poisson",
    fromlist=["poisson"]).normalize_points(pts, jnp.ones((n3,), bool), R)
# Rebuild the splat inputs exactly as _setup_sparse does (narrow-key
# depth) — cheapest is to call _setup_sparse and recompute dest/contrib
# from its returned flat/w/cfound.
(rhs, W, nbr, block_valid, block_coords, density, flat, w, cfound,
 *_r) = ps._setup_sparse(pts, nrm, jnp.ones((n3,), bool), R, MAXB,
                         jnp.float32(4.0))
m = MAXB
vals = jnp.concatenate([nrm, jnp.ones((n3, 1), jnp.float32)], -1)
contrib = (w[..., None] * vals[:, None, :]).reshape(-1, 4)
dest = jnp.where(cfound, flat, m * 512).reshape(-1)
jax.block_until_ready((contrib, dest))
OUT_ROWS = m * 512 + 1
NR = dest.shape[0]
print(f"rows {NR}, out table {OUT_ROWS}", flush=True)


def timeit(f, label, reps=3):
    def run(rep):
        np.asarray(jnp.sum(f(contrib + jnp.float32(1e-6 * rep))))

    run(-1)
    ts = []
    for rep in range(reps):
        t0 = time.perf_counter()
        run(rep)
        ts.append((time.perf_counter() - t0) * 1e3)
    print(f"{label}: median {statistics.median(ts):.0f} ms "
          f"({[round(t) for t in ts]})", flush=True)
    return statistics.median(ts)


@jax.jit
def sort_only(c):
    return jnp.argsort(dest) + jnp.int32(jnp.sum(c[0]) * 0)


@jax.jit
def variant_a(c):
    acc = jnp.zeros((OUT_ROWS, 4), jnp.float32)
    return acc.at[dest].add(c)[:-1]


@jax.jit
def variant_b(c):
    dorder = jnp.argsort(dest)
    acc = jnp.zeros((OUT_ROWS, 4), jnp.float32)
    return acc.at[dest[dorder]].add(c[dorder],
                                    indices_are_sorted=True)[:-1]


def _two_sum(a, b):
    s = a + b
    bv = s - a
    return s, (a - (s - bv)) + (b - bv)


def _df_add(x, y):
    (xh, xl), (yh, yl) = x, y
    s, e = _two_sum(xh, yh)
    e = e + (xl + yl)
    hi = s + e
    return hi, e - (hi - s)


@jax.jit
def variant_c(c):
    # The r5 first-cut (removed from poisson_sparse after this probe):
    # double-float prefix scan + boundary diff + compacted set.
    dorder = jnp.argsort(dest)
    ds, cs = dest[dorder], c[dorder]
    nrows = ds.shape[0]
    pre_h, pre_l = jax.lax.associative_scan(
        _df_add, (cs, jnp.zeros_like(cs)), axis=0)
    last = jnp.concatenate([ds[1:] != ds[:-1], jnp.ones((1,), bool)])
    (idx,) = jnp.nonzero(last, size=nrows, fill_value=nrows - 1)
    seg_ok = jnp.arange(nrows) < jnp.sum(last)
    end_h, end_l = pre_h[idx], pre_l[idx]
    prev_h = jnp.concatenate([jnp.zeros_like(end_h[:1]), end_h[:-1]])
    prev_l = jnp.concatenate([jnp.zeros_like(end_l[:1]), end_l[:-1]])
    seg = (end_h - prev_h) + (end_l - prev_l)
    seg_dest = jnp.where(seg_ok, ds[idx], OUT_ROWS - 1)
    out = jnp.zeros((OUT_ROWS,) + cs.shape[1:], cs.dtype)
    return out.at[seg_dest].set(jnp.where(seg_ok[:, None], seg, 0.0))[:-1]


def _seg_add(x, y):
    (xv, xf), (yv, yf) = x, y
    return jnp.where(yf, yv, xv + yv), xf | yf


@jax.jit
def variant_d(c):
    dorder = jnp.argsort(dest)
    ds = dest[dorder]
    cs = c[dorder]
    first = jnp.concatenate([jnp.ones((1,), bool), ds[1:] != ds[:-1]])
    seg, _ = jax.lax.associative_scan(
        _seg_add, (cs, jnp.broadcast_to(first[:, None], cs.shape)), axis=0)
    last = jnp.concatenate([ds[1:] != ds[:-1], jnp.ones((1,), bool)])
    tgt = jnp.where(last, ds, OUT_ROWS)  # non-last -> out of range: drop
    acc = jnp.zeros((OUT_ROWS, 4), jnp.float32)
    return acc.at[tgt].set(jnp.where(last[:, None], seg, 0.0),
                           mode="drop", unique_indices=True)[:-1]


timeit(sort_only, "argsort alone")
ta = timeit(variant_a, "A unsorted scatter-add")
tb = timeit(variant_b, "B argsort + sorted scatter-add")
tc = timeit(variant_c, "C df-scan + compact + set (current)")
td = timeit(variant_d, "D segmented scan + drop set")

ref = np.asarray(variant_b(contrib))
for name, v in (("A", variant_a), ("C", variant_c), ("D", variant_d)):
    got = np.asarray(v(contrib))
    print(f"{name} max abs err vs B: {np.abs(got - ref).max():.3e} "
          f"(ref max {np.abs(ref).max():.3e})", flush=True)
