"""Host-side: how often are a grid-step's CB neighbor slots (direction
d) a contiguous run nbr[b,d] == nbr[0,d] + b? Decides whether
run-coalesced range-DMAs can replace per-block DMAs in the Poisson
stencil kernel. Uses the real depth-10 bench band."""

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson_sparse as ps,
    pointcloud,
)

rng = np.random.default_rng(0)
n3 = 1 << 20
theta = rng.uniform(0, 2 * np.pi, n3)
zz = rng.uniform(-80, 80, n3)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts = jax.device_put(jnp.asarray(cloud))
nrm, _ = pointcloud.estimate_normals(pts, k=12)
nrm = pointcloud.orient_normals(pts, nrm,
                                jnp.asarray([0.0, 0.0, 500.0]), outward=True)
valid = jnp.ones((n3,), bool)

MAXB = 196_608
(rhs, W, nbr, block_valid, *_rest) = ps._setup_sparse(
    pts, nrm, valid, 1024, MAXB, jnp.float32(4.0))
nbr = np.asarray(nbr)
bv = np.asarray(block_valid)
m = nbr.shape[0]
print(f"blocks: {bv.sum()} valid of {m} budget")

for CB in (8, 16, 32):
    mp = (m // CB) * CB
    nb = nbr[:mp].reshape(-1, CB, 6)
    live = bv[:mp].reshape(-1, CB).any(axis=1)
    base = nb[:, :1, :] + np.arange(CB)[None, :, None]
    run = (nb == base).all(axis=1)           # (steps, 6)
    # Also allow the all-absent step-direction (skippable entirely).
    absent = (nb == m).all(axis=1)
    hit = (run | absent)[live]
    print(f"CB={CB:3d}: per-direction run|absent rate "
          f"{np.round(hit.mean(axis=0), 3)}  overall {hit.mean():.3f} "
          f"(live steps {live.sum()})")
