#!/usr/bin/env python
"""Diff a fresh bench run against the BENCH_r*.json trajectory.

The driver archives each round's bench output as ``BENCH_r<NN>.json``
(``{"n": round, "tail": <last stdout>, ...}``); the headline metric rides
the tail as single-line JSON objects (``{"metric": ..., "value": ...}``,
`bench.py`). This script rebuilds the per-metric trajectory from those
archives and compares a fresh run against it, flagging regressions —
the "did this PR slow the north star down" answer as a command instead
of archaeology.

The fresh run can be any of:

* a bench stdout log (or a single headline line) — headline JSON lines
  are extracted exactly like the history tails;
* a ``BENCH_DETAILS.json`` — per-config ``value_s``/``value_ms`` leaves
  are lifted, with the known config → headline-metric aliases applied.

Exit code is 0 (informational) unless ``--strict``, where any
regression beyond the threshold fails the run.

Usage::

    python scripts/bench_compare.py --fresh BENCH_DETAILS.json
    python bench.py | tee fresh.log; python scripts/bench_compare.py \
        --fresh fresh.log --strict
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# BENCH_DETAILS config name → headline metric name, where they differ.
_DETAILS_ALIASES = {
    "full_360_scan_to_mesh": "full_360_scan_to_mesh_s",
    # Config 6b (the capture-overlapped finalize tail) SUPERSEDES config
    # 6's batch sum as the scan→mesh headline when both rows are present
    # — load_fresh applies that precedence explicitly below.
    "full_360_mesh_tail": "full_360_scan_to_mesh_s",
    "full_360_24x46_1080p": "full_360_scan_24x46_1080p_s",
    "tsdf_stream_preview": "tsdf_preview_s",
    "splat_render_view": "render_view_s",
}


def higher_is_better(metric: str) -> bool:
    """Most headline metrics are seconds (lower wins); throughput lines
    (config [9]'s ``soak_scans_per_s``, config [10]'s
    ``fleet_scans_per_s``, and the suffixed device-sweep family like
    config [7b]'s ``serve_scans_per_s_8dev``), QUALITY lines
    (config [12]'s ``render_psnr_db`` — decibels of rendered fidelity),
    hit-rate-shaped ``*_ratio`` lines (e.g. a fleet duplicate-hit
    ratio) and capacity-shaped ``*_replicas`` lines (the
    /fleet/signals family — more ready replicas is healthier) invert —
    going UP is the improvement, going down the regression.
    Latency-shaped fleet lines (``fleet_failover_s``, the proactive
    tier's ``fleet_proactive_repin_s`` — background adoption must get
    FASTER — config [7c]'s ``lane_failover_s``, the device-loss
    tier's fault-to-adopted-lane window, and config [7c2]'s
    ``sharded_failover_s``, the sharded tier's fault-to-re-formed-span
    window — probe conviction must stay cheap), config [11]'s per-stop
    preview latency (``tsdf_preview_s``), config [12]'s per-view
    render latency (``render_view_s``), config [6b]'s finalize-tail
    lines (``full_360_scan_to_mesh_s`` re-based on the overlapped
    finalize wall, and ``finalize_default_s`` — the TSDF-default
    finalize seconds), and count-shaped
    tenant/overload lines (``*_rejected_total``, ``*_shed_total`` —
    shed work going up is a regression) keep the lower-wins default."""
    return (metric.endswith("_per_s") or "_per_s_" in metric
            or metric.endswith("_psnr_db")
            or metric.endswith("_ratio")
            or metric.endswith("_replicas"))


def _headline_metrics(text: str) -> dict[str, float]:
    """Every ``{"metric": ..., "value": ...}`` JSON line in ``text``;
    later lines win per metric (bench prints the crash-hedge scan→cloud
    headline first, the promoted scan→mesh one later)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        metric, value = obj.get("metric"), obj.get("value")
        if isinstance(metric, str) and isinstance(value, (int, float)):
            out[metric] = float(value)
    return out


def load_history(paths: list[str]) -> dict[str, list[tuple[int, float]]]:
    """{metric: [(round, value), ...]} sorted by round."""
    traj: dict[str, list[tuple[int, float]]] = {}
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"warn: skipping {path}: {e}", file=sys.stderr)
            continue
        n = int(doc.get("n", -1))
        for metric, value in _headline_metrics(doc.get("tail", "")).items():
            traj.setdefault(metric, []).append((n, value))
    for rounds in traj.values():
        rounds.sort()
    return traj


def load_fresh(path: str) -> dict[str, float]:
    """Fresh-run metrics from a headline log OR a BENCH_DETAILS.json."""
    with open(path) as f:
        text = f.read()
    metrics = _headline_metrics(text)
    if metrics:
        return metrics
    try:
        details = json.loads(text)
    except json.JSONDecodeError:
        raise SystemExit(
            f"{path}: neither headline JSON lines nor a JSON document")
    if not isinstance(details, dict):
        raise SystemExit(f"{path}: unrecognized bench document")
    for config, row in details.items():
        if not isinstance(row, dict):
            continue
        name = _DETAILS_ALIASES.get(config, config)
        if isinstance(row.get("value_s"), (int, float)):
            metrics[name if name.endswith("_s") else name + "_s"] = \
                float(row["value_s"])
        elif isinstance(row.get("value_ms"), (int, float)):
            metrics[name + "_ms"] = float(row["value_ms"])
    # Config 6b precedence, independent of the document's key order: its
    # overlapped-finalize wall IS the scan→mesh headline when the row
    # exists (bench.py replaces state["headline"] the same way), and its
    # TSDF-finalize figure rides the `finalize_default_s` headline line.
    tail_row = details.get("full_360_mesh_tail")
    if isinstance(tail_row, dict):
        if isinstance(tail_row.get("value_s"), (int, float)):
            metrics["full_360_scan_to_mesh_s"] = float(tail_row["value_s"])
        if isinstance(tail_row.get("finalize_default_tsdf_s"),
                      (int, float)):
            metrics["finalize_default_s"] = \
                float(tail_row["finalize_default_tsdf_s"])
    if not metrics:
        raise SystemExit(f"{path}: no value_s/value_ms leaves found")
    return metrics


def compare(fresh: dict[str, float],
            traj: dict[str, list[tuple[int, float]]],
            threshold: float) -> list[dict]:
    """One row per fresh metric: verdict vs the last round and the best
    round. Latency metrics are lower-is-better; ``*_per_s`` throughput
    metrics are higher-is-better (:func:`higher_is_better`)."""
    rows = []
    for metric in sorted(fresh):
        value = fresh[metric]
        history = traj.get(metric, [])
        row: dict = {"metric": metric, "fresh": value,
                     "rounds": len(history)}
        if history:
            hib = higher_is_better(metric)
            last_n, last_v = history[-1]
            best_n, best_v = (max if hib else min)(
                history, key=lambda nv: nv[1])
            row.update(last=last_v, last_round=last_n,
                       best=best_v, best_round=best_n,
                       vs_last=round(value / last_v, 3) if last_v else None)
            worse = (value < last_v * (1 - threshold) if hib
                     else value > last_v * (1 + threshold))
            better = (value > last_v * (1 + threshold) if hib
                      else value < last_v * (1 - threshold))
            if last_v and worse:
                row["verdict"] = "REGRESSION"
            elif last_v and better:
                row["verdict"] = "improved"
            else:
                row["verdict"] = "flat"
        else:
            row["verdict"] = "no-history"
        rows.append(row)
    return rows


def render(rows: list[dict]) -> str:
    if not rows:
        return "(no comparable metrics)"
    w = max(len(r["metric"]) for r in rows)
    lines = [f"{'metric':<{w}}  {'fresh':>10}  {'last':>10}  "
             f"{'best':>10}  {'x last':>7}  verdict"]
    for r in rows:
        last = f"{r['last']:.3f}" if "last" in r else "-"
        best = f"{r['best']:.3f}" if "best" in r else "-"
        ratio = f"{r['vs_last']:.3f}" if r.get("vs_last") else "-"
        lines.append(f"{r['metric']:<{w}}  {r['fresh']:>10.3f}  "
                     f"{last:>10}  {best:>10}  {ratio:>7}  {r['verdict']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--fresh", required=True,
                   help="fresh bench output: stdout log with headline "
                        "lines, or a BENCH_DETAILS.json")
    p.add_argument("--history", default=None,
                   help="history glob (default <root>/BENCH_r*.json)")
    p.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root for the default history glob")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="relative slowdown vs the last round that flags "
                        "a regression (default 0.10)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 when any metric regressed")
    p.add_argument("--json", action="store_true",
                   help="emit the comparison as one JSON line instead "
                        "of a table")
    args = p.parse_args(argv)

    pattern = args.history or os.path.join(args.root, "BENCH_r*.json")
    history_paths = sorted(glob.glob(pattern))
    traj = load_history(history_paths)
    fresh = load_fresh(args.fresh)
    rows = compare(fresh, traj, args.threshold)

    regressions = [r for r in rows if r["verdict"] == "REGRESSION"]
    if args.json:
        print(json.dumps({"rows": rows,
                          "history_files": len(history_paths),
                          "regressions": len(regressions)}))
    else:
        print(f"history: {len(history_paths)} rounds "
              f"({pattern.replace(os.path.expanduser('~'), '~')})")
        print(render(rows))
        if regressions:
            print(f"\n{len(regressions)} regression(s) beyond "
                  f"{args.threshold:.0%} vs the last round")
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    raise SystemExit(main())
