"""Round-5 XProf profile of the fused ring registration tail at the
bench shape — refreshes the r4 hotspot table (FPFH gathers ~260 ms,
RANSAC ~250 ms, stratified searchsorted 165 ms, covariance ~130 ms,
triangulate ~130 ms, ICP NN ~90 ms). Run alone on the TPU; parse with
the hlo_stats recipe in .claude/skills/verify/SKILL.md."""

import glob
import json
import sys

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.config import ProjectorConfig  # noqa: E402
from structured_light_for_3d_model_replication_tpu.models import (  # noqa: E402
    merge,
    scan360,
    synthetic,
)
from structured_light_for_3d_model_replication_tpu.ops.patterns import (  # noqa: E402
    pattern_stack_for,
)
from structured_light_for_3d_model_replication_tpu.ops.triangulate import (  # noqa: E402
    make_calibration,
)
from structured_light_for_3d_model_replication_tpu.utils import trace  # noqa: E402

OUT = sys.argv[1] if len(sys.argv) > 1 else "/tmp/xprof_ring_r5"

proj = ProjectorConfig()
H, W = proj.height, proj.width
cam_K, proj_K, R, T = synthetic.default_calibration(H, W, proj)
calib = make_calibration(cam_K, proj_K, R, T, H, W,
                         proj_width=proj.width, proj_height=proj.height)


def bump(az_deg, y, r):
    az = np.radians(az_deg)
    return synthetic.Sphere(
        (90.0 * np.sin(az), y, 500.0 + 90.0 * np.cos(az)), r, 0.75)


scene = synthetic.Scene(wall_z=None, spheres=(
    synthetic.Sphere((0.0, 10.0, 500.0), 80.0, 0.9),
    bump(0, -40, 32), bump(60, 30, 26), bump(130, -10, 30),
    bump(200, 55, 24), bump(270, -55, 28), bump(320, 20, 22)))
frames = np.asarray(pattern_stack_for(proj))
print("rendering 24 stops (untimed)...", flush=True)
stacks_np = np.empty((24, frames.shape[0], H, W), np.uint8)
for k in range(24):
    sc = synthetic.rotated_scene(scene, k * 15.0)
    shader = synthetic.FrameShader(sc, cam_K, proj_K, R, T, H, W, proj)
    for f in range(frames.shape[0]):
        stacks_np[k, f] = shader.shade(frames[f])
params = scan360.Scan360Params(
    merge=merge.MergeParams(voxel_size=3.0, final_max_points=131_072,
                            step_deg=15.0),
    method="sequential", fused=True, view_cap=16_384, stop_chunk=3,
    output_cap=32_768)
stacks_dev = jax.device_put(jnp.asarray(stacks_np))
jax.block_until_ready(stacks_dev)


def run(rep):
    merged, poses, stats = scan360.scan_stacks_to_cloud(
        stacks_dev, calib, proj.col_bits, proj.row_bits, params=params,
        key=jax.random.PRNGKey(rep + 1), with_stats=True)
    return merged


print("warming...", flush=True)
run(-1)
print("tracing...", flush=True)
with trace.device_trace(OUT):
    m = run(7)
print(f"traced: {len(m)} pts -> {OUT}", flush=True)

from xprof.convert import raw_to_tool_data as rtd  # noqa: E402

f = glob.glob(OUT + "/plugins/profile/*/*.xplane.pb")
data, _ = rtd.xspace_to_tool_data(f, "hlo_stats", {})
d = json.loads(data)
cols = [c["label"] if isinstance(c, dict) else c for c in d["cols"]]
i_self = next(i for i, c in enumerate(cols) if "self" in c.lower()
              and "us" in c.lower())
i_src = next((i for i, c in enumerate(cols) if "source" in c.lower()), None)
i_cat = next((i for i, c in enumerate(cols) if "category" in c.lower()), 1)
rows = []
for r in d["rows"]:
    c = r["c"] if isinstance(r, dict) else r
    vals = [x.get("v") if isinstance(x, dict) else x for x in c]
    rows.append(vals)
rows.sort(key=lambda v: -(v[i_self] or 0))
total = sum(v[i_self] or 0 for v in rows)
print(f"\ntotal self time: {total/1e3:.1f} ms; top 30:")
for v in rows[:30]:
    src = (v[i_src] or "")[:60] if i_src is not None else ""
    print(f"  {v[i_self]/1e3:8.2f} ms  {str(v[i_cat])[:28]:28s} {src}")
