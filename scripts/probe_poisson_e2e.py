"""End-to-end TPU check after the round-5 Poisson changes: depth-10 @1M
wall-clock (bench config 3c shape; was 5.90 s) and full-solve pallas-vs-
XLA equivalence at depth 9. Run alone."""

import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson_sparse as ps,
    pointcloud,
)

rng = np.random.default_rng(0)
n3 = 1 << 20
theta = rng.uniform(0, 2 * np.pi, n3)
zz = rng.uniform(-80, 80, n3)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts = jax.device_put(jnp.asarray(cloud))
nrm, _ = pointcloud.estimate_normals(pts, k=12)
nrm = pointcloud.orient_normals(pts, nrm,
                                jnp.asarray([0.0, 0.0, 500.0]), outward=True)
jax.block_until_ready(nrm)

# Equivalence at depth 9 (both matvec paths on the REAL chip).
sub = pts[: 200_000]
subn = nrm[: 200_000]
outs = {}
for up in (False, True):
    ps_cg = ps._cg_sparse
    (rhs, W, nbr, bvalid, *_r) = ps._setup_sparse(
        sub, subn, jnp.ones((200_000,), bool), 512, 65_536,
        jnp.float32(4.0))
    chi, iters = ps_cg(rhs, W, rhs, nbr, bvalid, 60, 3e-4, use_pallas=up)
    outs[up] = (np.asarray(chi), int(iters))
err = np.abs(outs[True][0] - outs[False][0]).max()
ref = np.abs(outs[False][0]).max()
print(f"depth-9 CG equivalence: max|Δchi| {err:.3e} (ref max {ref:.3e}), "
      f"iters xla={outs[False][1]} pallas={outs[True][1]}", flush=True)

def run(rep):
    grid, nb = ps.reconstruct_sparse(
        pts + jnp.float32(0.001 * rep), nrm, depth=10, cg_iters=100,
        max_blocks=196_608)
    np.asarray(jnp.sum(grid.chi))
    return nb

run(-1)
for rep in range(2):
    t0 = time.perf_counter()
    nb = run(rep)
    print(f"depth-10 @1M warm: {time.perf_counter() - t0:.2f} s "
          f"({int(nb)} blocks)", flush=True)
