"""Round-5 Poisson probes on TPU, at the bench config-3c shape
(1M-point cylinder, depth 10, ~183k active blocks):

  E0  baseline _lap_band_flat matvec (6 rolls + 6 halo matmuls)
  E1  concatenated halo placement: one (M,384)@(384,512) matmul
  E2  interior stencil as a SAME-padded 3x3x3 conv over (M,8,8,8)
  E3  E1+E2 combined
  E4  splat scatter-add vs double-float scan + unique-index scatter

Measure-first harness; run alone (never with another TPU process)."""

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson_sparse as ps,
)
from structured_light_for_3d_model_replication_tpu.ops import pointcloud  # noqa: E402

BS = ps.BS
hi = jax.lax.Precision.HIGHEST

rng = np.random.default_rng(0)
n3 = 1 << 20
theta = rng.uniform(0, 2 * np.pi, n3)
zz = rng.uniform(-80, 80, n3)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts = jax.device_put(jnp.asarray(cloud))
nrm, _ = pointcloud.estimate_normals(pts, k=12)
nrm = pointcloud.orient_normals(pts, nrm,
                                jnp.asarray([0.0, 0.0, 500.0]), outward=True)
valid = jnp.ones((n3,), bool)
jax.block_until_ready(nrm)

MAXB = 196_608
(rhs, W, nbr, block_valid, block_coords, density, flat, w, cfound,
 origin, scale, n_blocks) = ps._setup_sparse(pts, nrm, valid, 1024, MAXB,
                                             jnp.float32(4.0))
jax.block_until_ready(rhs)
print(f"setup done: active blocks {int(n_blocks)}", flush=True)
m = MAXB
x = rhs  # representative band field


def timeit(f, label, reps=5):
    def run(rep):
        np.asarray(jnp.sum(f(x + jnp.float32(1e-6 * rep))))

    run(-1)
    times = []
    for rep in range(reps):
        t0 = time.perf_counter()
        run(rep)
        times.append((time.perf_counter() - t0) * 1e3)
    print(f"{label}: median {statistics.median(times):.1f} ms "
          f"({[round(t, 1) for t in times]})", flush=True)


# --- E0: baseline ---------------------------------------------------------
timeit(jax.jit(lambda xx: ps._lap_band_flat(xx, nbr)), "E0 baseline matvec")

# --- E1: concatenated halo matmul ----------------------------------------
_PLACE_ALL = jnp.asarray(np.concatenate([ps._PLACE[d] for d in range(6)],
                                        axis=0))  # (384, 512)


def lap_e1(xx):
    faces = xx[:, ps._FACES_ALL].reshape(m, 6, BS * BS)
    fpad = jnp.concatenate([faces, jnp.zeros((1, 6, BS * BS), xx.dtype)])
    acc = jnp.zeros_like(xx)
    halos = []
    for d in range(6):
        delta, interior, *_ = ps._dir_consts(d)
        acc = acc + jnp.roll(xx, -delta, axis=1) * interior
        halos.append(fpad[:, ps._OPP[d], :][nbr[:, d]])
    halo_all = jnp.concatenate(halos, axis=1)          # (M, 384)
    acc = acc + jnp.matmul(halo_all, _PLACE_ALL, precision=hi)
    return acc - 6.0 * xx


timeit(jax.jit(lap_e1), "E1 concat-halo matvec")

# --- E2: conv interior ----------------------------------------------------
K = np.zeros((3, 3, 3), np.float32)
K[0, 1, 1] = K[2, 1, 1] = K[1, 0, 1] = K[1, 2, 1] = K[1, 1, 0] = \
    K[1, 1, 2] = 1.0
KERN = jnp.asarray(K.reshape(3, 3, 3, 1, 1))


def interior_conv(xx):
    g = xx.reshape(m, BS, BS, BS, 1)
    out = jax.lax.conv_general_dilated(
        g, KERN, window_strides=(1, 1, 1), padding="SAME",
        dimension_numbers=("NHWDC", "HWDIO", "NHWDC"),
        precision=hi)
    return out.reshape(m, BS ** 3)


def lap_e2(xx):
    faces = xx[:, ps._FACES_ALL].reshape(m, 6, BS * BS)
    fpad = jnp.concatenate([faces, jnp.zeros((1, 6, BS * BS), xx.dtype)])
    acc = interior_conv(xx)
    for d in range(6):
        halo = fpad[:, ps._OPP[d], :][nbr[:, d]]
        acc = acc + jnp.matmul(halo, jnp.asarray(ps._PLACE[d]),
                               precision=hi)
    return acc - 6.0 * xx


timeit(jax.jit(lap_e2), "E2 conv-interior matvec")


# --- E3: both -------------------------------------------------------------
def lap_e3(xx):
    faces = xx[:, ps._FACES_ALL].reshape(m, 6, BS * BS)
    fpad = jnp.concatenate([faces, jnp.zeros((1, 6, BS * BS), xx.dtype)])
    halos = [fpad[:, ps._OPP[d], :][nbr[:, d]] for d in range(6)]
    acc = interior_conv(xx) + jnp.matmul(
        jnp.concatenate(halos, axis=1), _PLACE_ALL, precision=hi)
    return acc - 6.0 * xx


timeit(jax.jit(lap_e3), "E3 conv+concat matvec")

# Equivalence check (E1/E2/E3 vs E0) on the real band field.
ref = ps._lap_band_flat(x, nbr)
for name, f in (("E1", lap_e1), ("E2", lap_e2), ("E3", lap_e3)):
    got = jax.jit(f)(x)
    err = float(jnp.max(jnp.abs(got - ref)))
    den = float(jnp.max(jnp.abs(ref)))
    print(f"{name} max abs err vs E0: {err:.3e} (ref max {den:.3e})",
          flush=True)

# --- E4: splat scatter vs double-float scan + unique scatter --------------
# Stand-in contribution stream at the real shape: 8.4M sorted rows, ~4
# rows per unique destination.
NROWS = n3 * 8
dest_np = np.sort(rng.integers(0, NROWS // 4, NROWS).astype(np.int64))
dest_dev = jax.device_put(jnp.asarray(dest_np.astype(np.int32)))
contrib_dev = jax.device_put(jnp.asarray(
    rng.normal(size=(NROWS, 4)).astype(np.float32)))
ACC_ROWS = NROWS // 4 + 1


def splat_scatter(c):
    acc = jnp.zeros((ACC_ROWS, 4), jnp.float32)
    return acc.at[dest_dev].add(c, indices_are_sorted=True)


def _two_sum(a, b):
    s = a + b
    bv = s - a
    err = (a - (s - bv)) + (b - bv)
    return s, err


def _df_add(x, y):
    """Double-float (hi, lo) addition — error-free-transform based;
    associative to ~2^-48, good enough to recover exact-f32 segment sums
    from prefix differences (the plain-f32 cumsum dedup measured a real
    surface-error regression in round 4)."""
    (xh, xl), (yh, yl) = x, y
    s, e = _two_sum(xh, yh)
    e = e + (xl + yl)
    hi_ = s + e
    lo_ = e - (hi_ - s)
    return hi_, lo_


def splat_scan(c):
    pre_h, pre_l = jax.lax.associative_scan(
        _df_add, (c, jnp.zeros_like(c)), axis=0)
    last = jnp.concatenate([dest_dev[1:] != dest_dev[:-1],
                            jnp.ones((1,), bool)])
    # Segment sum = prefix[last] - prefix[previous last] in df arithmetic.
    (idx,) = jnp.nonzero(last, size=ACC_ROWS - 1, fill_value=NROWS - 1)
    seg_end_h = pre_h[idx]
    seg_end_l = pre_l[idx]
    prev_h = jnp.concatenate([jnp.zeros((1, 4)), seg_end_h[:-1]])
    prev_l = jnp.concatenate([jnp.zeros((1, 4)), seg_end_l[:-1]])
    seg = (seg_end_h - prev_h) + (seg_end_l - prev_l)
    seg_dest = dest_dev[idx]
    valid_seg = jnp.arange(ACC_ROWS - 1) < jnp.sum(last)
    # Invalid (padding) segments route to a dump row past the slice; the
    # real destinations are unique by construction.
    out = jnp.zeros((ACC_ROWS + 1, 4), jnp.float32)
    return out.at[jnp.where(valid_seg, seg_dest, ACC_ROWS)].set(
        jnp.where(valid_seg[:, None], seg, 0.0))[:ACC_ROWS]


def time_splat(f, label):
    def run(rep):
        np.asarray(jnp.sum(f(contrib_dev + jnp.float32(1e-6 * rep))))

    run(-1)
    times = []
    for rep in range(3):
        t0 = time.perf_counter()
        run(rep)
        times.append((time.perf_counter() - t0) * 1e3)
    print(f"{label}: median {statistics.median(times):.1f} ms "
          f"({[round(t, 1) for t in times]})", flush=True)


time_splat(jax.jit(splat_scatter), "E4a sorted scatter-add (baseline)")
time_splat(jax.jit(splat_scan), "E4b double-float scan + unique set")
a = jax.jit(splat_scatter)(contrib_dev)
b = jax.jit(splat_scan)(contrib_dev)
err = float(jnp.max(jnp.abs(a - b)))
print(f"E4 max abs err: {err:.3e} (acc max {float(jnp.max(jnp.abs(a))):.3e})",
      flush=True)
