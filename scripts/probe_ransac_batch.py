"""RANSAC hypothesis-batch sweep at the ring shape (23 vmapped edges,
8192-pt clouds, 100k budget). r3 measured 2048→8192 as a win
(step-chain bound); this asks whether 16384/32768 keep paying. Run
alone."""

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.models import merge  # noqa: E402
from structured_light_for_3d_model_replication_tpu.ops import registration  # noqa: E402

rng = np.random.default_rng(0)


def view(i):
    u = rng.normal(size=(8192, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    r = 80 + 8 * np.sin(4 * u[:, 0] + 0.3 * i) * np.cos(3 * u[:, 1])
    p = u * r[:, None] + np.asarray([0.0, 10.0, 500.0])
    th = np.radians(15.0 * i)
    R = np.array([[np.cos(th), 0, np.sin(th)], [0, 1, 0],
                  [-np.sin(th), 0, np.cos(th)]])
    return (p @ R.T).astype(np.float32)


pts = jax.device_put(jnp.asarray(np.stack([view(i) for i in range(24)])))
val = jnp.ones((24, 8192), bool)
pre = jax.jit(jax.vmap(
    lambda p, v: merge._preprocess(p, v, 3.0, 30, 100)))(pts, val)
dpts, dval, nrm, feat = jax.block_until_ready(pre)

s_pts, s_val, s_feat = dpts[1:], dval[1:], feat[1:]
d_pts, d_val, d_feat = dpts[:-1], dval[:-1], feat[:-1]

for batch in (8192, 16384, 32768):
    def edge(sp, sf, dp, df, sv, dv, key):
        r = registration.ransac_feature_registration(
            sp, sf, dp, df, distance_threshold=4.5,
            src_valid=sv, dst_valid=dv, num_iterations=100_000,
            batch=batch, key=key)
        return r.transformation, r.fitness

    f = jax.jit(jax.vmap(edge))

    def run(rep):
        keys = jax.random.split(jax.random.PRNGKey(rep + 7), 23)
        T, fit = f(s_pts + jnp.float32(1e-4 * rep), s_feat, d_pts,
                   d_feat, s_val, d_val, keys)
        np.asarray(jnp.sum(T))
        return fit

    run(-1)
    ts = []
    for rep in range(3):
        t0 = time.perf_counter()
        fit = run(rep)
        ts.append((time.perf_counter() - t0) * 1e3)
    print(f"batch={batch}: median {statistics.median(ts):.0f} ms "
          f"({[round(t) for t in ts]}), min fitness "
          f"{float(jnp.min(fit)):.3f}", flush=True)
