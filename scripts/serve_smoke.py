#!/usr/bin/env python
"""CI smoke for the serving layer: real process, real signal, real bytes.

Starts ``cli serve`` as a subprocess on a free port (tiny projector so the
warmup compiles in seconds), submits ONE synthetic capture over HTTP,
asserts a non-empty STL mesh comes back, then SIGTERMs the server and
asserts a clean graceful drain (exit code 0, "drained clean" on stderr).
Everything is bounded by an overall deadline so a hang fails loudly
instead of eating the CI job's timeout.

Run: ``python scripts/serve_smoke.py`` (CPU is fine; CI uses
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import os
import re
import signal
import struct
import subprocess
import sys
import threading
import time

DEADLINE_S = 420.0
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tiny rig: 64x32 projector (6+5 bits, 24 frames), 24x40 camera.
PROJ_W, PROJ_H = 64, 32
CAM_H, CAM_W = 24, 40


def _fail(msg: str, proc: subprocess.Popen | None = None,
          stderr_lines: list | None = None) -> "NoReturn":
    print(f"SMOKE FAIL: {msg}", file=sys.stderr)
    if stderr_lines:
        print("--- server stderr ---", file=sys.stderr)
        print("".join(stderr_lines[-50:]), file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    sys.exit(1)


def main() -> int:
    t_start = time.monotonic()
    sys.path.insert(0, REPO)
    import numpy as np  # noqa: F401  (stack build below)

    from structured_light_for_3d_model_replication_tpu.config import (
        ProjectorConfig,
    )
    from structured_light_for_3d_model_replication_tpu.models import (
        synthetic,
    )
    from structured_light_for_3d_model_replication_tpu.serve.client import (
        ServeClient,
    )

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m",
           "structured_light_for_3d_model_replication_tpu.cli", "serve",
           "--port", "0", "--proj-width", str(PROJ_W),
           "--proj-height", str(PROJ_H),
           "--buckets", f"{CAM_H}x{CAM_W}", "--batch-sizes", "1,2",
           "--mesh-depth", "6", "--drain-timeout", "60"]
    print("starting:", " ".join(cmd))
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stderr=subprocess.PIPE, text=True)

    stderr_lines: list[str] = []
    port_event = threading.Event()
    port = [None]

    def pump():
        for line in proc.stderr:
            stderr_lines.append(line)
            m = re.search(r"serving on :(\d+)", line)
            if m:
                port[0] = int(m.group(1))
                port_event.set()
        port_event.set()  # EOF: unblock the waiter either way

    threading.Thread(target=pump, daemon=True).start()

    if not port_event.wait(DEADLINE_S) or port[0] is None:
        _fail("server never announced its port", proc, stderr_lines)
    print(f"server up on :{port[0]} "
          f"({time.monotonic() - t_start:.1f}s to ready)")

    # One synthetic scan over the wire → STL back.
    proj = ProjectorConfig(width=PROJ_W, height=PROJ_H)
    cam = synthetic.default_calibration(CAM_H, CAM_W, proj)
    stack, _ = synthetic.render_scan(synthetic.Scene(), *cam,
                                     CAM_H, CAM_W, proj)
    client = ServeClient(f"http://127.0.0.1:{port[0]}", timeout_s=60.0)
    health = client.healthz()
    # /healthz is liveness (always ok while answering); READINESS —
    # warmup done, worker lanes alive — is the /readyz contract.
    ready = client.readyz()
    if not health.get("ok") or not ready.get("ready"):
        _fail(f"server not ready: health={health.get('ok')} "
              f"ready={ready}", proc, stderr_lines)

    data, status = client.run(stack, result_format="stl",
                              timeout_s=DEADLINE_S)
    if len(data) <= 84:
        _fail(f"STL result too small ({len(data)} bytes)", proc,
              stderr_lines)
    (n_faces,) = struct.unpack("<I", data[80:84])  # binary STL face count
    if n_faces == 0 or n_faces != status["result"]["faces"]:
        _fail(f"empty/inconsistent mesh: header={n_faces}, "
              f"status={status['result']}", proc, stderr_lines)
    print(f"got mesh: {n_faces} faces, {len(data)} bytes "
          f"(coverage {status['result']['coverage']})")

    metrics = client.metrics()
    if "serve_program_cache_hits_total" not in metrics:
        _fail("metrics endpoint missing cache counters", proc,
              stderr_lines)

    # Graceful drain on SIGTERM.
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=max(10.0,
                                   DEADLINE_S - (time.monotonic()
                                                 - t_start)))
    except subprocess.TimeoutExpired:
        _fail("server did not exit after SIGTERM", proc, stderr_lines)
    if rc != 0:
        _fail(f"server exited {rc} after SIGTERM", proc, stderr_lines)
    time.sleep(0.2)  # let the pump thread catch the final lines
    if not any("drained clean" in line for line in stderr_lines):
        _fail("no 'drained clean' in server stderr", None, stderr_lines)
    print(f"SMOKE PASS in {time.monotonic() - t_start:.1f}s "
          "(submit → mesh → SIGTERM → clean drain)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
