"""Reproduce and bisect the BENCH r5 depth-15 p90 error tail (4.63 vox
vs 0.29 median) at the full 1M-point bench shape, isolating the coarse
grid as the variable. Jacobi preconditioner on both runs so the only
difference is `coarse_depth`; extraction via the DEVICE path
(`ops/marching_jax.py`) — 13.8M faces, which also exercises it at
production scale.

Measured on this config (CPU, 2026-08):
    coarse 128³ (ratio 256): err med 0.33  p90 9.25  max 24.5 vox
    coarse 256³ (ratio 128): err med 0.13  p90 0.32  max  1.3 vox
— the tail is the unresolved coarse Dirichlet halo across the thin
band; `reconstruct_sparse` now auto-raises the coarse grid so the
coarse/fine ratio stays ≤ 128 (see docs/MESHING.md).
"""

import time

import numpy as np

import jax.numpy as jnp

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    marching_jax,
    poisson_sparse,
)


def main():
    n_pts = 1 << 20
    u = np.random.default_rng(4).normal(size=(n_pts, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    r_sphere = 25.0
    anchors = np.asarray(
        [[s * 1000.0, t * 1000.0, v * 1000.0]
         for s in (-1, 1) for t in (-1, 1) for v in (-1, 1)], np.float32)
    pts = jnp.asarray(np.vstack([(u * r_sphere).astype(np.float32),
                                 anchors]))
    nrm = jnp.asarray(np.vstack(
        [u.astype(np.float32),
         np.tile([1.0, 0.0, 0.0], (8, 1)).astype(np.float32)]))

    for cd in (7, 8):
        t0 = time.time()
        grid, nb = poisson_sparse.reconstruct_sparse(
            pts, nrm, depth=15, cg_iters=100, max_blocks=131_072,
            coarse_depth=cd, preconditioner="jacobi")
        solve_s = time.time() - t0
        voxel = float(grid.scale)
        t0 = time.time()
        mesh = marching_jax.extract_sparse_jax(grid)
        ext_s = time.time() - t0
        rad = np.linalg.norm(mesh.vertices, axis=1)
        shell = rad < 500.0
        err = np.abs(rad[shell] - r_sphere) / voxel
        print(f"coarse_depth={cd}: solve {solve_s:.0f}s extract "
              f"{ext_s:.0f}s blocks {int(nb)} faces {len(mesh.faces)} "
              f"shell {shell.mean():.3f} err med {np.median(err):.2f} "
              f"p90 {np.percentile(err, 90):.2f} "
              f"p99 {np.percentile(err, 99):.2f} max {err.max():.1f} vox",
              flush=True)
        del grid, mesh


if __name__ == "__main__":
    main()
