"""Round-5 on-TPU measurement: gather vs brick FPFH at the ring
preprocess shape (24 views x 8192 pts, voxel 3.0), plus the rewritten
brick_knn rescue-pass cost at 1M. Not part of the test suite — a
measure-first harness (run alone; never concurrently with another TPU
process)."""

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.models import merge  # noqa: E402
from structured_light_for_3d_model_replication_tpu.ops.brickknn import (  # noqa: E402
    brick_knn,
)

rng = np.random.default_rng(0)


def view(i):
    u = rng.normal(size=(8192, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    r = 80 + 8 * np.sin(4 * u[:, 0] + i) * np.cos(3 * u[:, 1])
    p = u * r[:, None] + np.asarray([0.0, 10.0, 500.0])
    return p.astype(np.float32)


pts = jax.device_put(jnp.asarray(np.stack([view(i) for i in range(24)])))
val = jnp.ones((24, 8192), bool)
jax.block_until_ready(pts)

for engine in ("gather", "brick"):
    f = jax.jit(jax.vmap(
        lambda p, v: merge._preprocess(p, v, 3.0, 30, 100, engine)))

    def run(rep):
        o = f(pts + jnp.float32(0.001 * rep), val)
        np.asarray(jnp.sum(o[3]) + jnp.sum(o[2]))

    t0 = time.perf_counter()
    run(-1)  # compile+warm
    warm = time.perf_counter() - t0
    times = []
    for rep in range(5):
        t0 = time.perf_counter()
        run(rep)
        times.append((time.perf_counter() - t0) * 1e3)
    print(f"preprocess[{engine}]: median {statistics.median(times):.1f} ms "
          f"(runs {[round(t) for t in times]}, warm/compile {warm:.1f} s)",
          flush=True)

# Rescue-pass cost at 1M (bench config 3b shape).
theta = rng.uniform(0, 2 * np.pi, 1 << 20)
zz = rng.uniform(-80, 80, 1 << 20)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts1m = jax.device_put(jnp.asarray(cloud))
jax.block_until_ready(pts1m)

for rescue in (False, True):
    def run_knn(rep):
        out = brick_knn(pts1m + jnp.float32(0.001 * rep), 20,
                        exclude_self=True, rescue=rescue,
                        return_dropped=True)
        np.asarray(jnp.sum(out[0]))
        return out[3]

    run_knn(-1)
    times = []
    for rep in range(3):
        t0 = time.perf_counter()
        nd = run_knn(rep)
        times.append((time.perf_counter() - t0) * 1e3)
    print(f"brick_knn[rescue={rescue}]: median "
          f"{statistics.median(times):.0f} ms, dropped={int(nd)}",
          flush=True)
