"""Stage-isolation probe for the brick FPFH cost on TPU: which part of
the 2.7 s (vs 0.7 s gather) is the money — brick gathers, pair d2+mask,
Darboux trig, or the one-hot histogram? Variants run the real layout
with later stages replaced by cheap reductions. Measure-first harness;
run alone."""

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import features_brick as fb  # noqa: E402
from structured_light_for_3d_model_replication_tpu.ops import features  # noqa: E402
from structured_light_for_3d_model_replication_tpu.ops.brickknn import (  # noqa: E402
    _sorted_segments,
)

rng = np.random.default_rng(0)


def view(i):
    u = rng.normal(size=(8192, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    r = 80 + 8 * np.sin(4 * u[:, 0] + i) * np.cos(3 * u[:, 1])
    p = u * r[:, None] + np.asarray([0.0, 10.0, 500.0])
    return p.astype(np.float32)


pts = jax.device_put(jnp.asarray(np.stack([view(i) for i in range(24)])))
nrm = pts / jnp.linalg.norm(pts, axis=-1, keepdims=True)  # fake but unit
val = jnp.ones((24, 8192), bool)
jax.block_until_ready((pts, nrm))
RADIUS = 15.0


def timeit(f, label):
    def run(rep):
        o = f(pts + jnp.float32(0.001 * rep), nrm, val)
        np.asarray(sum(jnp.sum(x) for x in jax.tree.leaves(o)))

    run(-1)
    times = []
    for rep in range(4):
        t0 = time.perf_counter()
        run(rep)
        times.append((time.perf_counter() - t0) * 1e3)
    print(f"{label}: median {statistics.median(times):.0f} ms "
          f"({[round(t) for t in times]})", flush=True)


def staged(stage, slots, chunk_rows=512):
    """stage: 'sort' | 'gather' | 'mask' | 'spfh' | 'full'."""
    S, M = slots, 1024

    def one(p, nv, v):
        n = p.shape[0]
        cid = fb._cell_ids(p, v, jnp.float32(RADIUS))
        (cid_s, pts_s, val_s, orig_s, _f, _r, ok, dest,
         ucid) = _sorted_segments(p, v, cid, S, M)
        if stage == "sort":
            return (pts_s, dest)
        nrm_s = nv[orig_s]

        def brick(vals, fill, dtype):
            shape = (M * S + 1,) + vals.shape[1:]
            t = jnp.full(shape, fill, dtype).at[dest].set(vals)
            return t[:-1].reshape((M, S) + vals.shape[1:])

        bp = brick(pts_s, 0.0, jnp.float32)
        bn = brick(nrm_s, 0.0, jnp.float32)
        bv = brick(ok, False, bool)
        bo = brick(orig_s, -1, jnp.int32)
        pad = lambda t, fill: jnp.concatenate(
            [t, jnp.full((1,) + t.shape[1:], fill, t.dtype)])
        bppad, bnpad, bvpad, bopad = (pad(bp, 0.0), pad(bn, 0.0),
                                      pad(bv, False), pad(bo, -1))
        nbr = fb._row_neighbor_bricks(cid_s, ucid, M)

        hi = jax.lax.Precision.HIGHEST
        r2 = jnp.float32(RADIUS * RADIUS)

        def chunkf(args):
            q, qn, qo, qv, nb = args
            c = q.shape[0]
            kp = bppad[nb].reshape(c, 27 * S, 3)
            kv = bvpad[nb].reshape(c, 27 * S)
            ko = bopad[nb].reshape(c, 27 * S)
            kn = bnpad[nb].reshape(c, 27 * S, 3)
            if stage == "gather":
                return (jnp.sum(kp, axis=(1, 2)) + jnp.sum(kn, axis=(1, 2))
                        + jnp.sum(kv, axis=1) + jnp.sum(ko, axis=1))
            q2 = jnp.sum(q * q, axis=-1, keepdims=True)
            p2 = jnp.sum(kp * kp, axis=-1)
            cross = jnp.einsum("cd,cnd->cn", q, kp, precision=hi)
            d2 = q2 + p2 - 2.0 * cross
            pair_ok = kv & (d2 <= r2) & (ko != qo[:, None]) & qv[:, None]
            if stage == "mask":
                return jnp.sum(pair_ok, axis=1) + jnp.sum(kn[..., 0], axis=1)
            dvec = kp - q[:, None, :]
            dist = jnp.sqrt(jnp.maximum(jnp.sum(dvec * dvec, -1), 1e-20))
            dn = dvec / dist[..., None]
            u = jnp.broadcast_to(qn[:, None, :], dvec.shape)
            vv = jnp.cross(u, dn)
            v_norm = jnp.linalg.norm(vv, axis=-1, keepdims=True)
            vv = vv / jnp.where(v_norm > 1e-12, v_norm, 1.0)
            w = jnp.cross(u, vv)
            alpha = jnp.sum(vv * kn, axis=-1)
            phi = jnp.sum(u * dn, axis=-1)
            theta = jnp.arctan2(jnp.sum(w * kn, axis=-1),
                                jnp.sum(u * kn, axis=-1))
            bins = jnp.stack([fb._bin(alpha, -1.0, 1.0),
                              fb._bin(phi, -1.0, 1.0),
                              fb._bin(theta, -jnp.pi, jnp.pi)], axis=-1)
            onehot = jax.nn.one_hot(bins, 11, dtype=jnp.float32)
            onehot = onehot * pair_ok[..., None, None]
            spfh = onehot.sum(axis=1).reshape(c, 33)
            return spfh

        padr = (-n) % chunk_rows

        def padded(x, fill):
            return jnp.concatenate(
                [x, jnp.full((padr,) + x.shape[1:], fill, x.dtype)]
            ) if padr else x

        def chunked(x):
            return x.reshape((-1, chunk_rows) + x.shape[1:])

        out = jax.lax.map(chunkf, (chunked(padded(pts_s, 0.0)),
                                   chunked(padded(nrm_s, 0.0)),
                                   chunked(padded(orig_s, -1)),
                                   chunked(padded(val_s, False)),
                                   chunked(padded(nbr, M))))
        return out

    return jax.jit(jax.vmap(one))


timeit(jax.jit(jax.vmap(
    lambda p, nv, v: features.fpfh(p, nv, RADIUS, valid=v, max_nn=100))),
    "gather-full (incl its knn)")
for slots in (32, 48):
    for stage in ("sort", "gather", "mask", "spfh"):
        timeit(staged(stage, slots), f"brick[{stage},S={slots}]")
timeit(jax.jit(jax.vmap(
    lambda p, nv, v: fb.fpfh_brick(p, nv, RADIUS, valid=v, slots=32))),
    "brick-full[S=32]")
