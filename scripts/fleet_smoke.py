#!/usr/bin/env python
"""CI fleet smoke: 2 replicas + 2 HA routers over a FAKE OBJECT STORE
with seeded blob-store faults, SIGKILL one router AND the pinned
replica, assert availability end to end.

The tier-1-safe end of the fleet chaos spectrum (the 3-replica chaos
gate with offered load, peer-network faults and fresh-node recovery is
``tests/test_fleet.py::test_fleet_chaos_gate``; the measured version is
bench config [10]):

1. start an in-process :class:`serve.blobstore.ObjectStoreServer` —
   the replicas' ``--handoff-dir`` and the routers' ``--pin-store``
   both point at it over HTTP, so NOTHING in the fleet shares a POSIX
   volume — and arm ``SL_BLOB_FAULTS`` (latency + torn writes) in the
   replica processes: store faults must degrade durability counters,
   never availability;
2. spawn replicas r0/r1 (`cli serve` on the soak-smoke tiny rig, each
   with its own local ``--store-dir``, peered at each other) and TWO
   `cli serve --router` processes peered at each other, sharing the
   pin board through the object store;
3. via router A: one-shot job completes; a duplicate hits the content
   cache; a duplicate pushed directly at the OTHER replica comes back
   as a PEER hit (the shared-cache path);
4. open a session via router A, fuse stop 1, then **SIGKILL router A
   and the pinned replica**. The client rotates to router B, which
   re-learns the pin from the shared board and whose failure detector
   proactively adopts the session onto the survivor — the next stop
   and finalize must succeed, and every job acked anywhere must reach
   ``done`` (zero lost acked jobs);
5. SIGTERM survivor + router B: clean exits, the survivor's journal
   volume drains clean, and the object store holds no live session
   streams.

This module is also the SHARED SPAWN RECIPE for the fleet gates:
``spawn_fleet`` / ``spawn_router`` are imported by tests/test_fleet.py
and bench config [10] (same import-by-path pattern soak_smoke.py
established), so every fleet gate exercises the same ports/flags/rig.

CI runs this as the `fleet-smoke` job with SL_SANITIZE=1 (ci.yml).
"""

from __future__ import annotations

import importlib.util
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time

DEADLINE_S = 540.0
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SOAK_SPEC = importlib.util.spec_from_file_location(
    "soak_smoke", os.path.join(REPO, "scripts", "soak_smoke.py"))
soak_smoke = importlib.util.module_from_spec(_SOAK_SPEC)
_SOAK_SPEC.loader.exec_module(soak_smoke)

PROJ_W, PROJ_H = soak_smoke.PROJ_W, soak_smoke.PROJ_H
CAM_H, CAM_W = soak_smoke.CAM_H, soak_smoke.CAM_W
STREAM_PARAMS = soak_smoke.STREAM_PARAMS


def free_ports(n: int) -> list[int]:
    """Pre-pick n distinct free ports: replicas need their PEERS' URLs
    at spawn time, before any of them is listening. The close→bind race
    is real but vanishing at test scale (SO_REUSEADDR on the server)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def replica_store(shared_dir: str, idx: int) -> str:
    return os.path.join(shared_dir, "replicas", f"r{idx}")


def handoff_dir(shared_dir: str) -> str:
    return os.path.join(shared_dir, "handoff")


def spawn_replica(shared_dir: str, idx: int, ports: list[int],
                  recover: bool = False, sanitize: bool = True,
                  env_extra: dict | None = None,
                  handoff: str | None = None):
    """One fleet replica on its pre-picked port: own journal volume
    under the shared dir, the shared handoff store (a directory under
    the shared dir by default, or any blob-store spec — e.g. the fake
    object service's ``http://...``), peered at every other port.
    Returns (proc, port, stderr_lines)."""
    peers = ",".join(f"http://127.0.0.1:{p}"
                     for i, p in enumerate(ports) if i != idx)
    extra = ["--port", str(ports[idx]),
             "--replica-id", f"r{idx}",
             "--handoff-dir", handoff or handoff_dir(shared_dir)]
    if peers:
        extra += ["--peers", peers]
    return soak_smoke.spawn_serve(
        replica_store(shared_dir, idx), recover=recover, extra=extra,
        sanitize=sanitize, env_extra=env_extra)


def spawn_router(ports: list[int], sanitize: bool = True,
                 timeout_s: float = 60.0, port: int = 0,
                 router_id: str | None = None, peers=(),
                 pin_store: str | None = None):
    """One thin front (`cli serve --router`) over the replica ports;
    returns (proc, router_port, stderr_lines). ``peers``/``pin_store``
    arm the HA topology (dual routers sharing the pin board)."""
    replicas = ",".join(f"http://127.0.0.1:{p}" for p in ports)
    cmd = [sys.executable, "-m",
           "structured_light_for_3d_model_replication_tpu.cli", "serve",
           "--router", "--replicas", replicas, "--port", str(port),
           "--check-interval", "0.25"]
    if router_id:
        cmd += ["--router-id", router_id]
    if peers:
        cmd += ["--router-peers", ",".join(peers)]
    if pin_store:
        cmd += ["--pin-store", pin_store]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if sanitize:
        env.setdefault("SL_SANITIZE", "1")
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stderr=subprocess.PIPE, text=True)
    lines: list[str] = []
    port = [None]
    got = threading.Event()

    def pump():
        for line in proc.stderr:
            lines.append(line)
            m = re.search(r"routing on :(\d+)", line)
            if m:
                port[0] = int(m.group(1))
                got.set()
        got.set()

    threading.Thread(target=pump, daemon=True).start()
    if not got.wait(timeout_s) or port[0] is None:
        proc.kill()
        raise soak_smoke.SpawnError(
            "router never announced its port:\n" + "".join(lines[-30:]))
    return proc, port[0], lines


def spawn_fleet(shared_dir: str, n: int = 2, sanitize: bool = True,
                env_extra: dict | None = None,
                handoff: str | None = None):
    """n replicas + ports; returns ([(proc, port, lines)], ports)."""
    ports = free_ports(n)
    out = []
    for i in range(n):
        out.append(spawn_replica(shared_dir, i, ports,
                                 sanitize=sanitize, env_extra=env_extra,
                                 handoff=handoff))
    return out, ports


def _fail(msg, procs=(), stderr_lines=None):
    print(f"FLEET SMOKE FAIL: {msg}", file=sys.stderr)
    if stderr_lines:
        print("--- stderr ---", file=sys.stderr)
        print("".join(stderr_lines[-60:]), file=sys.stderr)
    for p in procs:
        if p is not None and p.poll() is None:
            p.kill()
    sys.exit(1)


#: Seeded blob-store faults armed in the REPLICA processes (latency +
#: torn writes on the shared object store; no hard error rate — torn
#: heads are retried by the verify-then-kill loop below, hard errors
#: would only re-test the same containment nondeterministically).
BLOB_FAULTS = {"seed": 11, "latency_s": 0.03, "latency_rate": 0.25,
               "torn_write_rate": 0.05}


def main() -> int:
    t0 = time.monotonic()
    sys.path.insert(0, REPO)
    import tempfile

    import numpy as np  # noqa: F401  (spawn recipe parity)

    from structured_light_for_3d_model_replication_tpu.config import (
        ProjectorConfig,
    )
    from structured_light_for_3d_model_replication_tpu.models import (
        synthetic,
    )
    from structured_light_for_3d_model_replication_tpu.serve import (
        read_live_state,
    )
    from structured_light_for_3d_model_replication_tpu.serve.blobstore \
        import ObjectStoreServer
    from structured_light_for_3d_model_replication_tpu.serve.client import (
        ServeClient,
        ServeClientError,
    )
    from structured_light_for_3d_model_replication_tpu.serve.store import (
        SessionStreamStore,
    )

    proj = ProjectorConfig(width=PROJ_W, height=PROJ_H)
    cam = synthetic.default_calibration(CAM_H, CAM_W, proj)
    stack, _ = synthetic.render_scan(synthetic.Scene(), *cam,
                                     CAM_H, CAM_W, proj)
    scene = synthetic.Scene(
        wall_z=None,
        spheres=(synthetic.Sphere((0.0, 2.0, 500.0), 80.0, 0.9),
                 synthetic.Sphere((55.0, -30.0, 460.0), 35.0, 0.7)))
    ring = [s for s, _ in synthetic.render_turntable_scans(
        scene, n_stops=3, degrees_per_stop=12.0, cam_K=cam[0],
        proj_K=cam[1], R=cam[2], T=cam[3], cam_height=CAM_H,
        cam_width=CAM_W, proj=proj)]

    # The fake object store: handoff streams AND the router pin board
    # live here over HTTP — no process in the fleet shares a POSIX
    # volume. Replica processes see it through a FaultyBlobStore.
    ostore = ObjectStoreServer().start()
    handoff_spec = f"{ostore.url}/handoff"
    pin_spec = f"{ostore.url}/pins"
    shared = tempfile.mkdtemp(prefix="sl-fleet-smoke-")
    try:
        members, ports = spawn_fleet(
            shared, n=2, handoff=handoff_spec,
            env_extra={"SL_BLOB_FAULTS": json.dumps(BLOB_FAULTS)})
    except soak_smoke.SpawnError as e:
        _fail(str(e))
    procs = [m[0] for m in members]
    all_lines = [ln for m in members for ln in m[2]]
    rports = free_ports(2)
    rurls = [f"http://127.0.0.1:{p}" for p in rports]
    routers = []
    for i in range(2):
        try:
            routers.append(spawn_router(
                ports, port=rports[i], router_id=f"router-{'ab'[i]}",
                peers=[rurls[1 - i]], pin_store=pin_spec))
        except soak_smoke.SpawnError as e:
            _fail(str(e), procs + [r[0] for r in routers])
    procs += [r[0] for r in routers]
    client_a = ServeClient(rurls[0], timeout_s=120.0)
    # The chaos client knows BOTH routers: when A dies it rotates to B.
    client = ServeClient(rurls, timeout_s=120.0, retries=8,
                         retry_backoff_s=0.25, retry_budget_s=120.0)
    acked: list[str] = []    # every job id a 200 was returned for
    print(f"fleet up: replicas :{ports[0]}/:{ports[1]}, routers "
          f":{rports[0]}/:{rports[1]}, object store :{ostore.port} "
          f"({time.monotonic() - t0:.0f}s)")

    # One-shot via router A + local duplicate via consistent hashing.
    jid = client_a.submit(stack)
    acked.append(jid)
    st = client_a.wait(jid, timeout_s=240.0)
    if st["status"] != "done":
        _fail(f"routed job failed: {st}", procs, all_lines)
    jid2 = client_a.submit(stack)
    acked.append(jid2)
    st2 = client_a.wait(jid2, timeout_s=60.0)
    if not st2["result"].get("content_cache_hit"):
        _fail(f"routed duplicate missed the cache: {st2}", procs,
              all_lines)
    # Cross-replica duplicate straight at each replica: whichever did
    # NOT compute it must answer via the PEER cache.
    peer_hit = False
    for p in ports:
        direct = ServeClient(f"http://127.0.0.1:{p}", timeout_s=120.0)
        djid = direct.submit(stack)
        std = direct.wait(djid, timeout_s=120.0)
        if std["status"] != "done":
            _fail(f"direct duplicate failed: {std}", procs, all_lines)
        if std["result"].get("cache_source") == "peer":
            peer_hit = True
    if not peer_hit:
        _fail("no cross-replica duplicate came from the peer cache",
              procs, all_lines)
    print(f"cache: routed dup hit + cross-replica peer hit "
          f"({time.monotonic() - t0:.0f}s)")

    # Session through router A. Torn-write faults can maim the mirrored
    # stream head (durability degraded, loudly) — verify the stream is
    # adoptable on the object store BEFORE staging the kill, retrying
    # with a fresh session if not (the availability contract is about
    # serving, not about any single faulted write).
    handoff_reader = SessionStreamStore(handoff_spec)
    sid = None
    for attempt in range(6):
        cand = client_a.create_session()
        stj = client_a.wait(client_a.submit_stop(cand, ring[0]),
                            timeout_s=240.0)
        info = handoff_reader.read_session(cand)
        blob_ok = False
        if info is not None and info.stops:
            try:    # a torn stop blob would only degrade the adoption
                handoff_reader.load_blob(info.stops[0][1])
                blob_ok = True
            except Exception:
                blob_ok = False
        if stj["status"] == "done" and blob_ok:
            sid = cand
            break
        print(f"session {cand} stream not adoptable (attempt "
              f"{attempt + 1}: faulted mirror) — retrying")
        try:
            client_a.delete_session(cand)
        except ServeClientError:
            pass
    if sid is None:
        _fail("no adoptable session stream after 6 attempts", procs,
              all_lines)
    import urllib.request

    with urllib.request.urlopen(f"{rurls[0]}/fleet", timeout=10) as r:
        fleet = json.loads(r.read())
    pin = fleet["sessions_pinned"].get(sid)
    if pin is None:
        _fail(f"session not pinned: {fleet}", procs, all_lines)
    victim_idx = ports.index(int(pin.rsplit(":", 1)[1]))
    survivor_idx = 1 - victim_idx

    # SIGKILL router A AND the pinned replica: the client must rotate
    # to router B, which re-learns the pin from the shared board and
    # proactively adopts the session onto the survivor.
    routers[0][0].kill()
    routers[0][0].wait(timeout=30.0)
    members[victim_idx][0].kill()                 # SIGKILL, no drain
    members[victim_idx][0].wait(timeout=30.0)
    print(f"SIGKILLed router A and pinned replica r{victim_idx} "
          f"({time.monotonic() - t0:.0f}s)")

    # The surviving router's failure detector must adopt the session
    # in the BACKGROUND — no client op drives it (the proactive tier).
    deadline = time.monotonic() + 60.0
    repinned = False
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{rurls[1]}/fleet",
                                        timeout=10) as r:
                fb = json.loads(r.read())
            if fb["sessions_pinned"].get(sid) not in (None, pin):
                repinned = True
                break
        except OSError:
            pass
        time.sleep(0.25)
    if not repinned:
        _fail("router B never proactively re-pinned the session",
              procs, all_lines)
    print(f"proactive: router B adopted the session in the background "
          f"({time.monotonic() - t0:.0f}s)")

    stj2 = client.wait(client.submit_stop(sid, ring[1]),
                       timeout_s=240.0)
    if stj2["status"] != "done":
        _fail(f"post-kill stop failed (no handoff?): {stj2}", procs,
              all_lines)
    sst = client.session_status(sid)
    if sst.get("stops_fused") != 2:
        _fail(f"session lost stops across handoff: {sst}", procs,
              all_lines)
    # Fresh one-shot load through router B must flow (and every job
    # acked post-kill completes: zero lost acked jobs).
    for i in range(2):
        v = stack.copy()
        v[0, 0, 0] = 200 + i
        njid = client.submit(v)
        acked.append(njid)
        nst = client.wait(njid, timeout_s=240.0)
        if nst["status"] != "done":
            _fail(f"post-kill job {njid} not done: {nst}", procs,
                  all_lines)
    fin = client.finalize_session(sid, result_format="ply")
    acked.append(fin["job_id"])
    if not client.result(fin["job_id"]).startswith(b"ply"):
        _fail("finalize artifact not a PLY", procs, all_lines)
    with urllib.request.urlopen(f"{rurls[1]}/fleet", timeout=10) as r:
        fleet_b = json.loads(r.read())
    print(f"handoff: session re-pinned + finalized on survivor "
          f"r{survivor_idx} via router B (proactive_repins="
          f"{fleet_b.get('proactive_repins')}, {len(acked)} acked "
          f"jobs all done) ({time.monotonic() - t0:.0f}s)")

    # Clean exits: survivor drains clean, router B stops, no live
    # session streams left on the object store.
    for proc in (members[survivor_idx][0], routers[1][0]):
        proc.send_signal(signal.SIGTERM)
    rcs = [members[survivor_idx][0].wait(timeout=120.0),
           routers[1][0].wait(timeout=60.0)]
    if any(rc != 0 for rc in rcs):
        _fail(f"non-zero exits: {rcs}", procs, all_lines)
    state = read_live_state(replica_store(shared, survivor_idx))
    if state.jobs or state.sessions:
        _fail(f"survivor journal not clean: {len(state.jobs)} jobs, "
              f"{len(state.sessions)} sessions", procs, all_lines)
    streams = handoff_reader.list_sessions()
    if streams:
        _fail(f"handoff streams left behind: {streams}", procs,
              all_lines)
    ostore.stop()
    print(f"FLEET SMOKE PASS in {time.monotonic() - t0:.0f}s "
          "(2 routers + 2 replicas over the fake object store with "
          "blob faults, SIGKILL router A + pinned replica, handoff to "
          "survivor via router B, zero lost acked jobs, clean drains, "
          "no live streams)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
