#!/usr/bin/env python
"""CI fleet smoke: 2 real replica processes + a real router process,
kill one replica mid-session, assert the session hands off.

The tier-1-safe end of the fleet chaos spectrum (the 3-replica chaos
gate with offered load, peer-network faults and fresh-node recovery is
``tests/test_fleet.py::test_fleet_chaos_gate``; the measured version is
bench config [10]):

1. spawn replicas r0/r1 (`cli serve` on the soak-smoke tiny rig, each
   with its own ``--store-dir`` under one shared volume plus the shared
   ``--handoff-dir``, peered at each other) and a `cli serve --router`
   process fronting both;
2. via the ROUTER: one-shot job completes; a duplicate submit hits the
   content cache (consistent-hash placement makes it a local hit); a
   duplicate pushed directly at the OTHER replica comes back as a PEER
   hit (the shared-cache path);
3. open a session via the router, fuse stop 1, then **SIGKILL the
   pinned replica**. The next stop through the router must succeed —
   the router re-pins the session onto the survivor, which adopts it
   from the handoff stream — and finalize must return a mesh;
4. SIGTERM survivor + router: clean exits, the survivor's journal
   volume drains clean, and the handoff dir holds no session streams.

This module is also the SHARED SPAWN RECIPE for the fleet gates:
``spawn_fleet`` / ``spawn_router`` are imported by tests/test_fleet.py
and bench config [10] (same import-by-path pattern soak_smoke.py
established), so every fleet gate exercises the same ports/flags/rig.

CI runs this as the `fleet-smoke` job with SL_SANITIZE=1 (ci.yml).
"""

from __future__ import annotations

import importlib.util
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time

DEADLINE_S = 540.0
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SOAK_SPEC = importlib.util.spec_from_file_location(
    "soak_smoke", os.path.join(REPO, "scripts", "soak_smoke.py"))
soak_smoke = importlib.util.module_from_spec(_SOAK_SPEC)
_SOAK_SPEC.loader.exec_module(soak_smoke)

PROJ_W, PROJ_H = soak_smoke.PROJ_W, soak_smoke.PROJ_H
CAM_H, CAM_W = soak_smoke.CAM_H, soak_smoke.CAM_W
STREAM_PARAMS = soak_smoke.STREAM_PARAMS


def free_ports(n: int) -> list[int]:
    """Pre-pick n distinct free ports: replicas need their PEERS' URLs
    at spawn time, before any of them is listening. The close→bind race
    is real but vanishing at test scale (SO_REUSEADDR on the server)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def replica_store(shared_dir: str, idx: int) -> str:
    return os.path.join(shared_dir, "replicas", f"r{idx}")


def handoff_dir(shared_dir: str) -> str:
    return os.path.join(shared_dir, "handoff")


def spawn_replica(shared_dir: str, idx: int, ports: list[int],
                  recover: bool = False, sanitize: bool = True,
                  env_extra: dict | None = None):
    """One fleet replica on its pre-picked port: own journal volume
    under the shared dir, the shared handoff volume, peered at every
    other port. Returns (proc, port, stderr_lines)."""
    peers = ",".join(f"http://127.0.0.1:{p}"
                     for i, p in enumerate(ports) if i != idx)
    extra = ["--port", str(ports[idx]),
             "--replica-id", f"r{idx}",
             "--handoff-dir", handoff_dir(shared_dir)]
    if peers:
        extra += ["--peers", peers]
    return soak_smoke.spawn_serve(
        replica_store(shared_dir, idx), recover=recover, extra=extra,
        sanitize=sanitize, env_extra=env_extra)


def spawn_router(ports: list[int], sanitize: bool = True,
                 timeout_s: float = 60.0):
    """The thin front (`cli serve --router`) over the replica ports;
    returns (proc, router_port, stderr_lines)."""
    replicas = ",".join(f"http://127.0.0.1:{p}" for p in ports)
    cmd = [sys.executable, "-m",
           "structured_light_for_3d_model_replication_tpu.cli", "serve",
           "--router", "--replicas", replicas, "--port", "0",
           "--check-interval", "0.25"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if sanitize:
        env.setdefault("SL_SANITIZE", "1")
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stderr=subprocess.PIPE, text=True)
    lines: list[str] = []
    port = [None]
    got = threading.Event()

    def pump():
        for line in proc.stderr:
            lines.append(line)
            m = re.search(r"routing on :(\d+)", line)
            if m:
                port[0] = int(m.group(1))
                got.set()
        got.set()

    threading.Thread(target=pump, daemon=True).start()
    if not got.wait(timeout_s) or port[0] is None:
        proc.kill()
        raise soak_smoke.SpawnError(
            "router never announced its port:\n" + "".join(lines[-30:]))
    return proc, port[0], lines


def spawn_fleet(shared_dir: str, n: int = 2, sanitize: bool = True,
                env_extra: dict | None = None):
    """n replicas + ports; returns ([(proc, port, lines)], ports)."""
    ports = free_ports(n)
    out = []
    for i in range(n):
        out.append(spawn_replica(shared_dir, i, ports,
                                 sanitize=sanitize, env_extra=env_extra))
    return out, ports


def _fail(msg, procs=(), stderr_lines=None):
    print(f"FLEET SMOKE FAIL: {msg}", file=sys.stderr)
    if stderr_lines:
        print("--- stderr ---", file=sys.stderr)
        print("".join(stderr_lines[-60:]), file=sys.stderr)
    for p in procs:
        if p is not None and p.poll() is None:
            p.kill()
    sys.exit(1)


def main() -> int:
    t0 = time.monotonic()
    sys.path.insert(0, REPO)
    import tempfile

    import numpy as np

    from structured_light_for_3d_model_replication_tpu.config import (
        ProjectorConfig,
    )
    from structured_light_for_3d_model_replication_tpu.models import (
        synthetic,
    )
    from structured_light_for_3d_model_replication_tpu.serve import (
        read_live_state,
    )
    from structured_light_for_3d_model_replication_tpu.serve.client import (
        ServeClient,
    )
    from structured_light_for_3d_model_replication_tpu.serve.store import (
        SessionStreamStore,
    )

    proj = ProjectorConfig(width=PROJ_W, height=PROJ_H)
    cam = synthetic.default_calibration(CAM_H, CAM_W, proj)
    stack, _ = synthetic.render_scan(synthetic.Scene(), *cam,
                                     CAM_H, CAM_W, proj)
    scene = synthetic.Scene(
        wall_z=None,
        spheres=(synthetic.Sphere((0.0, 2.0, 500.0), 80.0, 0.9),
                 synthetic.Sphere((55.0, -30.0, 460.0), 35.0, 0.7)))
    ring = [s for s, _ in synthetic.render_turntable_scans(
        scene, n_stops=3, degrees_per_stop=12.0, cam_K=cam[0],
        proj_K=cam[1], R=cam[2], T=cam[3], cam_height=CAM_H,
        cam_width=CAM_W, proj=proj)]

    shared = tempfile.mkdtemp(prefix="sl-fleet-smoke-")
    try:
        members, ports = spawn_fleet(shared, n=2)
    except soak_smoke.SpawnError as e:
        _fail(str(e))
    procs = [m[0] for m in members]
    all_lines = [ln for m in members for ln in m[2]]
    try:
        rproc, rport, rlines = spawn_router(ports)
    except soak_smoke.SpawnError as e:
        _fail(str(e), procs)
    procs.append(rproc)
    client = ServeClient(f"http://127.0.0.1:{rport}", timeout_s=120.0)
    print(f"fleet up: replicas :{ports[0]}/:{ports[1]}, router :{rport} "
          f"({time.monotonic() - t0:.0f}s)")

    # One-shot via the router + local duplicate via consistent hashing.
    jid = client.submit(stack)
    st = client.wait(jid, timeout_s=240.0)
    if st["status"] != "done":
        _fail(f"routed job failed: {st}", procs, all_lines)
    st2 = client.wait(client.submit(stack), timeout_s=60.0)
    if not st2["result"].get("content_cache_hit"):
        _fail(f"routed duplicate missed the cache: {st2}", procs,
              all_lines)
    # Cross-replica duplicate straight at each replica: whichever did
    # NOT compute it must answer via the PEER cache.
    peer_hit = False
    for p in ports:
        direct = ServeClient(f"http://127.0.0.1:{p}", timeout_s=120.0)
        std = direct.wait(direct.submit(stack), timeout_s=120.0)
        if std["status"] != "done":
            _fail(f"direct duplicate failed: {std}", procs, all_lines)
        if std["result"].get("cache_source") == "peer":
            peer_hit = True
    if not peer_hit:
        _fail("no cross-replica duplicate came from the peer cache",
              procs, all_lines)
    print(f"cache: routed dup hit + cross-replica peer hit "
          f"({time.monotonic() - t0:.0f}s)")

    # Session through the router; kill the pinned replica mid-session.
    sid = client.create_session()
    stj = client.wait(client.submit_stop(sid, ring[0]), timeout_s=240.0)
    if stj["status"] != "done":
        _fail(f"stop 1 failed: {stj}", procs, all_lines)
    import urllib.request

    with urllib.request.urlopen(f"http://127.0.0.1:{rport}/fleet",
                                timeout=10) as r:
        fleet = json.loads(r.read())
    pin = fleet["sessions_pinned"].get(sid)
    if pin is None:
        _fail(f"session not pinned: {fleet}", procs, all_lines)
    victim_idx = ports.index(int(pin.rsplit(":", 1)[1]))
    survivor_idx = 1 - victim_idx
    members[victim_idx][0].kill()                 # SIGKILL, no drain
    members[victim_idx][0].wait(timeout=30.0)
    print(f"killed pinned replica r{victim_idx} "
          f"({time.monotonic() - t0:.0f}s)")

    stj2 = client.wait(client.submit_stop(sid, ring[1]), timeout_s=240.0)
    if stj2["status"] != "done":
        _fail(f"post-kill stop failed (no handoff?): {stj2}", procs,
              all_lines)
    sst = client.session_status(sid)
    if sst.get("stops_fused") != 2:
        _fail(f"session lost stops across handoff: {sst}", procs,
              all_lines)
    fin = client.finalize_session(sid, result_format="ply")
    if not client.result(fin["job_id"]).startswith(b"ply"):
        _fail("finalize artifact not a PLY", procs, all_lines)
    print(f"handoff: session re-pinned + finalized on survivor "
          f"r{survivor_idx} ({time.monotonic() - t0:.0f}s)")

    # Clean exits: survivor drains clean, router stops, handoff empty.
    for proc in (members[survivor_idx][0], rproc):
        proc.send_signal(signal.SIGTERM)
    rcs = [members[survivor_idx][0].wait(timeout=120.0),
           rproc.wait(timeout=60.0)]
    if any(rc != 0 for rc in rcs):
        _fail(f"non-zero exits: {rcs}", procs, all_lines)
    state = read_live_state(replica_store(shared, survivor_idx))
    if state.jobs or state.sessions:
        _fail(f"survivor journal not clean: {len(state.jobs)} jobs, "
              f"{len(state.sessions)} sessions", procs, all_lines)
    streams = SessionStreamStore(handoff_dir(shared)).list_sessions()
    if streams:
        _fail(f"handoff streams left behind: {streams}", procs,
              all_lines)
    print(f"FLEET SMOKE PASS in {time.monotonic() - t0:.0f}s "
          "(router + 2 replicas, SIGKILL pinned mid-session, handoff "
          "to survivor, clean drains, empty handoff volume)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
