"""On-TPU timing: Pallas stencil matvec vs the XLA matvec at the bench
depth-10 shape, plus a full CG solve A/B. Run alone."""

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from structured_light_for_3d_model_replication_tpu.ops import (  # noqa: E402
    poisson_pallas,
    poisson_sparse as ps,
    pointcloud,
)

rng = np.random.default_rng(0)
n3 = 1 << 20
theta = rng.uniform(0, 2 * np.pi, n3)
zz = rng.uniform(-80, 80, n3)
cloud = np.stack([80 * np.cos(theta), zz, 80 * np.sin(theta) + 500],
                 1).astype(np.float32)
cloud += rng.normal(0, 0.5, cloud.shape).astype(np.float32)
pts = jax.device_put(jnp.asarray(cloud))
nrm, _ = pointcloud.estimate_normals(pts, k=12)
nrm = pointcloud.orient_normals(pts, nrm,
                                jnp.asarray([0.0, 0.0, 500.0]), outward=True)
valid = jnp.ones((n3,), bool)
jax.block_until_ready(nrm)

MAXB = 196_608
(rhs, W, nbr, block_valid, *_rest) = ps._setup_sparse(
    pts, nrm, valid, 1024, MAXB, jnp.float32(4.0))
jax.block_until_ready(rhs)
print("setup done", flush=True)
x = rhs
band = block_valid[:, None]


def xla_mv(xx, Wa, nbra, bva):
    return jnp.where(bva[:, None],
                     -(ps._lap_band_flat(xx, nbra) - Wa * xx), 0.0)


def pl_mv(xx, Wa, nbra, bva):
    return poisson_pallas.matvec_pallas(xx, Wa, nbra, bva)


def pl_mv16(xx, Wa, nbra, bva):
    return poisson_pallas.matvec_pallas(xx, Wa, nbra, bva, cb=16)


def pl_mv32(xx, Wa, nbra, bva):
    return poisson_pallas.matvec_pallas(xx, Wa, nbra, bva, cb=32)


# BURST timing: 8 chained applications per launch, one host pull — the
# per-launch RTT (~110 ms) would otherwise dominate a single matvec.
# Band state travels as ARGUMENTS: closure-captured device arrays bake
# into the program as constants and the 385 MB W tensor overflows the
# remote compile service (HTTP 413) — the documented axon failure mode.
def burst(f):
    @jax.jit
    def g(xx, Wa, nbra, bva):
        return jnp.sum(jax.lax.fori_loop(
            0, 8, lambda i, v: f(v, Wa, nbra, bva) * 1e-3, xx))
    return g


def pl_v2(xx, Wa, nbra, bva):
    return poisson_pallas.matvec_pallas_v2(xx, Wa, nbra, bva)


def pl_v2_cb64(xx, Wa, nbra, bva):
    return poisson_pallas.matvec_pallas_v2(xx, Wa, nbra, bva, cb=64)


for label, f in (("xla", xla_mv), ("pallas-cb32", pl_mv32),
                 ("pallas-v2-cb32", pl_v2), ("pallas-v2-cb64", pl_v2_cb64)):
    g = burst(f)

    def run(rep):
        np.asarray(g(x + jnp.float32(1e-6 * rep), W, nbr, block_valid))

    run(-1)
    times = []
    for rep in range(5):
        t0 = time.perf_counter()
        run(rep)
        times.append((time.perf_counter() - t0) * 1e3)
    med = statistics.median(times)
    print(f"matvec[{label}]: {med / 8:.1f} ms/apply (burst8 median "
          f"{med:.1f} ms, runs {[round(t) for t in times]})", flush=True)

# Numerical check on device.
a = np.asarray(jax.jit(xla_mv)(x, W, nbr, block_valid))
b = np.asarray(jax.jit(pl_mv)(x, W, nbr, block_valid))
print(f"max abs diff: {np.abs(a - b).max():.3e} "
      f"(ref max {np.abs(a).max():.3e})", flush=True)
