"""Pattern generation: protocol layout, Gray-code round trip."""

import numpy as np
import jax.numpy as jnp

from structured_light_for_3d_model_replication_tpu.config import ProjectorConfig
from structured_light_for_3d_model_replication_tpu.ops import patterns


def test_gray_roundtrip():
    x = jnp.arange(4096, dtype=jnp.int32)
    g = patterns.gray_code(x)
    assert np.array_equal(np.asarray(patterns.gray_to_binary(g, 12)), np.asarray(x))
    # Successive Gray codes differ in exactly one bit.
    diff = np.asarray(g[1:] ^ g[:-1])
    assert np.all(np.bitwise_count(diff.astype(np.uint32)) == 1)


def test_frame_count_1080p():
    proj = ProjectorConfig()  # 1920x1080
    assert proj.col_bits == 11 and proj.row_bits == 11
    assert proj.n_frames == 46  # reference server/sl_system.py:52-54


def test_stack_layout():
    proj = ProjectorConfig(width=32, height=16, brightness=200)
    s = np.asarray(patterns.pattern_stack(
        proj.width, proj.height, proj.col_bits, proj.row_bits, proj.brightness))
    assert s.shape == (2 + 2 * 5 + 2 * 4, 16, 32)
    assert s.dtype == np.uint8
    assert np.all(s[0] == 200) and np.all(s[1] == 0)
    # Pattern + inverse are complementary.
    for b in range(5):
        assert np.all(s[2 + 2 * b].astype(int) + s[3 + 2 * b].astype(int) == 200)
    # Column frames constant along rows; row frames constant along columns.
    assert np.all(s[2] == s[2][0:1, :])
    assert np.all(s[2 + 10] == s[2 + 10][:, 0:1])
    # MSB column plane: left half 0 (gray MSB of 0..15 is 0), right half on.
    assert np.all(s[2][:, :16] == 0) and np.all(s[2][:, 16:] == 200)


def test_decoded_value_is_column_index():
    """Decoding noiseless patterns must recover the exact column/row index."""
    from structured_light_for_3d_model_replication_tpu.ops import decode

    proj = ProjectorConfig(width=64, height=32, brightness=200)
    s = patterns.pattern_stack(proj.width, proj.height, proj.col_bits,
                               proj.row_bits, proj.brightness)
    # Treat projector frames as a perfectly-captured camera stack.
    col_map, row_map, _ = decode.decode_stack(s, proj.col_bits, proj.row_bits)
    cm = np.asarray(col_map)
    rm = np.asarray(row_map)
    assert np.array_equal(cm, np.broadcast_to(np.arange(64), (32, 64)))
    assert np.array_equal(rm, np.broadcast_to(np.arange(32)[:, None], (32, 64)))


def test_downsample_reduces_bits_and_frames():
    """D_SAMPLE_PROJ semantics: coarser stripes -> fewer planes. The
    BASELINE.json 42-frame 1080p protocol is 1920x1080 @ downsample=2."""
    from structured_light_for_3d_model_replication_tpu.ops import decode

    assert ProjectorConfig(downsample=2).n_frames == 42
    assert ProjectorConfig(downsample=1).n_frames == 46

    proj = ProjectorConfig(width=64, height=32, downsample=4)
    assert proj.col_bits == 4 and proj.row_bits == 3
    s = patterns.pattern_stack(proj.width, proj.height, proj.col_bits,
                               proj.row_bits, proj.brightness, proj.downsample)
    assert s.shape[0] == proj.n_frames == 2 + 2 * 4 + 2 * 3
    col_map, _, _ = decode.decode_stack(
        s, proj.col_bits, proj.row_bits, downsample=proj.downsample)
    cm = np.asarray(col_map)
    # Decoded values are stripe centers in projector pixels.
    assert np.array_equal(cm[0], (np.arange(64) // 4) * 4 + 1)
